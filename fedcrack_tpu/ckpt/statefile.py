"""Mid-round durable server state: the crash-consistency layer the orbax
checkpoint cannot provide.

``ckpt.manager`` saves at ROUND BOUNDARIES (one orbax step per aggregation).
Everything between boundaries — the enrolled cohort, the phase, and above
all the ``received`` update blobs — used to die with the process: a server
killed after K of N clients reported restarted the round from zero and
silently threw away K finished local fits. This module persists the full
:class:`fedcrack_tpu.fed.rounds.ServerState` as one msgpack blob through
``ioutils.atomic_write_bytes`` (write-temp + fsync + atomic rename), so the
file on disk is always a complete, parseable snapshot — a kill between
write and rename leaves the previous snapshot plus an ignorable ``*.tmp.*``
sibling (pinned by the chaos suite).

What is NOT persisted: monotonic timestamps (``round_started_at`` /
``enroll_opened_at`` are process-local clocks; the restored state re-arms
them from the first event the new process sees) and the config (the booting
server's config wins — derived fields like the decode template and the
wire-dtype broadcast copy are rebuilt through ``initial_state`` exactly as
on a fresh boot).
"""

from __future__ import annotations

import logging
from typing import Any

import msgpack

from fedcrack_tpu.ioutils import atomic_write_bytes

log = logging.getLogger("fedcrack.ckpt.statefile")

STATE_FORMAT = 1


def server_state_to_bytes(state: Any) -> bytes:
    """Serialize the dynamic fields of a ``ServerState`` (msgpack, no
    pickle — same trust posture as the wire)."""
    from flax import serialization as flax_ser

    from fedcrack_tpu.fed import buffered as _buffered
    from fedcrack_tpu.fed.serialization import tree_to_bytes
    from fedcrack_tpu.health import ledger as _health_ledger

    opt_blob = None
    if state.server_opt_state is not None:
        # Round-trip optimizer moments through flax's state-dict view: optax
        # states are namedtuples of arrays, which msgpack cannot carry
        # directly but whose state-dict (nested plain dicts) it can.
        opt_blob = tree_to_bytes(flax_ser.to_state_dict(state.server_opt_state))
    payload = {
        "format": STATE_FORMAT,
        "phase": state.phase,
        "cohort": sorted(state.cohort),
        "departed": sorted(state.departed),
        "current_round": int(state.current_round),
        "model_version": int(state.model_version),
        "failed_rounds": int(state.failed_rounds),
        "global_blob": state.global_blob,
        "received": {
            # Sorted so the statefile bytes are a function of the state, not
            # of upload arrival order — two snapshots of the same round hash
            # identically.
            name: [blob, int(ns)]
            for name, (blob, ns) in sorted(state.received.items())
        },
        "logs": dict(state.logs),
        "history": [dict(h) for h in state.history],
        "rejected": dict(state.rejected),
        # Wire accounting for the in-flight round (round 12): sorted like
        # `received` so the snapshot bytes stay a pure function of state.
        "wire_bytes": {
            name: int(n) for name, n in sorted(state.wire_bytes.items())
        },
        "codecs": {name: c for name, c in sorted(state.codecs.items())},
        "opt_state": opt_blob,
        # Buffered-async mode (round 14, fed/buffered.py): the in-flight
        # buffer, per-client pulled versions and retained base window — a
        # mid-BUFFER kill resumes with the accepted updates intact and
        # flushes to the bit-identical next global version. All three are
        # canonically sorted (buffer by its own (cname, seq) flush key) so
        # the snapshot bytes stay a pure function of state; the per-entry
        # wire row is fed/buffered's ONE shared codec (the edge statefile
        # uses the same pair, so the row can never drift positionally).
        # Empty in sync mode; absent keys in pre-round-14 snapshots
        # restore as empty.
        "buffer": [
            _buffered.buffer_entry_to_wire(e)
            for e in sorted(
                state.buffer, key=lambda e: (e["cname"], e["seq"])
            )
        ],
        "pulled": {name: int(v) for name, v in sorted(state.pulled.items())},
        # str keys: msgpack's strict_map_key refuses int map keys.
        "base_blobs": {
            str(int(v)): b for v, b in sorted(state.base_blobs.items())
        },
        # Per-client health ledger (round 18, health/ledger.py):
        # canonically-sorted wire rows — the snapshot bytes stay a pure
        # function of state, arrival order never leaks in. Absent in
        # pre-round-18 snapshots (restores as empty).
        "ledger": _health_ledger.ledger_to_wire(state.ledger),
        # Privacy plane (round 23): the enroll-time secagg seeds, the
        # frozen masking roster, and the DP accountant's per-client noise
        # step counts (epsilon is recomputed from steps, never stored —
        # the snapshot cannot disagree with the math). Sorted like every
        # other map; absent in pre-round-23 snapshots (restore as empty).
        "secagg_seeds": {
            name: int(s) for name, s in sorted(state.secagg_seeds.items())
        },
        "secagg_roster": {
            name: int(s) for name, s in sorted(state.secagg_roster.items())
        },
        "privacy_steps": {
            name: int(t) for name, t in sorted(state.privacy_steps.items())
        },
    }
    return msgpack.packb(payload, use_bin_type=True)


def server_state_from_bytes(blob: bytes, config: Any) -> Any:
    """Rebuild a live ``ServerState`` under ``config``. Derived fields
    (float32 decode template, wire-dtype broadcast blob) are reconstructed
    via ``initial_state`` so a wire-dtype change between runs cannot leave
    a stale broadcast copy."""
    from fedcrack_tpu.fed import buffered as _buffered
    from fedcrack_tpu.fed import rounds as R
    from fedcrack_tpu.fed.serialization import tree_from_bytes
    from fedcrack_tpu.health import ledger as _health_ledger

    payload = msgpack.unpackb(blob, raw=False)
    if payload.get("format") != STATE_FORMAT:
        raise ValueError(f"unknown statefile format {payload.get('format')!r}")
    variables = tree_from_bytes(payload["global_blob"])
    state = R.initial_state(config, variables)
    opt_state = None
    if payload.get("opt_state") is not None:
        from flax import serialization as flax_ser

        from fedcrack_tpu.fed.algorithms import make_server_optimizer

        tx = make_server_optimizer(
            config.server_optimizer, config.server_lr, config.server_momentum
        )
        if tx is not None and "params" in variables:
            try:
                opt_state = flax_ser.from_state_dict(
                    tx.init(variables["params"]),
                    tree_from_bytes(payload["opt_state"]),
                )
            except (ValueError, KeyError, TypeError):
                log.warning(
                    "statefile optimizer moments do not match the configured "
                    "server optimizer %r; restarting moments from zero",
                    config.server_optimizer,
                )
    phase = payload["phase"]
    if payload["current_round"] > config.max_rounds:
        phase = R.PHASE_FINISHED
    return state._replace(
        phase=phase,
        cohort=frozenset(payload["cohort"]),
        departed=frozenset(payload["departed"]),
        current_round=payload["current_round"],
        model_version=payload["model_version"],
        failed_rounds=payload["failed_rounds"],
        received={
            name: (bytes(pair[0]), int(pair[1]))
            for name, pair in payload["received"].items()
        },
        logs={k: bytes(v) for k, v in payload["logs"].items()},
        history=tuple(payload["history"]),
        rejected=dict(payload.get("rejected", {})),
        # Absent in pre-round-12 snapshots: default to empty (the in-flight
        # round's wire accounting then restarts, never its updates).
        wire_bytes={
            k: int(v) for k, v in payload.get("wire_bytes", {}).items()
        },
        codecs=dict(payload.get("codecs", {})),
        buffer=tuple(
            _buffered.buffer_entry_from_wire(e)
            for e in payload.get("buffer", [])
        ),
        pulled={k: int(v) for k, v in payload.get("pulled", {}).items()},
        base_blobs=(
            {int(v): bytes(b) for v, b in payload.get("base_blobs", {}).items()}
            # A pre-round-14 snapshot restored under a buffered config must
            # still decode current-version deltas: seed the window with the
            # restored global under its restored version number.
            or (
                {int(payload["model_version"]): state.broadcast_blob}
                if config.mode == "buffered"
                else {}
            )
        ),
        ledger=_health_ledger.ledger_from_wire(payload.get("ledger", [])),
        secagg_seeds={
            k: int(v) for k, v in payload.get("secagg_seeds", {}).items()
        },
        secagg_roster={
            k: int(v) for k, v in payload.get("secagg_roster", {}).items()
        },
        privacy_steps={
            k: int(v) for k, v in payload.get("privacy_steps", {}).items()
        },
        server_opt_state=opt_state,
        # Monotonic clocks do not survive a process: re-arm on first event
        # (rounds._advance_time stamps round_started_at when RUNNING).
        enroll_opened_at=None,
        round_started_at=None,
    )


def save_state_file(path: str, state: Any) -> None:
    """One atomic, fsync'd snapshot; the previous snapshot survives any
    crash up to the rename instant."""
    atomic_write_bytes(path, server_state_to_bytes(state))


def load_state_file(path: str, config: Any) -> Any | None:
    """The latest durable snapshot, or None (missing file, or an unreadable
    one — which the atomic writer makes possible only via external
    corruption; it is logged, never fatal, and the orbax round-boundary
    checkpoint remains the fallback)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        return None
    except OSError:
        log.exception("statefile %s unreadable", path)
        return None
    try:
        return server_state_from_bytes(blob, config)
    except Exception:
        log.exception("statefile %s corrupt; falling back to the checkpoint", path)
        return None
