"""Orbax checkpoint manager for federation + trainer state.

Layout under ``directory/``: one orbax step per ``model_version``, each a
composite of the variables pytree (zarr-sharded arrays) and a JSON metadata
blob (round, version, phase-independent history). ``max_to_keep`` bounds
disk usage; the latest step wins on restore.
"""

from __future__ import annotations

import base64
import dataclasses
import logging
import os
from typing import Any, Mapping

import msgpack
import numpy as np
import orbax.checkpoint as ocp

log = logging.getLogger("fedcrack.ckpt")

# Cap on client-uploaded log bytes carried per checkpoint. Logs ride along
# so a coordinator restart does not lose half-finished uploads, but a large
# upload must not bloat every retained checkpoint (max_to_keep of them).
DEFAULT_MAX_LOG_BYTES = 16 * 1024 * 1024


@dataclasses.dataclass(frozen=True)
class FedCheckpoint:
    """What a coordinator needs to resume a federation."""

    current_round: int
    model_version: int
    variables: Any
    history: tuple[dict, ...] = ()
    # Client-uploaded log chunks (rounds.py LogChunk sink): title -> bytes.
    logs: Mapping[str, bytes] = dataclasses.field(default_factory=dict)
    # FedOpt server-optimizer moments (None for plain FedAvg).
    server_opt_state: Any = None


class FedCheckpointer:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    Steps are keyed by ``model_version`` — strictly monotonic across a
    federation (bumped exactly once per aggregation, fed/rounds.py), so
    "latest step" is always "most recent round".
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        max_to_keep: int = 3,
        max_log_bytes: int = DEFAULT_MAX_LOG_BYTES,
    ):
        self._dir = os.path.abspath(os.fspath(directory))
        self._max_log_bytes = max_log_bytes
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=False
            ),
        )

    def _capped_logs(self, logs: Mapping[str, bytes]) -> dict[str, bytes]:
        """Drop largest-first until the total fits the per-checkpoint cap —
        a multi-MB upload must not multiply across every retained step."""
        out = dict(logs)
        total = sum(len(v) for v in out.values())
        if total <= self._max_log_bytes:
            return out
        for k in sorted(out, key=lambda k: len(out[k]), reverse=True):
            if total <= self._max_log_bytes:
                break
            total -= len(out[k])
            log.warning(
                "dropping log buffer %r (%d bytes) from the checkpoint: "
                "total log bytes exceed the %d-byte per-checkpoint cap "
                "(the upload itself is unaffected)",
                k, len(out[k]), self._max_log_bytes,
            )
            del out[k]
        return out

    def save(self, ckpt: FedCheckpoint) -> None:
        meta = {
            "current_round": ckpt.current_round,
            "model_version": ckpt.model_version,
            "history": list(ckpt.history),
        }
        items = {
            "variables": ocp.args.StandardSave(ckpt.variables),
            "meta": ocp.args.JsonSave(meta),
        }
        logs = self._capped_logs(ckpt.logs)
        if logs:
            # Binary sidecar item, NOT base64 inside the JSON metadata: a
            # JSON round-trip of megabytes of b64 costs 4/3 the bytes and
            # a full parse on every restore.
            packed = msgpack.packb(logs, use_bin_type=True)
            items["logs"] = ocp.args.StandardSave(
                {"packed": np.frombuffer(packed, np.uint8)}
            )
        if ckpt.server_opt_state is not None:
            items["opt_state"] = ocp.args.StandardSave(ckpt.server_opt_state)
        self._mngr.save(ckpt.model_version, args=ocp.args.Composite(**items))
        self._mngr.wait_until_finished()

    def latest_version(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, template: Any | None = None) -> FedCheckpoint | None:
        """Restore the latest checkpoint; ``template`` (a matching variables
        pytree, e.g. a freshly initialized model) pins dtypes/shardings —
        without it arrays come back as host numpy."""
        step = self._mngr.latest_step()
        if step is None:
            return None
        restore_args = (
            ocp.args.StandardRestore(template)
            if template is not None
            else ocp.args.StandardRestore()
        )
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                variables=restore_args, meta=ocp.args.JsonRestore()
            ),
        )
        meta = restored["meta"]
        return FedCheckpoint(
            current_round=int(meta["current_round"]),
            model_version=int(meta["model_version"]),
            variables=restored["variables"],
            history=tuple(meta.get("history", [])),
            logs=self._restore_logs(step, meta),
        )

    def _restore_logs(self, step: int, meta: Mapping[str, Any]) -> dict[str, bytes]:
        if "logs" in meta:
            # checkpoints written before the binary sidecar carried base64
            # inside the JSON metadata
            return {k: base64.b64decode(v) for k, v in meta["logs"].items()}
        try:
            restored = self._mngr.restore(
                step, args=ocp.args.Composite(logs=ocp.args.StandardRestore())
            )
        except (KeyError, FileNotFoundError, ValueError):
            return {}  # step carries no log uploads
        packed = np.asarray(restored["logs"]["packed"], np.uint8).tobytes()
        return msgpack.unpackb(packed, raw=False)

    def restore_opt_state(self, opt_template: Any) -> Any | None:
        """Restore the FedOpt server-optimizer moments of the latest step
        into ``opt_template``'s structure (``tx.init(params)``); None when
        the step predates FedOpt or plain FedAvg was running."""
        step = self._mngr.latest_step()
        if step is None:
            return None
        try:
            restored = self._mngr.restore(
                step,
                args=ocp.args.Composite(
                    opt_state=ocp.args.StandardRestore(opt_template)
                ),
            )
        except (KeyError, FileNotFoundError):
            return None  # step predates FedOpt / plain FedAvg was running
        except ValueError:
            # The item exists but its structure does not match the template —
            # e.g. a checkpoint written by an older optimizer implementation.
            # Debug-level only: the legacy-migration retry is the NORMAL next
            # step, and restore_server_state warns loudly if that fails too —
            # a warning here would fire on every successful migration.
            log.debug(
                "server opt_state in step %s does not match the current "
                "optimizer structure; caller may retry with a legacy template",
                step,
            )
            return None
        return restored["opt_state"]

    def close(self) -> None:
        self._mngr.close()

    def __enter__(self) -> "FedCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---- coordinator state bridge (fed/rounds.py ServerState <-> checkpoint) ----


def save_server_state(ckptr: FedCheckpointer, state: Any) -> None:
    """Persist a ``fed.rounds.ServerState`` after an aggregation."""
    from fedcrack_tpu.fed.serialization import tree_from_bytes

    ckptr.save(
        FedCheckpoint(
            current_round=state.current_round,
            model_version=state.model_version,
            variables=tree_from_bytes(state.global_blob),
            history=state.history,
            logs=state.logs,
            server_opt_state=state.server_opt_state,
        )
    )


def _migrate_legacy_fedadam(ckptr: FedCheckpointer, params: Any) -> Any | None:
    """Checkpoints written when FedAdam was optax.adam stored the moments as
    ``(ScaleByAdamState(count, mu, nu), EmptyState)``; map mu/nu onto the
    hand-rolled ``(m, v)`` state so upgrading the coordinator keeps its
    momentum instead of silently re-zeroing it."""
    import optax

    legacy = ckptr.restore_opt_state(optax.adam(1.0).init(params))
    if legacy is None:
        return None
    try:
        scale_state = legacy[0]
        migrated = (scale_state.mu, scale_state.nu)
    except (TypeError, IndexError, AttributeError):
        return None
    log.info("migrated legacy optax.adam FedAdam moments to the paper update")
    return migrated


def restore_server_state(
    ckptr: FedCheckpointer, config: Any, template: Any | None = None
) -> Any | None:
    """Rebuild a resumable ``ServerState`` from the latest checkpoint.

    The restored coordinator re-opens enrollment (a fresh cohort must
    register — the old one's streams died with the old process) but keeps
    the round counter, model version, averaged weights, and history, so the
    federation continues instead of restarting from round 1 (closing
    SURVEY.md §5.4: "a restarted server forgets rounds").
    Returns ``None`` when the directory holds no checkpoint.
    """
    from fedcrack_tpu.fed import rounds as R

    ckpt = ckptr.restore(template)
    if ckpt is None:
        return None
    if ckpt.current_round > config.max_rounds:
        phase = R.PHASE_FINISHED
    else:
        phase = R.PHASE_ENROLL
    # FedOpt moments resume too — otherwise a restarted FedAvgM/FedAdam
    # coordinator would silently restart its momentum from zero.
    from fedcrack_tpu.fed.algorithms import make_server_optimizer

    opt_state = None
    tx = make_server_optimizer(
        config.server_optimizer, config.server_lr, config.server_momentum
    )
    if tx is not None:
        opt_state = ckptr.restore_opt_state(tx.init(ckpt.variables["params"]))
        if opt_state is None and config.server_optimizer in ("adam", "fedadam"):
            opt_state = _migrate_legacy_fedadam(ckptr, ckpt.variables["params"])
        if opt_state is None:
            log.warning(
                "no FedOpt moments restored for server_optimizer=%r: the "
                "server optimizer restarts from zero moments",
                config.server_optimizer,
            )
    # Route through initial_state so dtype-dependent derived fields (the
    # float32 decode template, the wire-dtype broadcast copy) are rebuilt
    # consistently with a fresh boot.
    fresh = R.initial_state(config, ckpt.variables)
    return fresh._replace(
        phase=phase,
        current_round=ckpt.current_round,
        model_version=ckpt.model_version,
        history=ckpt.history,
        logs=ckpt.logs,
        server_opt_state=opt_state,
        # Buffered mode (round 14): initial_state keys the retained-base
        # window under version 0; the restored global IS the broadcast for
        # the restored version — re-key it, or every post-restart upload
        # would miss the base lookup and resync forever.
        base_blobs=(
            {int(ckpt.model_version): fresh.broadcast_blob}
            if config.mode == "buffered"
            else {}
        ),
    )
