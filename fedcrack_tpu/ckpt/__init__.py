"""Checkpoint / resume (orbax-backed).

The reference writes weight pickles to disk but can never restore mid-run
state — a restarted server forgets all rounds (reference:
fl_server.py:104-105 writes ``./server_weights/weights.pickle`` that nothing
reads; SURVEY.md §5.4). Here both planes checkpoint durably:

- the federation coordinator saves ``(round, model_version, global variables,
  history)`` after every aggregation and can resume a federation where it
  left off (a fresh enrollment window opens, then rounds continue from the
  restored round counter);
- the centralized trainer keeps best-val and latest states (the reference's
  ``ModelCheckpoint(save_best_only=True)``, test/Segmentation.py:177-179);
- the mid-round statefile (``statefile.py``, ``FedConfig.state_path``)
  covers what orbax's round-boundary steps cannot: cohort/phase and the
  already-received update blobs, atomically snapshotted on every change so
  a server killed MID-round resumes the same round (round 8).

Orbax is the TPU-native choice: zarr-sharded array storage, async-safe,
restores straight onto whatever device/sharding layout the restore-side
template carries.
"""

from fedcrack_tpu.ckpt.manager import (
    FedCheckpoint,
    FedCheckpointer,
    restore_server_state,
    save_server_state,
)
from fedcrack_tpu.ckpt.statefile import (
    load_state_file,
    save_state_file,
    server_state_from_bytes,
    server_state_to_bytes,
)

__all__ = [
    "FedCheckpoint",
    "FedCheckpointer",
    "load_state_file",
    "restore_server_state",
    "save_server_state",
    "save_state_file",
    "server_state_from_bytes",
    "server_state_to_bytes",
]
