"""Durable small-file writes shared by the control plane.

Every non-orbax persistence site (the best-model pair in
``transport/service.py``, the mid-round server statefile in
``ckpt/statefile.py``) funnels through :func:`atomic_write_bytes`:
write-temp + flush + fsync + atomic rename, so a crash at ANY instruction
boundary leaves either the old complete file or the new complete file —
never a torn one. A crash between write and rename strands a ``*.tmp.*``
sibling, which readers must ignore (pinned by the chaos suite's
kill-between-write-and-rename test). Orbax checkpoints are not routed here:
``CheckpointManager`` already commits steps via its own temp-dir + rename
protocol.
"""

from __future__ import annotations

import os


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` so that the file is never observable in a
    torn state: temp file in the same directory (rename must not cross a
    filesystem), fsync before rename (the rename must never land before the
    bytes), then ``os.replace``. Directory fsync is best-effort — on hosts
    where it works, the *rename itself* also survives a power cut."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # platform without directory fsync; rename atomicity still holds
