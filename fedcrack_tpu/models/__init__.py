"""Model registry.

The reference advertises the (vestigial) model type string "mobilenet_v2"
(reference: fl_server.py:75) while server and client actually share one
architecture — the residual U-Net (reference: client_fit_model.py:92-150,
SURVEY.md §2.2(3)). The registry accepts the legacy alias so a reference
client's handshake still resolves to the real model.
"""

from __future__ import annotations

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.models.resunet import ResUNet, depth_to_space, space_to_depth

_ALIASES = {
    "resunet": "resunet",
    "unet": "resunet",
    # Legacy alias: the reference's advertised-but-vestigial model type string.
    "mobilenet_v2": "resunet",
}


def get_model(name: str = "resunet", config: ModelConfig | None = None) -> ResUNet:
    """Build a model by registry name (case-insensitive, legacy aliases ok)."""
    key = _ALIASES.get(name.lower())
    if key is None:
        raise KeyError(f"unknown model type {name!r}; known: {sorted(_ALIASES)}")
    return ResUNet(config=config or ModelConfig())


__all__ = ["ResUNet", "depth_to_space", "get_model", "space_to_depth"]
