"""Residual U-Net for crack segmentation, as a Flax module.

Capability parity with the reference's Keras builder
(reference: client_fit_model.py:92-150, identical in test/Segmentation.py:102-159):

- stem ``Conv(32, 3x3, stride 2, SAME)`` + BN + ReLU
- encoder blocks, filters (64, 128, 256): two ``ReLU -> SeparableConv -> BN``
  then ``MaxPool(3x3, stride 2, SAME)``, with a strided 1x1-conv residual add
- decoder blocks, filters (256, 128, 64, 32): two ``ReLU -> ConvT(3x3) -> BN``
  then nearest x2 upsampling, with an upsampled 1x1-conv residual add
- head ``Conv(1, 1x1)`` — this module returns **logits**; the reference bakes
  sigmoid into the head (client_fit_model.py:145) and we apply it in the loss
  (numerically stable) and in ``predict``.

TPU-first choices: NHWC layout, optional bfloat16 compute with float32 params,
static shapes throughout (everything jit/pjit-traceable), BatchNorm hyperparams
matched to Keras defaults (momentum 0.99, eps 1e-3) so an h5 weight import is
tensor-for-tensor (SURVEY.md §7 "hard parts").

Spatial bookkeeping: stem /2 and three pools /2 take 128x128 -> 8x8 at the
bottleneck; four x2 upsampling stages return to 128x128, matching the
full-resolution masks (SURVEY.md §2.3).

Layout transforms (``ModelConfig.stem_layout`` / ``res_layout``): exact
re-expressions of the same math targeting the HBM-bound narrow-channel convs
(BASELINE.md "The MFU ceiling"). Parameter shapes NEVER change — the
transformed kernels are derived in-forward from the reference weights
(``fold_stem_kernel_s2d`` and friends; the derivation is linear, so
gradients flow back to the reference parameterization and training is the
same program family either way), which keeps h5 imports/exports, FedAvg,
the wire format and checkpoints layout-blind.

Why "s2d" is a width fold and not the fully collapsed stride-1 conv: XLA
contracts a conv's reduction dimensions in (kh, kw, c) order, and a layout
transform is bit-exact iff it preserves the relative order of the NONZERO
terms (inserting exact zero taps anywhere is a no-op; reordering real taps
reassociates the float sum). Folding W into channels keeps that order
(per kh: kw-major, zeros appended); folding H too would need tap (0,2) to
land between (0,1) and (1,0), but (0,1)/(1,0) share a 2x2 block while (0,2)
does not — impossible for any channel permutation. The fully folded variant
is still offered as ``stem_layout="s2d_full"`` for the A/B bench, with its
~1-ulp reassociation documented rather than hidden (measured in
tests/test_model.py; BASELINE.md "layout levers").
"""

from __future__ import annotations

import os
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.ops.pooling import max_pool_auto

# A/B escape hatch: FEDCRACK_POOL=default routes the encoder pool through
# flax's nn.max_pool (XLA SelectAndScatter backward) instead of the
# grid-size-aware custom VJP — for benchmarking the two lowerings against
# each other on real hardware. Values are identical either way.
_USE_CUSTOM_POOL = os.environ.get("FEDCRACK_POOL", "custom") != "default"

# Keras BatchNormalization defaults (the reference relies on them).
_BN_MOMENTUM = 0.99
_BN_EPSILON = 1e-3

_glorot = nn.initializers.glorot_uniform()


def space_to_depth(x: jax.Array) -> jax.Array:
    """``[N,H,W,C] -> [N,H/2,W/2,4C]``: 2x2 pixel blocks to channels,
    block-position-major (packed channel = ``(di*2+dj)*C + c`` for the pixel
    at block offset ``(di, dj)``). Pure data movement — the canonical packed
    input layout for ``stem_layout="s2d"``/``"s2d_full"``; the host-side
    twin for staging is ``data.pipeline.space_to_depth_images``."""
    n, h, w, c = x.shape
    if h % 2 or w % 2:
        raise ValueError(f"space_to_depth needs even H,W; got {(h, w)}")
    x = x.reshape(n, h // 2, 2, w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // 2, w // 2, 4 * c)


def depth_to_space(x: jax.Array) -> jax.Array:
    """Inverse of :func:`space_to_depth`."""
    n, h2, w2, c4 = x.shape
    if c4 % 4:
        raise ValueError(f"depth_to_space needs channels % 4 == 0; got {c4}")
    c = c4 // 4
    x = x.reshape(n, h2, w2, 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, 2 * h2, 2 * w2, c)


def fold_stem_kernel_s2d(kernel: jax.Array) -> jax.Array:
    """Reference stem kernel ``[3,3,C,F]`` -> width-folded ``[3,2,2C,F]``.

    Tap ``(kh, kw, c)`` lands at ``[kh, kw//2, (kw%2)*C + c]``; the unused
    slot ``(kh, bw=1, dj=1)`` is exact zero. Preserves XLA's (kh, kw, c)
    contraction order, so the folded conv (strides (2,1), padding
    ((0,1),(0,1)) on the width-packed input) is BIT-EXACT vs the reference
    stem. Linear in ``kernel`` — differentiable, gradients flow back to the
    reference parameterization."""
    if kernel.shape[:2] != (3, 3):
        raise ValueError(f"expected a 3x3 stem kernel, got {kernel.shape}")
    k0 = jnp.concatenate([kernel[:, 0], kernel[:, 1]], axis=1)  # [3, 2C, F]
    k1 = jnp.concatenate([kernel[:, 2], jnp.zeros_like(kernel[:, 2])], axis=1)
    return jnp.stack([k0, k1], axis=1)  # [3, 2, 2C, F]


def unfold_stem_kernel_s2d(folded: jax.Array) -> jax.Array:
    """Exact inverse of :func:`fold_stem_kernel_s2d` (weight export for a
    kernel held in the folded layout)."""
    if folded.shape[:2] != (3, 2):
        raise ValueError(f"expected a [3,2,2C,F] folded kernel, got {folded.shape}")
    c = folded.shape[2] // 2
    k0, k1 = folded[:, 0], folded[:, 1]
    return jnp.stack([k0[:, :c], k0[:, c:], k1[:, :c]], axis=1)


def fold_stem_kernel_s2d_full(kernel: jax.Array) -> jax.Array:
    """Reference stem kernel ``[3,3,C,F]`` -> fully folded ``[2,2,4C,F]``
    for the stride-1 conv on the space-to-depth input.

    Tap ``(kh, kw, c)`` lands at ``[kh//2, kw//2, ((kh%2)*2 + kw%2)*C + c]``;
    the 2x2 block structure forces taps of different kh rows into one packed
    block, which REORDERS the contraction — mathematically identical (same
    multiplies plus exact zeros) but reassociated, so agreement with the
    reference stem is ~1 ulp rather than bitwise (module docstring)."""
    if kernel.shape[:2] != (3, 3):
        raise ValueError(f"expected a 3x3 stem kernel, got {kernel.shape}")
    zeros = jnp.zeros_like(kernel[0, 0])  # [C, F]

    def tap(kh: int, kw: int) -> jax.Array:
        return kernel[kh, kw] if kh < 3 and kw < 3 else zeros

    rows = []
    for bh in range(2):
        row = [
            jnp.concatenate(
                [tap(2 * bh + di, 2 * bw + dj) for di in (0, 1) for dj in (0, 1)],
                axis=0,
            )
            for bw in range(2)
        ]
        rows.append(jnp.stack(row, axis=0))
    return jnp.stack(rows, axis=0)  # [2, 2, 4C, F]


def unfold_stem_kernel_s2d_full(folded: jax.Array) -> jax.Array:
    """Exact inverse of :func:`fold_stem_kernel_s2d_full`."""
    if folded.shape[:2] != (2, 2):
        raise ValueError(f"expected a [2,2,4C,F] folded kernel, got {folded.shape}")
    c = folded.shape[2] // 4
    taps = []
    for kh in range(3):
        row = []
        for kw in range(3):
            lo = ((kh % 2) * 2 + kw % 2) * c
            row.append(folded[kh // 2, kw // 2, lo : lo + c])
        taps.append(jnp.stack(row, axis=0))
    return jnp.stack(taps, axis=0)


def pack_res_kernel(kernel: jax.Array) -> jax.Array:
    """Reference 1x1 residual kernel ``[1,1,C,F]`` -> ``[1,1,4C,F]`` for the
    stride-1 conv on the space-to-depth-packed block input: the real taps
    (block offset (0,0) — exactly the pixels a stride-2 1x1 conv reads) stay
    FIRST, zero-extension follows, so the contraction order of the nonzero
    terms is preserved and the packed projection is bit-exact."""
    if kernel.shape[:2] != (1, 1):
        raise ValueError(f"expected a 1x1 residual kernel, got {kernel.shape}")
    zeros = jnp.zeros(
        (1, 1, 3 * kernel.shape[2], kernel.shape[3]), dtype=kernel.dtype
    )
    return jnp.concatenate([kernel, zeros], axis=2)


def unpack_res_kernel(packed: jax.Array) -> jax.Array:
    """Exact inverse of :func:`pack_res_kernel`."""
    if packed.shape[2] % 4:
        raise ValueError(f"expected a [1,1,4C,F] packed kernel, got {packed.shape}")
    return packed[:, :, : packed.shape[2] // 4]


def upsample2x(x: jax.Array) -> jax.Array:
    """Nearest-neighbor x2 upsampling on NHWC, Keras ``UpSampling2D(2)`` semantics.

    One broadcast materializes both axes at once: two chained ``jnp.repeat``
    calls lower to two full-tensor HBM round-trips, which profiling showed
    were ~30% of forward device time at the flagship shape.
    """
    n, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (n, h, 2, w, 2, c))
    return x.reshape(n, 2 * h, 2 * w, c)


class SeparableConv(nn.Module):
    """Depthwise 3x3 + pointwise 1x1, Keras ``SeparableConv2D`` semantics.

    Keras puts the bias only on the pointwise projection; the depthwise stage
    is bias-free with depth_multiplier=1.
    """

    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_features = x.shape[-1]
        x = nn.Conv(
            features=in_features,
            kernel_size=(3, 3),
            feature_group_count=in_features,
            padding="SAME",
            use_bias=False,
            kernel_init=_glorot,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="depthwise",
        )(x)
        x = nn.Conv(
            features=self.features,
            kernel_size=(1, 1),
            padding="SAME",
            use_bias=True,
            kernel_init=_glorot,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="pointwise",
        )(x)
        return x


class S2DStemConv(nn.Module):
    """The stem conv executed in a space-to-depth layout.

    Declares the SAME parameters as the reference ``nn.Conv`` stem — kernel
    ``[3,3,C,F]`` (glorot) and bias ``[F]`` (zeros) under the same module
    name — so the variables pytree, its initialization values (same RNG
    fold), h5 import/export and FedAvg are all identical to the reference
    layout; only the executed program changes. Accepts the reference input
    ``[N,H,W,C]`` (packed on device: the width fold is a FREE row-major
    reshape) or the pre-packed ``[N,H/2,W/2,4C]`` of :func:`space_to_depth`
    (staged that way by ``parallel.driver``-style loops).

    ``layout="s2d"``: width-folded ``[3,2,2C,F]`` kernel, strides (2,1) —
    bit-exact (contraction-order-preserving, see module docstring).
    ``layout="s2d_full"``: fully folded ``[2,2,4C,F]`` kernel, stride 1 —
    mathematically identical, reassociated (~1 ulp).
    """

    features: int
    in_channels: int
    layout: str  # "s2d" | "s2d_full"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.in_channels
        kernel = self.param("kernel", _glorot, (3, 3, c, self.features), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros_init(), (self.features,), self.param_dtype)
        kernel = kernel.astype(self.dtype)
        bias = bias.astype(self.dtype)

        packed = x.shape[-1] == 4 * c
        if not packed and x.shape[-1] != c:
            raise ValueError(
                f"stem input has {x.shape[-1]} channels; expected {c} "
                f"(reference layout) or {4 * c} (space_to_depth-packed)"
            )
        n = x.shape[0]
        if self.layout == "s2d":
            if packed:
                h2, w2 = x.shape[1], x.shape[2]
                # Unpack H only: [N,H/2,W/2,4C] -> [N,H,W/2,2C] (data movement).
                x = x.reshape(n, h2, w2, 2, 2, c)
                x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, 2 * h2, w2, 2 * c)
            else:
                h, w = x.shape[1], x.shape[2]
                # Width fold is a pure row-major reshape — no copy.
                x = x.reshape(n, h, w // 2, 2 * c)
            folded = fold_stem_kernel_s2d(kernel)
            strides = (2, 1)
        else:  # "s2d_full"
            if not packed:
                x = space_to_depth(x)
            folded = fold_stem_kernel_s2d_full(kernel)
            strides = (1, 1)
        y = jax.lax.conv_general_dilated(
            x,
            folded,
            window_strides=strides,
            padding=[(0, 1), (0, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y + bias


class PackedResConv(nn.Module):
    """An encoder residual projection — reference ``Conv(F, 1x1, stride 2)``
    — executed as a stride-1 1x1 conv over the space-to-depth-packed block
    input with a zero-extended ``[1,1,4C,F]`` kernel (bit-exact: the packed
    block offset (0,0) channels are exactly the pixels the strided conv
    reads, and they stay first in the contraction). Parameters are identical
    to the reference ``nn.Conv`` (kernel ``[1,1,C,F]`` glorot + bias)."""

    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        kernel = self.param("kernel", _glorot, (1, 1, c, self.features), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros_init(), (self.features,), self.param_dtype)
        y = jax.lax.conv_general_dilated(
            space_to_depth(x),
            pack_res_kernel(kernel.astype(self.dtype)),
            window_strides=(1, 1),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y + bias.astype(self.dtype)


class ResUNet(nn.Module):
    """The crack-segmentation residual U-Net. Returns per-pixel logits.

    ``bn_axis_name``: when training under ``shard_map`` with the batch split
    across a mesh axis, set this to that axis so BatchNorm moments
    pmean-synchronize across the data-parallel shards — keeping the sharded
    step numerically identical to the single-device one. Inference is
    unaffected (running stats)."""

    config: ModelConfig = ModelConfig()
    bn_axis_name: str | None = None
    # Keras-parity default; 0.0 turns a train-mode forward into an exact
    # per-batch moment estimator (used by ``train.recalibrate_batch_stats``).
    bn_momentum: float = _BN_MOMENTUM

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        cfg = self.config
        dtype = jnp.dtype(cfg.compute_dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        conv_kw = dict(
            padding="SAME", kernel_init=_glorot, dtype=dtype, param_dtype=pdtype
        )

        def bn(name: str):
            return nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_momentum,
                epsilon=_BN_EPSILON,
                dtype=dtype,
                param_dtype=pdtype,
                axis_name=self.bn_axis_name,
                name=name,
            )

        x = x.astype(dtype)

        # Entry block (stem): /2. Under a space-to-depth layout the stem
        # consumes either the reference [N,H,W,C] input or the packed
        # [N,H/2,W/2,4C] of `space_to_depth` and runs a folded kernel derived
        # in-forward from the SAME parameters (S2DStemConv); everything from
        # stem_bn on is layout-independent.
        if cfg.stem_layout == "reference":
            x = nn.Conv(cfg.stem_features, (3, 3), strides=(2, 2), name="stem_conv", **conv_kw)(x)
        else:
            x = S2DStemConv(
                cfg.stem_features,
                in_channels=cfg.in_channels,
                layout=cfg.stem_layout,
                dtype=dtype,
                param_dtype=pdtype,
                name="stem_conv",
            )(x)
        x = bn("stem_bn")(x)
        x = nn.relu(x)
        previous = x  # residual carried across blocks

        # Encoder: each block halves H,W.
        for i, features in enumerate(cfg.encoder_features):
            x = nn.relu(x)
            x = SeparableConv(features, dtype=dtype, param_dtype=pdtype, name=f"enc{i}_sep1")(x)
            x = bn(f"enc{i}_bn1")(x)
            x = nn.relu(x)
            x = SeparableConv(features, dtype=dtype, param_dtype=pdtype, name=f"enc{i}_sep2")(x)
            x = bn(f"enc{i}_bn2")(x)
            # Same values as nn.max_pool(3x3, s2, SAME); on grids where it
            # measures faster the backward avoids XLA's SelectAndScatter
            # (ops/pooling.py — measured crossover at 64x64 on v5e).
            if _USE_CUSTOM_POOL:
                x = max_pool_auto(x)
            else:
                x = nn.max_pool(x, window_shape=(3, 3), strides=(2, 2), padding="SAME")
            if cfg.res_layout == "packed":
                # Strided 1x1 conv re-expressed channel-packed (bit-exact).
                residual = PackedResConv(
                    features, dtype=dtype, param_dtype=pdtype, name=f"enc{i}_res"
                )(previous)
            else:
                residual = nn.Conv(
                    features, (1, 1), strides=(2, 2), name=f"enc{i}_res", **conv_kw
                )(previous)
            x = x + residual
            previous = x

        # Decoder: each block doubles H,W.
        for i, features in enumerate(cfg.decoder_features):
            x = nn.relu(x)
            x = nn.ConvTranspose(
                features, (3, 3), padding="SAME", kernel_init=_glorot,
                dtype=dtype, param_dtype=pdtype, name=f"dec{i}_convT1",
            )(x)
            x = bn(f"dec{i}_bn1")(x)
            x = nn.relu(x)
            x = nn.ConvTranspose(
                features, (3, 3), padding="SAME", kernel_init=_glorot,
                dtype=dtype, param_dtype=pdtype, name=f"dec{i}_convT2",
            )(x)
            x = bn(f"dec{i}_bn2")(x)
            # Keras order is upsample-then-1x1-conv on the residual branch and
            # a separate upsample on the main path; a 1x1 conv commutes with
            # nearest-neighbor upsampling, so conv + add run at the low
            # resolution and ONE upsample replaces two — bit-identical output
            # (pinned by the h5-import forward-parity test), 4x cheaper
            # residual conv, half the broadcast HBM traffic.
            residual = nn.Conv(features, (1, 1), name=f"dec{i}_res", **conv_kw)(
                previous
            )
            x = x + residual
            if i + 1 < len(cfg.decoder_features):
                x = upsample2x(x)
                previous = x
            # else: the LAST block's upsample is deferred past the head below
            # (same commute); `previous` is dead after the loop.

        # Per-pixel classification head; logits in float32 for a stable loss.
        # The head's 1x1 conv also commutes with the final nearest-neighbor
        # upsample (replicated pixels produce replicated dot products), so it
        # runs at half resolution and the last upsample broadcasts ONE f32
        # logit channel instead of `decoder_features[-1]` bf16 feature
        # channels — at 256 px that upsample+head pair was ~12% of profiled
        # device step time, nearly all HBM-bound (bench_runs/
        # r05_profile_256.json: broadcast_in_dim 3.7% + its backward
        # reduce_sum 2.5% + head fwd/bwd fusions 5.4%).
        logits = nn.Conv(
            cfg.num_classes, (1, 1), padding="SAME", kernel_init=_glorot,
            dtype=jnp.float32, param_dtype=pdtype, name="head",
        )(x.astype(jnp.float32))
        return upsample2x(logits)


def init_variables(rng: jax.Array, config: ModelConfig | None = None) -> dict:
    """Initialize {'params', 'batch_stats'} for the model (host-side helper)."""
    config = config or ModelConfig()
    model = ResUNet(config=config)
    dummy = jnp.zeros((1, *config.input_shape), jnp.float32)
    return model.init(rng, dummy, train=False)


def predict(variables: dict, images: jax.Array, config: ModelConfig | None = None) -> jax.Array:
    """Sigmoid probabilities for a batch of images (inference mode)."""
    model = ResUNet(config=config or ModelConfig())
    logits = model.apply(variables, images, train=False)
    return jax.nn.sigmoid(logits)
