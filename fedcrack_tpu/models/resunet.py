"""Residual U-Net for crack segmentation, as a Flax module.

Capability parity with the reference's Keras builder
(reference: client_fit_model.py:92-150, identical in test/Segmentation.py:102-159):

- stem ``Conv(32, 3x3, stride 2, SAME)`` + BN + ReLU
- encoder blocks, filters (64, 128, 256): two ``ReLU -> SeparableConv -> BN``
  then ``MaxPool(3x3, stride 2, SAME)``, with a strided 1x1-conv residual add
- decoder blocks, filters (256, 128, 64, 32): two ``ReLU -> ConvT(3x3) -> BN``
  then nearest x2 upsampling, with an upsampled 1x1-conv residual add
- head ``Conv(1, 1x1)`` — this module returns **logits**; the reference bakes
  sigmoid into the head (client_fit_model.py:145) and we apply it in the loss
  (numerically stable) and in ``predict``.

TPU-first choices: NHWC layout, optional bfloat16 compute with float32 params,
static shapes throughout (everything jit/pjit-traceable), BatchNorm hyperparams
matched to Keras defaults (momentum 0.99, eps 1e-3) so an h5 weight import is
tensor-for-tensor (SURVEY.md §7 "hard parts").

Spatial bookkeeping: stem /2 and three pools /2 take 128x128 -> 8x8 at the
bottleneck; four x2 upsampling stages return to 128x128, matching the
full-resolution masks (SURVEY.md §2.3).
"""

from __future__ import annotations

import os
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.ops.pooling import max_pool_auto

# A/B escape hatch: FEDCRACK_POOL=default routes the encoder pool through
# flax's nn.max_pool (XLA SelectAndScatter backward) instead of the
# grid-size-aware custom VJP — for benchmarking the two lowerings against
# each other on real hardware. Values are identical either way.
_USE_CUSTOM_POOL = os.environ.get("FEDCRACK_POOL", "custom") != "default"

# Keras BatchNormalization defaults (the reference relies on them).
_BN_MOMENTUM = 0.99
_BN_EPSILON = 1e-3

_glorot = nn.initializers.glorot_uniform()


def upsample2x(x: jax.Array) -> jax.Array:
    """Nearest-neighbor x2 upsampling on NHWC, Keras ``UpSampling2D(2)`` semantics.

    One broadcast materializes both axes at once: two chained ``jnp.repeat``
    calls lower to two full-tensor HBM round-trips, which profiling showed
    were ~30% of forward device time at the flagship shape.
    """
    n, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (n, h, 2, w, 2, c))
    return x.reshape(n, 2 * h, 2 * w, c)


class SeparableConv(nn.Module):
    """Depthwise 3x3 + pointwise 1x1, Keras ``SeparableConv2D`` semantics.

    Keras puts the bias only on the pointwise projection; the depthwise stage
    is bias-free with depth_multiplier=1.
    """

    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_features = x.shape[-1]
        x = nn.Conv(
            features=in_features,
            kernel_size=(3, 3),
            feature_group_count=in_features,
            padding="SAME",
            use_bias=False,
            kernel_init=_glorot,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="depthwise",
        )(x)
        x = nn.Conv(
            features=self.features,
            kernel_size=(1, 1),
            padding="SAME",
            use_bias=True,
            kernel_init=_glorot,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="pointwise",
        )(x)
        return x


class ResUNet(nn.Module):
    """The crack-segmentation residual U-Net. Returns per-pixel logits.

    ``bn_axis_name``: when training under ``shard_map`` with the batch split
    across a mesh axis, set this to that axis so BatchNorm moments
    pmean-synchronize across the data-parallel shards — keeping the sharded
    step numerically identical to the single-device one. Inference is
    unaffected (running stats)."""

    config: ModelConfig = ModelConfig()
    bn_axis_name: str | None = None
    # Keras-parity default; 0.0 turns a train-mode forward into an exact
    # per-batch moment estimator (used by ``train.recalibrate_batch_stats``).
    bn_momentum: float = _BN_MOMENTUM

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        cfg = self.config
        dtype = jnp.dtype(cfg.compute_dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        conv_kw = dict(
            padding="SAME", kernel_init=_glorot, dtype=dtype, param_dtype=pdtype
        )

        def bn(name: str):
            return nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_momentum,
                epsilon=_BN_EPSILON,
                dtype=dtype,
                param_dtype=pdtype,
                axis_name=self.bn_axis_name,
                name=name,
            )

        x = x.astype(dtype)

        # Entry block (stem): /2.
        x = nn.Conv(cfg.stem_features, (3, 3), strides=(2, 2), name="stem_conv", **conv_kw)(x)
        x = bn("stem_bn")(x)
        x = nn.relu(x)
        previous = x  # residual carried across blocks

        # Encoder: each block halves H,W.
        for i, features in enumerate(cfg.encoder_features):
            x = nn.relu(x)
            x = SeparableConv(features, dtype=dtype, param_dtype=pdtype, name=f"enc{i}_sep1")(x)
            x = bn(f"enc{i}_bn1")(x)
            x = nn.relu(x)
            x = SeparableConv(features, dtype=dtype, param_dtype=pdtype, name=f"enc{i}_sep2")(x)
            x = bn(f"enc{i}_bn2")(x)
            # Same values as nn.max_pool(3x3, s2, SAME); on grids where it
            # measures faster the backward avoids XLA's SelectAndScatter
            # (ops/pooling.py — measured crossover at 64x64 on v5e).
            if _USE_CUSTOM_POOL:
                x = max_pool_auto(x)
            else:
                x = nn.max_pool(x, window_shape=(3, 3), strides=(2, 2), padding="SAME")
            residual = nn.Conv(
                features, (1, 1), strides=(2, 2), name=f"enc{i}_res", **conv_kw
            )(previous)
            x = x + residual
            previous = x

        # Decoder: each block doubles H,W.
        for i, features in enumerate(cfg.decoder_features):
            x = nn.relu(x)
            x = nn.ConvTranspose(
                features, (3, 3), padding="SAME", kernel_init=_glorot,
                dtype=dtype, param_dtype=pdtype, name=f"dec{i}_convT1",
            )(x)
            x = bn(f"dec{i}_bn1")(x)
            x = nn.relu(x)
            x = nn.ConvTranspose(
                features, (3, 3), padding="SAME", kernel_init=_glorot,
                dtype=dtype, param_dtype=pdtype, name=f"dec{i}_convT2",
            )(x)
            x = bn(f"dec{i}_bn2")(x)
            # Keras order is upsample-then-1x1-conv on the residual branch and
            # a separate upsample on the main path; a 1x1 conv commutes with
            # nearest-neighbor upsampling, so conv + add run at the low
            # resolution and ONE upsample replaces two — bit-identical output
            # (pinned by the h5-import forward-parity test), 4x cheaper
            # residual conv, half the broadcast HBM traffic.
            residual = nn.Conv(features, (1, 1), name=f"dec{i}_res", **conv_kw)(
                previous
            )
            x = x + residual
            if i + 1 < len(cfg.decoder_features):
                x = upsample2x(x)
                previous = x
            # else: the LAST block's upsample is deferred past the head below
            # (same commute); `previous` is dead after the loop.

        # Per-pixel classification head; logits in float32 for a stable loss.
        # The head's 1x1 conv also commutes with the final nearest-neighbor
        # upsample (replicated pixels produce replicated dot products), so it
        # runs at half resolution and the last upsample broadcasts ONE f32
        # logit channel instead of `decoder_features[-1]` bf16 feature
        # channels — at 256 px that upsample+head pair was ~12% of profiled
        # device step time, nearly all HBM-bound (bench_runs/
        # r05_profile_256.json: broadcast_in_dim 3.7% + its backward
        # reduce_sum 2.5% + head fwd/bwd fusions 5.4%).
        logits = nn.Conv(
            cfg.num_classes, (1, 1), padding="SAME", kernel_init=_glorot,
            dtype=jnp.float32, param_dtype=pdtype, name="head",
        )(x.astype(jnp.float32))
        return upsample2x(logits)


def init_variables(rng: jax.Array, config: ModelConfig | None = None) -> dict:
    """Initialize {'params', 'batch_stats'} for the model (host-side helper)."""
    config = config or ModelConfig()
    model = ResUNet(config=config)
    dummy = jnp.zeros((1, *config.input_shape), jnp.float32)
    return model.init(rng, dummy, train=False)


def predict(variables: dict, images: jax.Array, config: ModelConfig | None = None) -> jax.Array:
    """Sigmoid probabilities for a batch of images (inference mode)."""
    model = ResUNet(config=config or ModelConfig())
    logits = model.apply(variables, images, train=False)
    return jax.nn.sigmoid(logits)
