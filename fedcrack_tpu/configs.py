"""Single dataclass-based configuration system.

The reference has no config system: constants are module globals
(``fl_server.py:17-18``), magic ctor args (``fl_client.py:102``,
``fl_server.py:230-231``), hardcoded dataset paths
(``client_fit_model.py:58-59``) and a hardcoded port (``fl_server.py:218``).
Here every knob lives in one serializable config that also travels in-band in
the protocol handshake config map (SURVEY.md §2.4), closing SURVEY.md §5.6.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Residual U-Net hyperparameters (reference: client_fit_model.py:92-150).

    The reference hardcodes 128x128x3 inputs, encoder filters [64, 128, 256],
    decoder filters [256, 128, 64, 32] and a single-sigmoid head.
    """

    img_size: int = 128
    in_channels: int = 3
    num_classes: int = 1
    stem_features: int = 32
    encoder_features: tuple[int, ...] = (64, 128, 256)
    decoder_features: tuple[int, ...] = (256, 128, 64, 32)
    # "bfloat16" compute with float32 params is the TPU-native default; the
    # reference trains in float32 throughout.
    compute_dtype: str = "float32"
    param_dtype: str = "float32"
    # Layout transforms (models/resunet.py): exact re-expressions of the same
    # math targeting the HBM-bound narrow-channel convs (BASELINE.md "The MFU
    # ceiling" / "layout levers"). Parameter shapes NEVER change — transformed
    # kernels are derived in-forward from the reference weights, so h5
    # imports, FedAvg, serialization and checkpoints are layout-blind.
    #
    # stem_layout:
    #   "reference" — the reference's Conv(3x3, stride 2) on [N,H,W,3].
    #   "s2d"       — space-to-depth input [N,H/2,W/2,4C]; the stem runs as a
    #                 width-folded (3,2) conv on 2C channels, stride (2,1) —
    #                 BIT-EXACT vs the reference layout (the fold preserves
    #                 XLA's (kh,kw,c) contraction order; test-pinned).
    #   "s2d_full"  — the fully folded stride-1 (2,2) conv on 4C channels.
    #                 Mathematically identical (same multiplies + exact zero
    #                 terms) but XLA reassociates the longer contraction, so
    #                 agreement is ~1 ulp, not bitwise (documented in
    #                 BASELINE.md; the A/B bench measures both).
    # res_layout:
    #   "reference" — encoder residual projections as strided 1x1 convs.
    #   "packed"    — encoder residual 1x1 stride-2 convs re-expressed as
    #                 stride-1 1x1 convs over the space-to-depth-packed block
    #                 input (zero-extended kernel; bit-exact, test-pinned).
    stem_layout: str = "reference"
    res_layout: str = "reference"

    def __post_init__(self) -> None:
        # stem /2 + three pools /2 then four x2 upsamples: output comes back to
        # img_size only when img_size is a multiple of 16; otherwise the head
        # would silently emit a larger map than the mask.
        if self.img_size % 16 != 0 or self.img_size <= 0:
            raise ValueError(
                f"img_size must be a positive multiple of 16, got {self.img_size}"
            )
        if self.stem_layout not in ("reference", "s2d", "s2d_full"):
            raise ValueError(
                "stem_layout must be one of 'reference', 's2d', 's2d_full'; "
                f"got {self.stem_layout!r}"
            )
        if self.res_layout not in ("reference", "packed"):
            raise ValueError(
                "res_layout must be 'reference' or 'packed'; "
                f"got {self.res_layout!r}"
            )

    @property
    def input_shape(self) -> tuple[int, int, int]:
        return (self.img_size, self.img_size, self.in_channels)


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset layout + split semantics (reference: client_fit_model.py:54-90)."""

    image_dir: str = ""
    mask_dir: str = ""
    img_size: int = 128
    batch_size: int = 16          # reference: client_fit_model.py:55
    split_seed: int = 1337        # reference: client_fit_model.py:77-78
    train_samples: int = 6213     # reference: client_fit_model.py:76
    # "iid" or "skew" (per-client crack-density skew, SURVEY.md §7 step 2)
    partition: str = "iid"
    skew_alpha: float = 0.3       # Dirichlet concentration for non-IID shards
    prefetch: int = 2
    num_workers: int = 4


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-plane configuration (round 10): the TPU-native batched
    inference endpoint that turns the federation's global model into a
    served workload (ROADMAP north star: "serves heavy traffic").

    The reference's inference path is a one-shot script
    (test/Segmentation2.py); here prediction is a resident service with
    pre-compiled per-bucket programs, dynamic micro-batching and live
    hot-swap of the federated weights.
    """

    # Compiled square input buckets (H == W == size); a request lands in the
    # smallest bucket that holds it (spatially zero-padded, output cropped),
    # and anything larger than the largest bucket runs tiled sliding-window
    # inference with the largest bucket as the tile.
    bucket_sizes: tuple[int, ...] = (128, 256)
    # Compiled batch per bucket: requests accumulate until max_batch or
    # max_delay_ms, then are padded to exactly max_batch lanes (inference-
    # mode BN is per-sample independent, so pad lanes cannot perturb real
    # lanes — test-pinned).
    max_batch: int = 8
    max_delay_ms: float = 5.0
    # Hot-swap poll period: how often the version manager checks the
    # federation's checkpoint/statefile outputs for a newer global model.
    swap_poll_s: float = 2.0
    # Tile overlap (pixels) for sliding-window inference; overlapping rows/
    # cols are blended with a deterministic separable ramp.
    tile_overlap: int = 32
    # Serving compute dtype (params stay float32, as in training).
    compute_dtype: str = "float32"
    # Data-parallel shard of a served batch over the mesh 'batch' axis;
    # max_batch must be divisible by it.
    mesh_batch: int = 1
    # Default per-request deadline for accounting (0 = none). Requests past
    # their deadline are still served (never dropped) but counted.
    deadline_ms: float = 0.0
    host: str = "127.0.0.1"
    port: int = 8890
    max_message_mb: int = 64
    # ---- Serve fleet + quantized predict (round 17) ----
    # Replica workers behind the fleet router (serve/fleet.py). 1 keeps the
    # round-10 single-replica topology (no router, no admission control).
    replicas: int = 1
    # Post-training quantized predict program (serve/quant.py): "int8"
    # builds a weight-only per-channel-symmetric int8 program per bucket
    # alongside the reference program. Installs are A/B-gated: a quantized
    # build whose probe-batch mask IoU vs the reference oracle falls below
    # quant_iou_floor is REFUSED loudly and the replica keeps serving the
    # unquantized program — never a silent accuracy cliff.
    quant: str = "none"
    quant_iou_floor: float = 0.98
    # ---- Low-precision kernel plane (round 20, fedcrack_tpu/kernels/) ----
    # Which program body the quantized predict path compiles:
    #   "reference"  — r17's dequantize-in-graph + model.apply (the default);
    #   "fused_int8" — Pallas fused dequant-matmul forward: int8 codes feed
    #                  the MXU directly, f32 accumulation, no f32 weight
    #                  tensor ever materialized;
    #   "fp8"        — same fused forward over fp8 e4m3 codes; a backend
    #                  without fp8 support degrades to "reference" (the r17
    #                  path) bit-exactly at engine build time.
    # Every non-reference plane still requires quant="int8" and installs
    # ONLY through the r17 quant_gate — a failing probe refuses loudly and
    # the fleet keeps serving the reference program.
    kernel_plane: str = "reference"
    # Optional activation fake-quant at the program boundary (dynamic
    # per-tensor symmetric int8 of the pre-sigmoid logits). Weight-only
    # quantization needs no calibration data; this flag measures the
    # activation-quant accuracy headroom on top of it.
    quant_act_fakequant: bool = False
    # Seeded probe batch for the install-time A/B gate (per bucket size).
    quant_probe_batch: int = 4
    quant_probe_seed: int = 0
    # Admission control (serve/router.py): shed load with a loud
    # RESOURCE_EXHAUSTED reject when the fleet's rolling p95 latency
    # breaches slo_p95_ms (0 = off) or when queued requests across all
    # replicas exceed queue_bound (0 = off). Shedding happens at ACCEPT
    # time only — a request already admitted is never dropped.
    slo_p95_ms: float = 0.0
    queue_bound: int = 0
    # ---- Frame-coherent video serving (round 19, serve/stream.py) ----
    # Per-stream tile cache bound (entries = tiles). A video session keys
    # cached per-tile probabilities on (model_version, tile content hash),
    # so a new frame only re-runs tiles whose bytes changed; 0 disables
    # caching entirely (every frame is a full re-run — the escape hatch).
    stream_cache_tiles: int = 4096
    # Open video sessions the serve process will hold at once; opening one
    # past the bound is REJECTED loudly (the assembly-cap idiom).
    stream_max_sessions: int = 64
    # Crack-track continuity (serve/stream.py CrackTracker): a contour in
    # frame t+1 continues the track whose last centroid lies within this
    # fraction of the frame diagonal; beyond it a new stable id is born.
    stream_track_match_frac: float = 0.05
    # ---- Elastic fleet (round 22, serve/autoscaler.py) ----
    # SLO-driven autoscaling between min_replicas and max_replicas: the
    # controller consumes the registry's own Prometheus exposition (rolling
    # p95, per-bucket queue depth, live replica count) and scales the fleet
    # — scale-up compiles + warms the new replica OFF the serving path,
    # scale-down drains via the kill/reroute machinery so zero accepted
    # requests drop. min_replicas=0 disarms the controller entirely (the
    # static round-17 fleet); armed, `replicas` is the boot size and must
    # sit inside [min_replicas, max_replicas].
    min_replicas: int = 0
    max_replicas: int = 0
    # Controller evaluation period and the cooldown after ANY scale action
    # (hysteresis against flap storms — a gust can trigger at most one
    # action per cooldown window).
    scale_interval_s: float = 1.0
    scale_cooldown_s: float = 5.0
    # Scale-up triggers: queued backlog per live replica reaching this, or
    # the rolling p95 reaching this fraction of slo_p95_ms (act BEFORE the
    # shed probe does — shed stays the loud backstop, never the steady
    # state).
    scale_up_queue_depth: int = 4
    scale_up_p95_frac: float = 0.8
    # Scale-down hysteresis: this many CONSECUTIVE calm evaluations (empty
    # queues, p95 comfortably under the trigger) before one replica drains.
    scale_down_idle_evals: int = 3
    # ---- Shadow-replica progressive delivery (round 22, serve/shadow.py) --
    # Fraction of admitted production traffic mirrored to the shadow
    # candidate (responses NEVER returned to clients); 0 disables staging —
    # published versions install directly, the round-17 behavior.
    shadow_fraction: float = 0.0
    # Mirrored completions required before a promote/rollback verdict.
    shadow_min_samples: int = 16
    # Verdict floors: candidate canary IoU vs the production reference,
    # max PSI delta between candidate and production probe profiles, and
    # the shadow-vs-production p95 latency ratio ceiling.
    shadow_iou_floor: float = 0.98
    shadow_psi_ceiling: float = 0.25
    shadow_latency_factor: float = 3.0

    def __post_init__(self) -> None:
        if not self.bucket_sizes:
            raise ValueError("bucket_sizes must not be empty")
        sizes = tuple(self.bucket_sizes)
        if list(sizes) != sorted(set(sizes)):
            raise ValueError(
                f"bucket_sizes must be strictly increasing, got {sizes}"
            )
        for s in sizes:
            if s <= 0 or s % 16 != 0:
                raise ValueError(
                    f"every bucket size must be a positive multiple of 16 "
                    f"(the U-Net's spatial contract), got {s}"
                )
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms must be >= 0, got {self.max_delay_ms}"
            )
        if self.swap_poll_s <= 0:
            raise ValueError(
                f"swap_poll_s must be > 0, got {self.swap_poll_s}"
            )
        if self.tile_overlap < 0 or self.tile_overlap >= min(sizes):
            raise ValueError(
                f"tile_overlap must be in [0, smallest bucket), got "
                f"{self.tile_overlap} with buckets {sizes}"
            )
        if self.mesh_batch < 1 or self.max_batch % self.mesh_batch != 0:
            raise ValueError(
                f"mesh_batch={self.mesh_batch} must be >= 1 and divide "
                f"max_batch={self.max_batch}"
            )
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                "serve compute_dtype must be float32 or bfloat16, got "
                f"{self.compute_dtype!r}"
            )
        if self.replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {self.replicas}")
        if self.quant not in ("none", "int8"):
            raise ValueError(
                f"serve quant must be 'none' or 'int8', got {self.quant!r}"
            )
        if not 0.0 < self.quant_iou_floor <= 1.0:
            raise ValueError(
                f"quant_iou_floor must be in (0, 1], got {self.quant_iou_floor}"
            )
        if self.kernel_plane not in ("reference", "fused_int8", "fp8"):
            raise ValueError(
                "kernel_plane must be 'reference', 'fused_int8' or 'fp8', "
                f"got {self.kernel_plane!r}"
            )
        if self.kernel_plane != "reference" and self.quant != "int8":
            raise ValueError(
                f"kernel_plane={self.kernel_plane!r} requires quant='int8' — "
                "the fused planes consume the quantized tree and ride its "
                "install gate"
            )
        if self.quant_probe_batch < 1:
            raise ValueError(
                f"quant_probe_batch must be >= 1, got {self.quant_probe_batch}"
            )
        if self.slo_p95_ms < 0:
            raise ValueError(f"slo_p95_ms must be >= 0, got {self.slo_p95_ms}")
        if self.queue_bound < 0:
            raise ValueError(f"queue_bound must be >= 0, got {self.queue_bound}")
        if self.stream_cache_tiles < 0:
            raise ValueError(
                f"stream_cache_tiles must be >= 0, got {self.stream_cache_tiles}"
            )
        if self.stream_max_sessions < 1:
            raise ValueError(
                f"stream_max_sessions must be >= 1, got {self.stream_max_sessions}"
            )
        if not 0.0 < self.stream_track_match_frac <= 1.0:
            raise ValueError(
                f"stream_track_match_frac must be in (0, 1], got "
                f"{self.stream_track_match_frac}"
            )
        if self.min_replicas < 0 or self.max_replicas < 0:
            raise ValueError(
                f"min_replicas/max_replicas must be >= 0, got "
                f"{self.min_replicas}/{self.max_replicas}"
            )
        if self.min_replicas > 0:
            if self.max_replicas < self.min_replicas:
                raise ValueError(
                    f"max_replicas={self.max_replicas} must be >= "
                    f"min_replicas={self.min_replicas}"
                )
            if not self.min_replicas <= self.replicas <= self.max_replicas:
                raise ValueError(
                    f"replicas={self.replicas} (the boot size) must sit in "
                    f"[min_replicas={self.min_replicas}, "
                    f"max_replicas={self.max_replicas}]"
                )
        elif self.max_replicas > 0:
            raise ValueError(
                "max_replicas without min_replicas is a disarmed ceiling — "
                "set min_replicas >= 1 to arm the autoscaler"
            )
        if self.scale_interval_s <= 0:
            raise ValueError(
                f"scale_interval_s must be > 0, got {self.scale_interval_s}"
            )
        if self.scale_cooldown_s < 0:
            raise ValueError(
                f"scale_cooldown_s must be >= 0, got {self.scale_cooldown_s}"
            )
        if self.scale_up_queue_depth < 1:
            raise ValueError(
                f"scale_up_queue_depth must be >= 1, got "
                f"{self.scale_up_queue_depth}"
            )
        if not 0.0 < self.scale_up_p95_frac <= 1.0:
            raise ValueError(
                f"scale_up_p95_frac must be in (0, 1], got "
                f"{self.scale_up_p95_frac}"
            )
        if self.scale_down_idle_evals < 1:
            raise ValueError(
                f"scale_down_idle_evals must be >= 1, got "
                f"{self.scale_down_idle_evals}"
            )
        if not 0.0 <= self.shadow_fraction <= 1.0:
            raise ValueError(
                f"shadow_fraction must be in [0, 1], got {self.shadow_fraction}"
            )
        if self.shadow_min_samples < 1:
            raise ValueError(
                f"shadow_min_samples must be >= 1, got {self.shadow_min_samples}"
            )
        if not 0.0 < self.shadow_iou_floor <= 1.0:
            raise ValueError(
                f"shadow_iou_floor must be in (0, 1], got {self.shadow_iou_floor}"
            )
        if self.shadow_psi_ceiling <= 0:
            raise ValueError(
                f"shadow_psi_ceiling must be > 0, got {self.shadow_psi_ceiling}"
            )
        if self.shadow_latency_factor < 1.0:
            raise ValueError(
                f"shadow_latency_factor must be >= 1, got "
                f"{self.shadow_latency_factor}"
            )


@dataclasses.dataclass(frozen=True)
class FedConfig:
    """Federation round/protocol configuration.

    Reference values: MAX_NUM_ROUND=5 (fl_server.py:18), 10 s registration
    window (fl_server.py:42), 20 s version poll (fl_client.py:141), local
    epochs hardcoded to 10 (client_fit_model.py:166).
    """

    max_rounds: int = 5
    cohort_size: int = 2
    # Async federation (round 14, fedcrack_tpu/fed/buffered.py): "sync" is
    # the barrier round machine (reference semantics + all fixes); in
    # "buffered" mode the server runs FedBuff-style buffered aggregation
    # (Nguyen et al., 2022): updates are accepted AS THEY ARRIVE, each
    # weighted by the polynomial staleness decay (1 + staleness)^-alpha
    # (FedAsync, Xie et al., 2019), folded into a buffer of `buffer_k`
    # updates, and flushed to a new global version at K — no round barrier,
    # so one straggler never stalls the federation. Clients loop
    # pull→train→push continuously. `buffer_k = cohort_size` with
    # `staleness_alpha = 0` reproduces the sync FedAvg trajectory
    # bit-exactly (test-pinned).
    mode: str = "sync"
    # Buffered mode: how many accepted updates trigger a flush (FedBuff's
    # K). The round_deadline_s backstop flushes a non-empty partial buffer
    # so a dwindling cohort cannot stall the version counter forever.
    buffer_k: int = 2
    # Polynomial staleness-decay exponent: an update trained on a base
    # `s` versions behind the current global is weighted by
    # (1 + s)^-alpha (on top of its sample count). 0 disables decay
    # (every update weighs its plain sample count — the sync-degeneration
    # escape hatch).
    staleness_alpha: float = 0.5
    # Updates staler than this many versions are REJECTED into the round
    # history (like r8 sanitation rejects) and the sender is re-synced with
    # the current global. Also bounds the window of past broadcast blobs
    # the server retains for delta-frame decode (memory: max_staleness + 1
    # broadcast-sized blobs). 0 = only updates against the current version
    # are accepted.
    max_staleness: int = 4
    # Seeded per-round cohort sampling (round 13): the seed behind
    # fed.algorithms.sample_cohort — harnesses that sample `cohort_size`
    # clients per round from a larger population (the time-multiplexed
    # cohort plane, the hierarchical aggregation tree) derive every round's
    # cohort from (cohort_seed, round), so the whole multi-round trajectory
    # reproduces from this one number.
    cohort_seed: int = 0
    local_epochs: int = 10
    learning_rate: float = 1e-3
    registration_window_s: float = 10.0
    poll_period_s: float = 20.0
    # Per-round deadline; on expiry the cohort shrinks to the clients that
    # reported (fixes the reference's forever-hanging barrier, SURVEY.md §5.3).
    round_deadline_s: float = 0.0  # 0 = no deadline
    # Quorum aggregation (Bonawitz et al., MLSys 2019: over-provision the
    # cohort, aggregate at a goal count instead of the full barrier): the
    # round closes as soon as ceil(quorum_fraction * |cohort|) updates are
    # in. 1.0 keeps the full barrier (reference semantics); the deadline
    # stays as the backstop either way. Stragglers whose report lands after
    # the quorum closed the round are re-synced to the current round (their
    # late update is logged to history, never averaged).
    quorum_fraction: float = 1.0
    # Update sanitation before FedAvg: every TrainDone payload is checked
    # against the global template (decodable, leaf count, per-leaf shape,
    # finite values) and rejected — logged to the round's history entry —
    # instead of averaged. A single NaN client otherwise poisons the global
    # model for every client. Disable only for wire-format experiments.
    sanitize_updates: bool = True
    # Byzantine-robust aggregation (round 21, fed/aggregation.py): how the
    # server COMBINES the round's accepted updates. "fedavg" is the null
    # algebra — the sample-weighted mean, bitwise-pinned to every plane's
    # historical fold. "trimmed_mean" / "median" (alias "coordinate_median")
    # are the coordinate-wise robust estimators of Yin et al. (ICML 2018);
    # "krum" / "multi_krum" the distance-scored selection of Blanchard et
    # al. (NeurIPS 2017). Robust combines ignore client-reported sample
    # counts (a Byzantine client self-reports them) and run on the gRPC
    # rounds plane and the buffered root only — edge tiers refuse them
    # loudly (a trimmed partial of a partial is not a trimmed total).
    aggregation: str = "fedavg"
    # TrimmedMean's beta: drop floor(beta * n) per coordinate from each
    # tail. [0, 0.5) so at least one value survives per coordinate.
    trim_fraction: float = 0.1
    # Krum/Multi-Krum's f: the assumed Byzantine count. Scores sum the
    # n - f - 2 smallest squared distances (clamped to >= 1 neighbor);
    # Multi-Krum averages the n - f lowest-scoring updates.
    byzantine_f: int = 1
    # Ledger-coupled quarantine (round 21): a client whose flush-time
    # robust-z anomaly score (health/ledger.py observe_flush — the r18
    # detection plane) is >= this threshold is EXCLUDED from the fold,
    # logged in the history entry's "quarantined" map, and re-synced
    # NOT_WAIT like a sanitation reject. 0 disables (detection without
    # response — r18 behavior). Composable with any `aggregation`.
    quarantine_z: float = 0.0
    # ---- Privacy plane (round 23, fedcrack_tpu/privacy/) ----
    # DP-SGD (Abadi et al. 2016): per-client gradient clipping to this L2
    # norm inside the mesh plane's sgd_step (and, update-level, in the
    # gRPC client CLI — McMahan et al. 2018). 0 disables DP entirely; the
    # dp=off traced program is byte-identical to today's (test-pinned).
    dp_clip_norm: float = 0.0
    # Gaussian noise sigma, as a multiple of dp_clip_norm (noise stddev =
    # dp_noise_multiplier * dp_clip_norm). Requires dp_clip_norm > 0 —
    # unclipped noise has no sensitivity bound to calibrate against.
    dp_noise_multiplier: float = 0.0
    # Accountant parameters (privacy/accountant.py, the RDP/moments
    # accountant): per-step sampling rate q, the delta of the reported
    # eps(delta), and how many noise additions one round charges a client
    # (0 derives local_epochs — the mesh plane's one-noise-per-epoch-step
    # granularity collapses to epochs on the gRPC plane, where the server
    # cannot see client step counts).
    dp_sample_rate: float = 0.01
    dp_delta: float = 1e-5
    dp_steps_per_round: int = 0
    # Root of the (client, round, step, leaf) noise seed tree — the r12
    # codec-seed precedent, so chaos/retry replays are bit-identical.
    dp_seed: int = 0
    # eps(delta) budget: when any charged client's cumulative epsilon
    # reaches this, the federation REFUSES to open further rounds and
    # finishes (loud, recorded in history). 0 = unlimited.
    dp_epsilon_budget: float = 0.0
    # Pairwise-mask secure aggregation (round 23, privacy/secagg.py;
    # Bonawitz et al. 2017): clients upload fixed-point int64 updates
    # under pairwise PRG masks that cancel exactly in the ordered fold;
    # dropout is closed by a seed-recovery step under the r8 quorum
    # machinery. Masked updates are OPAQUE to the r18 ledger's norm/
    # cosine windows, so secagg composes only with the null combine:
    # aggregation must stay "fedavg", quarantine_z must stay 0, the
    # update codec must stay "null", and mode must stay "sync" — each
    # violation is a loud config error (the edge-tier-refuses-non-null
    # precedent), documented as the privacy/robustness trade-off.
    secagg: bool = False
    # Fixed-point fractional bits for the masked encoding (values are
    # round(x * 2^bits) in the 2^64 residue ring).
    secagg_bits: int = 24
    # Mid-round durable server state (msgpack via atomic write+fsync+rename;
    # empty disables): persists cohort/phase/received blobs on every
    # membership or upload change, so a server killed MID-round resumes the
    # same round with the already-received updates intact (the orbax
    # checkpoint only covers round boundaries). Restored in preference to
    # the orbax checkpoint when strictly newer.
    state_path: str = ""
    # FedProx proximal term; 0 disables (plain FedAvg).
    fedprox_mu: float = 0.0
    # Crack-pixel loss weight (1 + (pos_weight-1)*mask scales each pixel's
    # BCE): >1 counters the ~7% foreground imbalance of crack masks, which
    # under plain BCE converges to low-confidence maps that threshold poorly.
    # 1.0 is the reference's unweighted BCE (client_fit_model.py:157).
    # Travels in-band to every client like fedprox_mu.
    pos_weight: float = 1.0
    # FedOpt server optimizer on the round pseudo-gradient (Reddi et al.):
    # "avg" = plain FedAvg (the reference's behavior), "momentum"/"fedavgm",
    # "adam"/"fedadam", "yogi"/"fedyogi". Applied to params only; BN stats
    # are plain-averaged.
    server_optimizer: str = "avg"
    server_lr: float = 1.0
    server_momentum: float = 0.9
    # Advertised model type. The reference advertises the vestigial string
    # "mobilenet_v2" (fl_server.py:75) while actually sharing the U-Net; we
    # advertise honestly but accept the legacy alias (SURVEY.md §2.2(3)).
    model_type: str = "resunet"
    # Wire dtype for weight payloads on the control plane: "bfloat16" halves
    # upload + broadcast bytes (server math stays float32; the reference
    # shipped full float32 pickles, fl_client.py:63).
    wire_dtype: str = "float32"
    # Compressed update transport (round 12, fedcrack_tpu/compress): how
    # each client's upload is encoded. "null" ships today's msgpack bytes
    # unchanged (the bit-exactness escape hatch, test-pinned); "int8" ships
    # the per-leaf symmetric int8-quantized round delta with f32 scale
    # sidecars; "topk_delta" ships the top-k sparsified delta with a
    # client-side error-feedback accumulator (dropped mass re-enters next
    # round). Negotiated in-band at enroll like every other hyperparameter;
    # legacy clients that ignore it keep sending raw blobs, which the
    # server still accepts (mixed-codec cohorts decode to full trees before
    # FedAvg, so they aggregate correctly).
    update_codec: str = "null"
    # TopKDeltaCodec keep fraction: each leaf transmits ceil(fraction * n)
    # entries per round (8 bytes each vs 4 per dense f32 — 0.01 is ~50x
    # fewer bytes before framing/zlib).
    topk_fraction: float = 0.01
    host: str = "127.0.0.1"
    port: int = 8889              # reference: fl_server.py:218
    # Orbax checkpoint directory; empty disables. When the directory already
    # holds a checkpoint the federation resumes from the latest round
    # (SURVEY.md §5.4 — the reference server forgot rounds on restart).
    ckpt_dir: str = ""
    # PRNG seed for the initial global model.
    seed: int = 0
    # JSONL structured-metrics file (per-round records, SURVEY.md §5.5);
    # empty disables.
    metrics_path: str = ""
    # TensorBoard event-file directory: numeric per-round/epoch metrics are
    # teed as real TB scalars (obs/tb.py, no TF dependency) — the
    # reference's workflow of opening training logs in TensorBoard
    # (client_fit_model.py:153-154). Empty disables.
    tb_dir: str = ""
    # Server-side sink directory for client-uploaded log files (the
    # reference's 'L' chunk path wrote TensorBoard events under ./logs,
    # fl_server.py:84-89); empty keeps uploads in memory only.
    logs_dir: str = ""
    # In-memory log sink caps: chunks accumulate in server memory until the
    # uploader sends `last` (then they flush to logs_dir; with logs_dir
    # empty they are retained in memory for checkpointing), so uploads must
    # hit a ceiling. Per-upload and across-all-uploads, in MiB; over-cap
    # chunks are REJECTED; 0 = uncapped. Only cohort members may upload.
    log_max_mb_per_upload: int = 64
    log_max_mb_total: int = 256
    # jax.profiler trace directory for training spans; empty disables.
    profile_dir: str = ""
    # Msgpack pytree seeding the initial global model (e.g. from the Keras h5
    # importer, tools/h5_import.py); empty initializes from `seed`.
    init_weights: str = ""
    # When server-side eval runs (server --eval-*), the best global model by
    # eval loss is kept here as a msgpack pytree with a .json metrics sidecar
    # — the federated analog of the reference's best-val ModelCheckpoint
    # (test/Segmentation.py:177-179). Empty disables.
    best_path: str = ""
    # Control-plane security. The reference's channel was fully open — no
    # identity, no transport security; anyone reaching the port could
    # enroll or poison the cohort (fl_client.py:181, SURVEY.md §5.8).
    # auth_token: shared secret required on every client message when set
    # (constant-time compared server-side; unauthenticated messages are
    # REJECTED). Empty disables. Over a plaintext channel the token would
    # travel in cleartext on every message, so auth_token without TLS
    # (no tls_cert/tls_key on the server, no tls_ca on the client) is
    # refused unless allow_insecure_token is set explicitly.
    auth_token: str = ""
    # Escape hatch for loopback/test deployments that genuinely want a
    # shared token over plaintext. Anything crossing a real network should
    # configure TLS instead — with this on, anyone on the path reads the
    # secret off the first message.
    allow_insecure_token: bool = False
    # TLS: the server serves with ssl_server_credentials when tls_cert +
    # tls_key are both set (PEM file paths); a client connects over TLS
    # when tls_ca is set (PEM root to verify the server). When the server
    # also sets tls_ca, client certificates are required (mTLS) — clients
    # then present tls_cert/tls_key. All empty = plaintext.
    tls_cert: str = ""
    tls_key: str = ""
    tls_ca: str = ""
    max_message_mb: int = 512     # reference: fl_server.py:215 (both directions here)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    # Serving plane (round 10): bucket/batching/hot-swap knobs for
    # `python -m fedcrack_tpu.serve`. Rides the same config object so one
    # preset describes a whole deployment (training + serving).
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    # Mesh shape for the TPU data plane: (#federated clients, per-client DP).
    mesh_clients: int = 8
    mesh_batch: int = 1
    # Epoch-segmented round execution (parallel.fedavg_mesh.SegmentedRound):
    # 0 runs the round as ONE compiled program (the monolithic
    # local_epochs x steps scan); K > 0 splits it into K device-resident-
    # carry segment programs (K must divide local_epochs; K = local_epochs
    # is one segment per epoch). Segmentation is bit-exact vs the monolith
    # and unlocks segment-grain staging overlap plus 1/K-sized compiles
    # (the 256 px reference-scale program only compiles chunked).
    segments: int = 0
    # With segments > 0: stream the next round's staging one step-range
    # chunk per in-flight segment (True, epoch-grain double buffering)
    # instead of one monolithic transfer per round (False). Peak staged
    # HBM is ~2 epoch slabs either way; streaming keeps any single
    # transfer 1/K the size and hides more of it under compute.
    segment_overlap: bool = True
    # Data plane for the mesh rounds (round 9): "streamed" re-stages each
    # round's shuffled epoch slab (the modes above); "resident" stages the
    # deduplicated per-client sample pool ONCE (data.pipeline.SamplePool,
    # sharded P('clients')) and ships only a [clients, epochs, steps,
    # batch] int32 gather plan per round — kilobytes instead of the epoch
    # slab, byte-identical trajectory (test-pinned). An HBM guard
    # (parallel.driver.resident_pool_fits) falls back to the streamed path
    # when the pool doesn't fit the device.
    data_placement: str = "streamed"

    def __post_init__(self) -> None:
        if self.data.img_size != self.model.img_size:
            raise ValueError(
                "data.img_size and model.img_size must match; got "
                f"{self.data.img_size} vs {self.model.img_size}"
            )
        if self.segments < 0:
            raise ValueError(f"segments must be >= 0, got {self.segments}")
        if self.segments > 0 and self.local_epochs % self.segments != 0:
            raise ValueError(
                f"segments={self.segments} must divide "
                f"local_epochs={self.local_epochs} (epoch-grain segmentation)"
            )
        if self.data_placement not in ("streamed", "resident"):
            raise ValueError(
                "data_placement must be 'streamed' or 'resident', got "
                f"{self.data_placement!r}"
            )
        if self.mode not in ("sync", "buffered"):
            raise ValueError(
                f"mode must be 'sync' or 'buffered', got {self.mode!r}"
            )
        if self.buffer_k < 1:
            raise ValueError(f"buffer_k must be >= 1, got {self.buffer_k}")
        if self.staleness_alpha < 0.0:
            raise ValueError(
                f"staleness_alpha must be >= 0, got {self.staleness_alpha}"
            )
        if self.max_staleness < 0:
            raise ValueError(
                f"max_staleness must be >= 0, got {self.max_staleness}"
            )
        if self.cohort_seed < 0:
            # SeedSequence entropy must be non-negative; fail at config
            # parse, not inside the first round's sample_cohort call.
            raise ValueError(
                f"cohort_seed must be >= 0, got {self.cohort_seed}"
            )
        if not 0.0 < self.quorum_fraction <= 1.0:
            raise ValueError(
                f"quorum_fraction must be in (0, 1], got {self.quorum_fraction}"
            )
        if self.aggregation not in (
            "fedavg", "trimmed_mean", "median", "coordinate_median",
            "krum", "multi_krum",
        ):
            raise ValueError(
                "aggregation must be one of 'fedavg', 'trimmed_mean', "
                "'median', 'coordinate_median', 'krum', 'multi_krum', got "
                f"{self.aggregation!r}"
            )
        if not 0.0 <= self.trim_fraction < 0.5:
            raise ValueError(
                f"trim_fraction must be in [0, 0.5), got {self.trim_fraction}"
            )
        if self.byzantine_f < 0:
            raise ValueError(
                f"byzantine_f must be >= 0, got {self.byzantine_f}"
            )
        if self.quarantine_z < 0.0:
            raise ValueError(
                f"quarantine_z must be >= 0 (0 disables), got "
                f"{self.quarantine_z}"
            )
        if self.dp_clip_norm < 0.0:
            raise ValueError(
                f"dp_clip_norm must be >= 0 (0 disables DP), got "
                f"{self.dp_clip_norm}"
            )
        if self.dp_noise_multiplier < 0.0:
            raise ValueError(
                f"dp_noise_multiplier must be >= 0, got "
                f"{self.dp_noise_multiplier}"
            )
        if self.dp_noise_multiplier > 0.0 and self.dp_clip_norm <= 0.0:
            raise ValueError(
                "dp_noise_multiplier > 0 requires dp_clip_norm > 0: noise "
                "is calibrated to the clip norm (stddev = multiplier * "
                "clip), and unclipped gradients have no sensitivity bound "
                "for the accountant to certify."
            )
        if not 0.0 < self.dp_sample_rate <= 1.0:
            raise ValueError(
                f"dp_sample_rate must be in (0, 1], got {self.dp_sample_rate}"
            )
        if not 0.0 < self.dp_delta < 1.0:
            raise ValueError(
                f"dp_delta must be in (0, 1), got {self.dp_delta}"
            )
        if self.dp_steps_per_round < 0:
            raise ValueError(
                f"dp_steps_per_round must be >= 0 (0 derives local_epochs), "
                f"got {self.dp_steps_per_round}"
            )
        if self.dp_epsilon_budget < 0.0:
            raise ValueError(
                f"dp_epsilon_budget must be >= 0 (0 = unlimited), got "
                f"{self.dp_epsilon_budget}"
            )
        if not 8 <= self.secagg_bits <= 52:
            raise ValueError(
                f"secagg_bits must be in [8, 52] (float64-exact fixed "
                f"point), got {self.secagg_bits}"
            )
        if self.secagg:
            # The privacy/robustness trade-off, stated loudly: masked
            # uploads are uniformly-random residues, opaque to the r18
            # ledger's norm/cosine windows and to every robust combine,
            # and only the sync plane carries the roster handshake. Refuse
            # the combination at config time (the edge-tier-refuses-
            # non-null precedent) rather than silently degrade either
            # property.
            if self.aggregation != "fedavg":
                raise ValueError(
                    "secagg composes only with the null combine: masked "
                    "updates are opaque to robust aggregation, so "
                    "aggregation must be 'fedavg', got "
                    f"{self.aggregation!r}. This is the privacy/robustness "
                    "trade-off — pick one per federation."
                )
            if self.quarantine_z != 0.0:
                raise ValueError(
                    "secagg requires quarantine_z=0: the r18 ledger cannot "
                    "window norms/cosines of masked uploads, so quarantine "
                    "would act on noise. Got quarantine_z="
                    f"{self.quarantine_z}."
                )
            if self.update_codec != "null":
                raise ValueError(
                    "secagg requires update_codec='null': the masked "
                    "fixed-point wire format replaces the codec stack, got "
                    f"{self.update_codec!r}"
                )
            if self.mode != "sync":
                raise ValueError(
                    "secagg requires mode='sync': the masking roster is a "
                    "closed cohort, and the buffered plane folds across "
                    f"cohort boundaries. Got mode={self.mode!r}."
                )
        if self.wire_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"wire_dtype must be float32 or bfloat16, got {self.wire_dtype!r}"
            )
        if self.update_codec not in ("null", "int8", "topk_delta"):
            raise ValueError(
                "update_codec must be 'null', 'int8' or 'topk_delta', got "
                f"{self.update_codec!r}"
            )
        if not 0.0 < self.topk_fraction <= 1.0:
            raise ValueError(
                f"topk_fraction must be in (0, 1], got {self.topk_fraction}"
            )
        if self.max_message_mb < 1:
            raise ValueError(
                f"max_message_mb must be >= 1, got {self.max_message_mb}"
            )
        if bool(self.tls_cert) != bool(self.tls_key):
            # Half a TLS identity must fail fast — otherwise the server
            # would silently fall back to a plaintext port (and a client
            # silently omit its mTLS certificate) while the operator
            # believes TLS is on.
            raise ValueError(
                "tls_cert and tls_key must be set together; got "
                f"tls_cert={self.tls_cert!r}, tls_key={self.tls_key!r}"
            )
        if (
            self.auth_token
            and not (self.tls_cert or self.tls_ca)
            and not self.allow_insecure_token
        ):
            # A shared secret over a plaintext channel is sent in cleartext
            # on EVERY message — an operator following a quickstart would
            # ship it to any on-path observer without noticing. Refuse the
            # combination unless it is opted into by name.
            raise ValueError(
                "auth_token is set but the channel is plaintext (no TLS "
                "config): the secret would travel in cleartext on every "
                "message. Configure tls_cert/tls_key (server) or tls_ca "
                "(client), or set allow_insecure_token=true to accept this "
                "for loopback/testing."
            )

    # ---- serialization (in-band config map + files) ----

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, blob: str | bytes) -> "FedConfig":
        raw = json.loads(blob)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: Mapping[str, Any]) -> "FedConfig":
        raw = dict(raw)
        model = raw.pop("model", {})
        data = raw.pop("data", {})
        serve = raw.pop("serve", {})
        known = {f.name for f in dataclasses.fields(cls)}
        raw = {k: v for k, v in raw.items() if k in known}
        mknown = {f.name for f in dataclasses.fields(ModelConfig)}
        dknown = {f.name for f in dataclasses.fields(DataConfig)}
        sknown = {f.name for f in dataclasses.fields(ServeConfig)}
        mc = ModelConfig(**{k: _detuple(k, v) for k, v in model.items() if k in mknown})
        dc = DataConfig(**{k: v for k, v in data.items() if k in dknown})
        sc = ServeConfig(
            **{k: _detuple(k, v) for k, v in serve.items() if k in sknown}
        )
        return cls(model=mc, data=dc, serve=sc, **raw)


def _detuple(key: str, value: Any) -> Any:
    if key in ("encoder_features", "decoder_features", "bucket_sizes") and isinstance(
        value, list
    ):
        return tuple(value)
    return value
