"""JSONL metrics sink + timing/profiling helpers.

One record per line, one file per process; records carry a monotonic
``t`` (seconds since logger creation) and a wall-clock ``ts`` so runs can
be merged across machines. The sink is thread-safe: the gRPC service, the
tick loop, and checkpoint tasks may all log concurrently.
"""

from __future__ import annotations

import contextlib
import io
import json
import math
import numbers
import os
import random
import threading
import time
from typing import Any, Iterator


class MetricsLogger:
    """Append-only JSONL metrics writer.

    ``kind`` names the record type (``round``, ``fit_epoch``, ``session``,
    ...); everything else is free-form JSON-safe fields. Non-JSON values
    (jax/numpy scalars) are coerced via ``float``/``int`` where possible.
    """

    def __init__(
        self,
        path: str | os.PathLike | io.TextIOBase,
        echo=None,
        tb_dir: str | os.PathLike | None = None,
    ):
        if isinstance(path, io.TextIOBase):
            self._f = path
            self._owns = False
        else:
            p = os.fspath(path)
            parent = os.path.dirname(os.path.abspath(p))
            os.makedirs(parent, exist_ok=True)
            self._f = open(p, "a", encoding="utf-8")
            self._owns = True
        self._echo = echo
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        # Optional TensorBoard tee (obs/tb.py): numeric fields of records
        # that carry a `round`/`epoch` step become `kind/field` scalars in a
        # real event file — the reference's "open it in TensorBoard"
        # workflow (client_fit_model.py:153-154) next to the JSONL.
        self._tb = None
        if tb_dir:
            from fedcrack_tpu.obs.tb import SummaryWriter

            self._tb = SummaryWriter(tb_dir)

    def log(self, kind: str, **fields: Any) -> dict:
        record = {
            "kind": kind,
            "t": round(time.monotonic() - self._t0, 6),
            # interval math uses the monotonic "t" above; "ts" is display-only
            # fedlint: disable=DET001 -- human-readable record timestamp
            "ts": time.time(),
        }
        for k, v in fields.items():
            record[k] = _coerce(v)
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()
        if self._tb is not None:
            step = record.get("round", record.get("epoch"))
            if isinstance(step, int) and not isinstance(step, bool):
                for k, v in record.items():
                    if k in ("kind", "t", "ts", "round", "epoch"):
                        continue
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        self._tb.add_scalar(f"{kind}/{k}", float(v), step)
        if self._echo is not None:
            self._echo(line)
        return record

    @property
    def tb_enabled(self) -> bool:
        """Whether a TensorBoard tee is attached — callers can skip building
        histogram inputs (e.g. a weight-delta tree) when nothing consumes
        them."""
        return self._tb is not None

    def log_histograms(self, step: int, tree: Any, prefix: str = "weights") -> None:
        """Tee per-layer distributions of a pytree (weights, round updates)
        into the TensorBoard file as histogram summaries — the reference's
        histogram_freq=1 Keras callback (client_fit_model.py:153-154).
        No-op without a tb_dir; the JSONL sink stays scalar-only (a
        30-bucket histogram per layer per round belongs in TB, not in the
        structured record of truth)."""
        if self._tb is None:
            return
        import jax

        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        for path, leaf in leaves:
            name = "/".join(_path_part(k) for k in path)
            self._tb.add_histogram(f"{prefix}/{name}", leaf, step)

    def close(self) -> None:
        if self._owns:
            with self._lock:
                self._f.close()
        if self._tb is not None:
            self._tb.close()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _path_part(key: Any) -> str:
    """One tree-path element as a clean tag component (DictKey('conv') ->
    'conv', SequenceKey(2) -> '2')."""
    for attr in ("key", "idx", "name"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def _coerce(value: Any) -> Any:
    """Make jax/numpy scalars and containers JSON-safe."""
    if isinstance(value, (str, bool, int, float)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _coerce(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_coerce(v) for v in value]
    if isinstance(value, numbers.Integral):
        return int(value)
    try:
        as_float = float(value)
    except (TypeError, ValueError):
        return repr(value)
    # NaN/Infinity are not valid JSON (RFC 8259); keep the line parseable by
    # strict consumers (jq, JSON.parse) while preserving the diagnostic.
    return as_float if math.isfinite(as_float) else str(as_float)


def read_metrics(path: str | os.PathLike, kind: str | None = None) -> list[dict]:
    """Load a JSONL metrics file, optionally filtered by record kind."""
    records = []
    with open(os.fspath(path), encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if kind is None or rec.get("kind") == kind:
                records.append(rec)
    return records


class StreamingPercentiles:
    """Streaming p50/p95/p99 over a bounded reservoir (Vitter's algorithm R).

    The serving batcher records one latency sample per request; an unbounded
    sample list would grow with traffic, and t-digest-style sketches are more
    machinery than three percentiles need. A seeded reservoir keeps a
    uniform sample of everything seen in O(capacity) memory, and while the
    reservoir has not overflowed the percentiles are EXACT — equal to
    ``numpy.percentile(all_samples, q)`` with linear interpolation
    (test-pinned). Deterministic for a given (seed, insertion sequence).

    Thread-safe: ``add`` may be called from the batcher worker while a stats
    endpoint reads ``summary``.
    """

    def __init__(self, capacity: int = 4096, seed: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._capacity = capacity
        self._rng = random.Random(seed)
        self._values: list[float] = []
        self._count = 0
        self._max = None
        self._min = None
        self._sum = 0.0
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            self._max = v if self._max is None else max(self._max, v)
            self._min = v if self._min is None else min(self._min, v)
            if len(self._values) < self._capacity:
                self._values.append(v)
            else:
                j = self._rng.randrange(self._count)
                if j < self._capacity:
                    self._values[j] = v

    @property
    def count(self) -> int:
        return self._count

    def merge(self, other: "StreamingPercentiles") -> "StreamingPercentiles":
        """Fold ``other``'s reservoir into this one — the cross-replica /
        cross-plane aggregation a multi-replica serve fleet needs (each
        replica keeps its own reservoir; the fleet view is the merge).

        Semantics (seeded, order-pinned — test-pinned):

        - count/sum/min/max merge EXACTLY, whatever the reservoir does;
        - while the combined sample fits ``capacity``, the merged reservoir
          is the concatenation (self's values then other's) — percentiles
          stay EXACTLY ``numpy.percentile`` of the pooled samples;
        - past capacity, each retained value represents ``seen/len``
          stream items; the merge keeps a weighted sample without
          replacement via Efraimidis–Spirakis keys (``u ** (1/w)``) drawn
          from SELF's rng over the pinned order (self's reservoir then
          other's) — deterministic for a given (seed, call sequence), and
          each side contributes ~proportionally to how much stream it saw.

        ``other`` is snapshotted under its own lock FIRST, then self is
        updated under its lock — sequential leaf acquisitions, so
        concurrent ``a.merge(b)`` / ``b.merge(a)`` cannot deadlock.
        Returns ``self`` for chaining.
        """
        if other is self:
            raise ValueError("merge(self) would double-count the reservoir")
        with other._lock:
            o_values = list(other._values)
            o_count, o_sum = other._count, other._sum
            o_min, o_max = other._min, other._max
        if o_count == 0:
            return self
        with self._lock:
            s_len = len(self._values)
            if self._count + o_count <= self._capacity:
                self._values.extend(o_values)
            else:
                weighted = []
                if s_len:
                    w_self = self._count / s_len
                    weighted += [(v, w_self) for v in self._values]
                w_other = o_count / len(o_values)
                weighted += [(v, w_other) for v in o_values]
                keyed = [
                    (self._rng.random() ** (1.0 / w), v) for v, w in weighted
                ]
                keyed.sort(key=lambda kv: (-kv[0], kv[1]))
                self._values = [v for _, v in keyed[: self._capacity]]
            self._count += o_count
            self._sum += o_sum
            if o_min is not None:
                self._min = o_min if self._min is None else min(self._min, o_min)
            if o_max is not None:
                self._max = o_max if self._max is None else max(self._max, o_max)
        return self

    def percentile(self, q: float) -> float | None:
        """numpy.percentile(..., method='linear') over the reservoir; None
        while empty."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"q must be in [0, 100], got {q}")
        with self._lock:
            vals = sorted(self._values)
        if not vals:
            return None
        pos = (len(vals) - 1) * (q / 100.0)
        lo = math.floor(pos)
        hi = math.ceil(pos)
        if lo == hi:
            return vals[int(pos)]
        return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)

    def summary(self) -> dict:
        """The serving artifact's latency block: count + min/mean/max +
        p50/p95/p99 (None while empty)."""
        with self._lock:
            count, total = self._count, self._sum
            vmin, vmax = self._min, self._max
        return {
            "count": count,
            "min": vmin,
            "mean": (total / count) if count else None,
            "max": vmax,
            "p50": self.percentile(50.0),
            "p95": self.percentile(95.0),
            "p99": self.percentile(99.0),
        }


@contextlib.contextmanager
def stopwatch() -> Iterator[dict]:
    """``with stopwatch() as w: ...; w['seconds']`` — wall-clock of a span."""
    out = {"seconds": 0.0}
    t0 = time.perf_counter()
    try:
        yield out
    finally:
        out["seconds"] = time.perf_counter() - t0


@contextlib.contextmanager
def profiler_trace(logdir: str | None) -> Iterator[None]:
    """Wrap a span in ``jax.profiler.trace`` when ``logdir`` is set.

    The produced trace is the TPU-native upgrade of the reference's
    TensorBoard callback (client_fit_model.py:153-154): open it with
    TensorBoard's profile plugin or xprof to see the XLA op timeline.
    ``None`` disables tracing with zero overhead.
    """
    if not logdir:
        yield
        return
    import jax

    os.makedirs(logdir, exist_ok=True)
    with jax.profiler.trace(logdir):
        yield
