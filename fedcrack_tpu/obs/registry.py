"""Thread-safe metrics registry — ONE catalog for every plane's counters.

The repo's signals grew up plane-by-plane (round history dicts, JSONL
records, dataclass fields, in-object reservoirs); this module gives them a
single live home with Prometheus's data model: **Counter** (monotone),
**Gauge** (set/inc/dec, or a collect-time callback), **Histogram**
(cumulative buckets + ``_sum``/``_count``), each optionally a *labeled
family* (``REGISTRY.counter("fed_updates_total", labels=("result",))``).
``fedcrack_tpu.obs.promexp`` serves the exposition over HTTP.

Design contracts:

- **Thread-safe by construction**: family creation is guarded by the
  registry lock, every value update by a per-family lock — both built via
  ``analysis.sanitizers.make_lock`` so the lock-order monitor and the
  LOCK001 static graph see them. All acquisitions are leaf-level
  (``collect`` snapshots the family map under the registry lock, releases,
  then visits each family lock in turn — never nested).
- **Deterministic exposition** (the DET004/ASYNC001 discipline applied to
  telemetry): families are emitted in sorted name order, children in sorted
  label-value order, histogram buckets in ascending ``le`` order. Two
  registries holding the same values expose byte-identical text.
- **Catalog-stable names, enforced twice**: metric names must be
  ``snake_case`` with a unit suffix (``_seconds``, ``_bytes``, ``_total``,
  ``_ratio``, ``_versions`` for staleness, or ``_replicas`` for fleet
  population) — validated here at runtime
  and by the fedlint rule OBS001 statically, so the exposition a dashboard
  scrapes can never drift into free-form spelling.
- **Get-or-create**: calling ``registry.counter(name, ...)`` twice returns
  the SAME family (type/labels must match, else ``ValueError``), so call
  sites need no import-time registration ceremony.

``REGISTRY`` is the process-default instance every plane instruments
against (the Prometheus client idiom); tests build private registries for
exposition-format pins and read deltas from the default one for
integration pins (counters only ever grow).
"""

from __future__ import annotations

import math
import re
from typing import Any, Callable, Sequence

from fedcrack_tpu.analysis.sanitizers import make_lock

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# The unit vocabulary OBS001 pins (ISSUE r15): the issue's four suffixes
# plus `_versions`, the async plane's staleness unit (a staleness histogram
# measures model-version lag, not seconds or bytes), `_replicas`
# (round 17: the serve fleet's live-worker count — a population gauge,
# not a monotone total), and `_info` (round 20: the Prometheus info-metric
# idiom — a constant-1 gauge whose LABELS carry categorical state, e.g.
# which kernel plane answers quantized traffic).
UNIT_SUFFIXES = (
    "_seconds", "_bytes", "_total", "_ratio", "_versions", "_replicas", "_info",
)

# Latency-shaped default buckets (Prometheus client defaults extended to
# 30 s — a federation flush on a loaded CPU host can take seconds).
DEFAULT_SECONDS_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)
# Staleness-shaped buckets: versions behind the global.
DEFAULT_VERSIONS_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)


def validate_metric_name(name: str) -> str:
    """The OBS001 contract at runtime: snake_case + a unit suffix."""
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} is not snake_case "
            "([a-z][a-z0-9_]*; no leading digit, no uppercase)"
        )
    if not name.endswith(UNIT_SUFFIXES):
        raise ValueError(
            f"metric name {name!r} lacks a unit suffix {UNIT_SUFFIXES} "
            "(OBS001: the catalog stays greppable and unit-unambiguous)"
        )
    return name


def _escape_label(value: str) -> str:
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: float) -> str:
    """Prometheus text-format number: integral floats print as integers,
    non-finite values in Go spelling (``+Inf``/``-Inf``/``NaN``)."""
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


class _Child:
    """One (label-values) time series inside a family."""

    __slots__ = ("_family",)

    def __init__(self, family: "MetricFamily"):
        self._family = family


class Counter(_Child):
    __slots__ = ("_value",)

    def __init__(self, family: "MetricFamily"):
        super().__init__(family)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        a = float(amount)
        if a < 0:
            raise ValueError(f"counters only go up; inc({amount}) refused")
        with self._family._lock:
            self._value += a

    @property
    def value(self) -> float:
        with self._family._lock:
            return self._value


class Gauge(_Child):
    __slots__ = ("_value", "_fn")

    def __init__(self, family: "MetricFamily"):
        super().__init__(family)
        self._value = 0.0
        self._fn: Callable[[], float] | None = None

    def set(self, value: float) -> None:
        with self._family._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._family._lock:
            self._fn = None
            self._value += float(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Collect-time callback: the gauge reads ``fn()`` at every scrape
        (live watermarks, sentry deltas). A raising callback surfaces as
        ``NaN`` rather than failing the whole exposition."""
        with self._family._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._family._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:
            return float("nan")


class Histogram(_Child):
    __slots__ = ("_counts", "_sum", "_count")

    def __init__(self, family: "MetricFamily"):
        super().__init__(family)
        self._counts = [0] * (len(family.buckets) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        buckets = self._family.buckets
        i = len(buckets)
        for j, ub in enumerate(buckets):
            if v <= ub:
                i = j
                break
        with self._family._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        """Cumulative bucket counts keyed by upper bound, plus sum/count."""
        with self._family._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        return {
            "buckets": list(zip(list(self._family.buckets) + [math.inf], cum)),
            "sum": total,
            "count": n,
        }


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """One named metric + its labeled children. An unlabeled family has a
    single anonymous child and proxies its methods (``family.inc(...)``)."""

    def __init__(
        self,
        name: str,
        kind: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] | None = None,
    ):
        validate_metric_name(name)
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__") or ln == "le":
                raise ValueError(f"bad label name {ln!r} for metric {name!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        if kind == "histogram":
            bks = tuple(
                float(b) for b in (
                    DEFAULT_SECONDS_BUCKETS if buckets is None else buckets
                )
            )
            if list(bks) != sorted(set(bks)):
                raise ValueError(f"histogram buckets must be strictly increasing: {bks}")
            self.buckets = bks
        elif buckets is not None:
            raise ValueError(f"buckets= is histogram-only (metric {name!r})")
        else:
            self.buckets = ()
        self._lock = make_lock(f"obs.registry.{kind}")
        self._children: dict[tuple[str, ...], Any] = {}
        if not self.labelnames:
            self._children[()] = _KINDS[kind](self)

    def labels(self, **labelvalues: str) -> Any:
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} wants labels {self.labelnames}, "
                f"got {tuple(sorted(labelvalues))}"
            )
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _KINDS[self.kind](self)
                self._children[key] = child
            return child

    # -- unlabeled proxy --

    def _solo(self) -> Any:
        if self.labelnames:
            raise ValueError(
                f"metric {self.name!r} is labeled {self.labelnames}; "
                "use .labels(...)"
            )
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._solo().set_function(fn)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    @property
    def value(self) -> float:
        return self._solo().value

    def snapshot(self) -> dict:
        return self._solo().snapshot()

    # -- exposition --

    def _series(self) -> list[tuple[tuple[str, ...], Any]]:
        with self._lock:
            items = list(self._children.items())
        return sorted(items, key=lambda kv: kv[0])

    def expose(self) -> list[str]:
        """This family's exposition lines (sorted children — deterministic)."""
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for key, child in self._series():
            pairs = [
                f'{ln}="{_escape_label(lv)}"'
                for ln, lv in zip(self.labelnames, key)
            ]
            base = "{" + ",".join(pairs) + "}" if pairs else ""
            if self.kind == "histogram":
                snap = child.snapshot()
                for ub, cum in snap["buckets"]:
                    le = f'le="{format_value(ub)}"'
                    lbl = "{" + ",".join(pairs + [le]) + "}"
                    lines.append(f"{self.name}_bucket{lbl} {cum}")
                lines.append(f"{self.name}_sum{base} {format_value(snap['sum'])}")
                lines.append(f"{self.name}_count{base} {snap['count']}")
            else:
                lines.append(f"{self.name}{base} {format_value(child.value)}")
        return lines


class MetricsRegistry:
    """The catalog: get-or-create metric families, deterministic exposition."""

    def __init__(self) -> None:
        self._lock = make_lock("obs.registry.families")
        self._families: dict[str, MetricFamily] = {}

    def _get_or_create(
        self,
        name: str,
        kind: str,
        help: str,
        labels: Sequence[str],
        buckets: Sequence[float] | None = None,
    ) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = MetricFamily(
                    name, kind, help=help, labelnames=labels, buckets=buckets
                )
                self._families[name] = fam
                return fam
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, wanted {kind}"
            )
        if tuple(labels) != fam.labelnames:
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{fam.labelnames}, wanted {tuple(labels)}"
            )
        if kind == "histogram" and buckets is not None and (
            tuple(float(b) for b in buckets) != fam.buckets
        ):
            raise ValueError(
                f"metric {name!r} already registered with buckets {fam.buckets}"
            )
        return fam

    def counter(
        self, name: str, help: str = "", labels: Sequence[str] = ()
    ) -> MetricFamily:
        return self._get_or_create(name, "counter", help, labels)

    def gauge(
        self, name: str, help: str = "", labels: Sequence[str] = ()
    ) -> MetricFamily:
        return self._get_or_create(name, "gauge", help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] | None = None,
    ) -> MetricFamily:
        return self._get_or_create(name, "histogram", help, labels, buckets=buckets)

    def families(self) -> list[MetricFamily]:
        with self._lock:
            fams = list(self._families.values())
        return sorted(fams, key=lambda f: f.name)

    def get(self, name: str) -> MetricFamily | None:
        with self._lock:
            return self._families.get(name)

    def exposition(self) -> str:
        """Prometheus text format v0.0.4 of the whole registry — sorted
        families, sorted children, trailing newline (the format requires the
        final line be newline-terminated)."""
        lines: list[str] = []
        for fam in self.families():
            lines.extend(fam.expose())
        return "\n".join(lines) + "\n" if lines else ""

    def values(self) -> dict[str, dict[tuple[str, ...], float]]:
        """Plain-number snapshot (histograms as their ``_count``) — the
        cheap programmatic read tests and drills diff before/after."""
        out: dict[str, dict[tuple[str, ...], float]] = {}
        for fam in self.families():
            series: dict[tuple[str, ...], float] = {}
            for key, child in fam._series():
                if fam.kind == "histogram":
                    series[key] = float(child.snapshot()["count"])
                else:
                    series[key] = float(child.value)
            out[fam.name] = series
        return out


# The process-default registry every plane instruments against.
REGISTRY = MetricsRegistry()
