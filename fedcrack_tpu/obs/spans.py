"""Dapper-style trace spans — correlated JSONL timelines across planes.

A span is one named interval with a **trace id** (the correlation key: a
federation round, a serve request) and an optional **parent span id**, so a
multi-plane session can be reconstructed as a tree instead of interleaved
log lines: ``round-3`` owns the driver's dispatch span, the tree edge's
flush span and the transport pushes it correlates; ``req-000042`` owns the
serve front door's request span, the batch it rode and the swap that
installed mid-flight.

Recording follows the repo's sanitizer idiom (``make_lock`` /
``install_monitor``): instrumentation calls the module-level
:func:`span` context manager unconditionally — it is a **no-op costing one
global read** until a recorder is installed (:func:`install`, or a
:class:`SpanRecorder` passed explicitly). Durations come from the
monotonic clock; the wall clock appears only as the display-only ``ts``
field, per the obs JSONL convention ("t" = monotonic offset there too).

Record shape (one JSON object per line)::

    {"name": "serve.batch", "trace": "req-000042", "span": 17,
     "parent": 12, "t": 3.104, "dur_s": 0.0021, "ts": 1789... ,
     "bucket": 128}

Span ids are a per-recorder sequence — deterministic for a deterministic
schedule, merely unique otherwise.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import time
from typing import Any, Iterator

from fedcrack_tpu.analysis.sanitizers import make_lock


class SpanHandle:
    """What a ``with span(...)`` body sees: the ids to thread to children."""

    __slots__ = ("span_id", "trace", "attrs")

    def __init__(self, span_id: int, trace: str | None):
        self.span_id = span_id
        self.trace = trace
        self.attrs: dict[str, Any] = {}

    def set(self, **attrs: Any) -> None:
        """Attach attributes discovered mid-span (e.g. the model version a
        batch was answered from)."""
        self.attrs.update(attrs)


class SpanRecorder:
    """Append-only JSONL span sink; thread-safe."""

    def __init__(self, path: str | os.PathLike | io.TextIOBase):
        if isinstance(path, io.TextIOBase):
            self._f = path
            self._owns = False
        else:
            p = os.fspath(path)
            parent = os.path.dirname(os.path.abspath(p))
            os.makedirs(parent, exist_ok=True)
            self._f = open(p, "a", encoding="utf-8")
            self._owns = True
        self._lock = make_lock("obs.spans.sink")
        self._t0 = time.monotonic()
        self._seq = 0

    def _next_id(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        trace: str | None = None,
        parent: int | None = None,
        **attrs: Any,
    ) -> Iterator[SpanHandle]:
        handle = SpanHandle(self._next_id(), trace)
        t_start = time.monotonic()
        try:
            yield handle
        finally:
            dur = time.monotonic() - t_start
            record: dict[str, Any] = {
                "name": name,
                "trace": trace,
                "span": handle.span_id,
                "parent": parent,
                "t": round(t_start - self._t0, 6),
                "dur_s": round(dur, 6),
                # Interval math above is monotonic; the wall clock is the
                # display-only "ts" field (obs JSONL convention).
                # fedlint: disable=DET001 -- human-readable record timestamp
                "ts": time.time(),
            }
            for k, v in attrs.items():
                record[k] = v
            for k, v in handle.attrs.items():
                record[k] = v
            line = json.dumps(record, sort_keys=True, default=str)
            with self._lock:
                self._f.write(line + "\n")
                self._f.flush()

    def close(self) -> None:
        if self._owns:
            with self._lock:
                self._f.close()

    def __enter__(self) -> "SpanRecorder":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# ---- the module-level recorder (sanitizer idiom: zero-cost when off) ----

_recorder: SpanRecorder | None = None
_recorder_lock = make_lock("obs.spans.install")


def install(path: str | os.PathLike | io.TextIOBase) -> SpanRecorder:
    """Install the process span recorder; returns it. Replacing an existing
    recorder closes the old one."""
    global _recorder
    rec = SpanRecorder(path)
    with _recorder_lock:
        old, _recorder = _recorder, rec
    if old is not None:
        old.close()
    return rec


def uninstall() -> None:
    global _recorder
    with _recorder_lock:
        old, _recorder = _recorder, None
    if old is not None:
        old.close()


def current() -> SpanRecorder | None:
    return _recorder


@contextlib.contextmanager
def span(
    name: str,
    *,
    trace: str | None = None,
    parent: int | None = None,
    **attrs: Any,
) -> Iterator[SpanHandle | None]:
    """Record ``name`` against the installed recorder; a no-op (yielding
    ``None``) when none is installed — instrumentation sites never branch."""
    rec = _recorder
    if rec is None:
        yield None
        return
    with rec.span(name, trace=trace, parent=parent, **attrs) as handle:
        yield handle


def read_spans(path: str | os.PathLike, name: str | None = None) -> list[dict]:
    """Load a span JSONL, optionally filtered by span name."""
    out = []
    with open(os.fspath(path), encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if name is None or rec.get("name") == name:
                out.append(rec)
    return out
