"""Dapper-style trace spans — correlated JSONL timelines across planes.

A span is one named interval with a **trace id** (the correlation key: a
federation round, a serve request) and an optional **parent span id**, so a
multi-plane session can be reconstructed as a tree instead of interleaved
log lines: ``round-3`` owns the driver's dispatch span, the tree edge's
flush span and the transport pushes it correlates; ``req-000042`` owns the
serve front door's request span, the batch it rode and the swap that
installed mid-flight.

Recording follows the repo's sanitizer idiom (``make_lock`` /
``install_monitor``): instrumentation calls the module-level
:func:`span` context manager unconditionally — it is a **no-op costing one
global read** until a recorder is installed (:func:`install`, or a
:class:`SpanRecorder` passed explicitly). Durations come from the
monotonic clock; the wall clock appears only as the display-only ``ts``
field, per the obs JSONL convention ("t" = monotonic offset there too).

Record shape (one JSON object per line)::

    {"name": "serve.batch", "trace": "req-000042", "span": 17,
     "parent": 12, "t": 3.104, "dur_s": 0.0021, "ts": 1789... ,
     "bucket": 128}

Span ids are a per-recorder sequence — deterministic for a deterministic
schedule, merely unique otherwise.

Cross-process propagation (round 16): a span that must be referenced from
ANOTHER process (or another recorder file) carries a **wire-safe trace
context** — :class:`TraceContext`, serialized as ``"<trace>#<key>"`` where
``key`` is a sender-chosen string unique within the trace (span ids are
per-recorder sequences, so an integer id cannot cross a file boundary
unambiguously). The sender records the context as its span's ``ctx``
attribute; the receiver records it as ``remote_parent`` (one upstream) or
``links`` (fan-in, e.g. a flush aggregating many pushes), and
``tools/trace_stitch.py`` joins the per-process JSONL files on those
strings. The trace id itself is derived from the model-version lineage —
:func:`version_trace` — because every party already learns the base
version in-band (the enroll/pull config map, the frame's ``base_version``),
so client, edge, root and serve spans of one update lifecycle agree on ONE
trace id without any extra negotiation. ``TraceContext.from_wire`` returns
``None`` on anything malformed: a dropped or corrupted context degrades to
a parentless span, never an error.

Rotation (round 16): ``SpanRecorder(path, max_bytes=..., keep=N)`` bounds
an hours-long soak's JSONL growth — the file rotates to ``path.1..path.N``
between whole-line writes under the sink lock, so a rotated set never
contains a torn JSON line.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import time
from dataclasses import dataclass
from typing import Any, Iterator

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs import flight as _flight

# Longest wire context accepted back off the wire: contexts are
# observability, never load-bearing, so an absurd one is dropped rather
# than stored.
_MAX_WIRE_CTX = 256


def version_trace(base_version: int) -> str:
    """The lineage trace id for work rooted at global model version
    ``base_version``: a client training on the version-``B`` broadcast, the
    flush publishing ``B+1``, the swap installing it and the first batch
    served from it all join ``fedtr-vB`` — one trace id across processes,
    derived from a number every party already carries in-band."""
    return f"fedtr-v{int(base_version)}"


@dataclass(frozen=True)
class TraceContext:
    """A wire-safe span reference: the trace id plus a sender-chosen key
    unique within that trace (NOT the recorder's integer span id, which is
    a per-process sequence and ambiguous across files)."""

    trace: str
    key: str

    def to_wire(self) -> str:
        return f"{self.trace}#{self.key}"

    @classmethod
    def from_wire(cls, wire: Any) -> "TraceContext | None":
        """Parse a wire context; ``None`` for anything malformed (missing,
        wrong type, no separator, empty halves, oversized) — the dropped-
        context contract: degrade to parentless, never raise."""
        if not isinstance(wire, str) or not wire or len(wire) > _MAX_WIRE_CTX:
            return None
        trace, sep, key = wire.partition("#")
        if not sep or not trace or not key:
            return None
        return cls(trace=trace, key=key)


def flush_context(version: int) -> TraceContext:
    """The DETERMINISTIC context of the flush that published global model
    ``version``: computable by anyone who knows the version (the serve
    plane links swap→flush from the statefile's version counter alone —
    nothing extra rides the statefile, so its snapshot bytes stay a pure
    function of protocol state)."""
    return TraceContext(version_trace(version - 1), f"flush:v{int(version)}")


class SpanHandle:
    """What a ``with span(...)`` body sees: the ids to thread to children."""

    __slots__ = ("span_id", "trace", "attrs")

    def __init__(self, span_id: int, trace: str | None):
        self.span_id = span_id
        self.trace = trace
        self.attrs: dict[str, Any] = {}

    def set(self, **attrs: Any) -> None:
        """Attach attributes discovered mid-span (e.g. the model version a
        batch was answered from)."""
        self.attrs.update(attrs)


class SpanRecorder:
    """Append-only JSONL span sink; thread-safe.

    ``max_bytes`` arms size-based rotation (``keep`` old files retained as
    ``path.1`` .. ``path.keep``, newest first): an hours-long soak appends
    to a BOUNDED set instead of one unbounded JSONL. Rotation happens
    between whole-line writes under the sink lock, so no file in the set
    ever holds a torn JSON line (test-pinned). File-object sinks never
    rotate."""

    def __init__(
        self,
        path: str | os.PathLike | io.TextIOBase,
        *,
        max_bytes: int | None = None,
        keep: int = 3,
    ):
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError(f"max_bytes must be positive, got {max_bytes}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.max_bytes = max_bytes
        self.keep = keep
        self._path: str | None = None
        if isinstance(path, io.TextIOBase):
            self._f = path
            self._owns = False
        else:
            p = os.fspath(path)
            parent = os.path.dirname(os.path.abspath(p))
            os.makedirs(parent, exist_ok=True)
            self._f = open(p, "a", encoding="utf-8")
            self._owns = True
            self._path = p
        self._bytes = (
            os.path.getsize(self._path)
            if self._path is not None and os.path.exists(self._path)
            else 0
        )
        self._lock = make_lock("obs.spans.sink")
        self._t0 = time.monotonic()
        self._seq = 0

    def _rotate_locked(self) -> None:
        """Shift path.(keep-1)→path.keep … path→path.1 and reopen. Caller
        holds the sink lock; writes only ever happen between whole lines,
        so every file in the rotated set is line-complete."""
        assert self._path is not None
        self._f.close()
        for i in range(self.keep - 1, 0, -1):
            src = f"{self._path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self._path}.{i + 1}")
        os.replace(self._path, f"{self._path}.1")
        self._f = open(self._path, "a", encoding="utf-8")
        self._bytes = 0

    def _next_id(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        trace: str | None = None,
        parent: int | None = None,
        **attrs: Any,
    ) -> Iterator[SpanHandle]:
        handle = SpanHandle(self._next_id(), trace)
        t_start = time.monotonic()
        try:
            yield handle
        finally:
            dur = time.monotonic() - t_start
            record: dict[str, Any] = {
                "name": name,
                "trace": trace,
                "span": handle.span_id,
                "parent": parent,
                "t": round(t_start - self._t0, 6),
                "dur_s": round(dur, 6),
                # Interval math above is monotonic; the wall clock is the
                # display-only "ts" field (obs JSONL convention).
                # fedlint: disable=DET001 -- human-readable record timestamp
                "ts": time.time(),
            }
            for k, v in attrs.items():
                record[k] = v
            for k, v in handle.attrs.items():
                record[k] = v
            line = json.dumps(record, sort_keys=True, default=str)
            with self._lock:
                if (
                    self._owns
                    and self.max_bytes is not None
                    and self._bytes > 0
                    and self._bytes + len(line) + 1 > self.max_bytes
                ):
                    self._rotate_locked()
                self._f.write(line + "\n")
                self._f.flush()
                self._bytes += len(line.encode("utf-8")) + 1
            # Flight-recorder tee (round 16): the bounded in-memory ring
            # gets a COMPACT event per span (name/trace/duration + the
            # cross-process context when one was attached) — one global
            # read when no ring is installed.
            _flight.note(
                "span",
                name=name,
                trace=trace,
                dur_s=record["dur_s"],
                ctx=record.get("ctx"),
            )

    def close(self) -> None:
        if self._owns:
            with self._lock:
                self._f.close()

    def __enter__(self) -> "SpanRecorder":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# ---- the module-level recorder (sanitizer idiom: zero-cost when off) ----

_recorder: SpanRecorder | None = None
_recorder_lock = make_lock("obs.spans.install")


def install(
    path: str | os.PathLike | io.TextIOBase,
    *,
    max_bytes: int | None = None,
    keep: int = 3,
) -> SpanRecorder:
    """Install the process span recorder; returns it. Replacing an existing
    recorder closes the old one. ``max_bytes``/``keep`` arm size-based
    rotation (see :class:`SpanRecorder`)."""
    global _recorder
    rec = SpanRecorder(path, max_bytes=max_bytes, keep=keep)
    with _recorder_lock:
        old, _recorder = _recorder, rec
    if old is not None:
        old.close()
    return rec


def uninstall() -> None:
    global _recorder
    with _recorder_lock:
        old, _recorder = _recorder, None
    if old is not None:
        old.close()


def current() -> SpanRecorder | None:
    return _recorder


@contextlib.contextmanager
def span(
    name: str,
    *,
    trace: str | None = None,
    parent: int | None = None,
    **attrs: Any,
) -> Iterator[SpanHandle | None]:
    """Record ``name`` against the installed recorder; a no-op (yielding
    ``None``) when none is installed — instrumentation sites never branch.

    When only the flight ring is installed (tracing off), the span still
    feeds the ring a compact timed event — "every plane feeds the flight
    recorder for free" — at the cost of two global reads and one deque
    append."""
    rec = _recorder
    if rec is not None:
        with rec.span(name, trace=trace, parent=parent, **attrs) as handle:
            yield handle
        return
    if _flight.current() is None:
        yield None
        return
    t_start = time.monotonic()
    handle = SpanHandle(0, trace)
    try:
        yield handle
    finally:
        _flight.note(
            "span",
            name=name,
            trace=trace,
            dur_s=round(time.monotonic() - t_start, 6),
            ctx=attrs.get("ctx") or handle.attrs.get("ctx"),
        )


def span_files(path: str | os.PathLike) -> list[str]:
    """The rotated set behind ``path``, oldest first (``path.N`` … ``path``)
    — what a stitcher should read so a chain is never cut by a rotation."""
    p = os.fspath(path)
    out: list[str] = []
    i = 1
    rotated: list[str] = []
    while os.path.exists(f"{p}.{i}"):
        rotated.append(f"{p}.{i}")
        i += 1
    out.extend(reversed(rotated))
    if os.path.exists(p):
        out.append(p)
    return out


def read_spans(path: str | os.PathLike, name: str | None = None) -> list[dict]:
    """Load a span JSONL, optionally filtered by span name."""
    out = []
    with open(os.fspath(path), encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if name is None or rec.get("name") == name:
                out.append(rec)
    return out
