"""Prometheus ``/metrics`` exposition over a stdlib HTTP server thread.

``MetricsExporter(registry)`` binds a ``ThreadingHTTPServer`` (port 0 =
ephemeral, like every other harness-facing port in the repo), serves the
registry's text-format v0.0.4 exposition at ``GET /metrics`` (anything else
is 404; ``/healthz`` answers a small JSON liveness body — registry family
count, uptime, spans-installed flag, git describe when available — so a
load balancer can tell "up" from "warm"), and shuts down cleanly. No third-party client library: the text format is ~20 lines to
write deterministically (``registry.exposition()``) and ~40 to parse back
(:func:`parse_prometheus_text`), and the stdlib server is one daemon thread
— the same footprint discipline as the hand-bound gRPC service.

:func:`parse_prometheus_text` / :func:`scrape` close the loop: the
round-trip (expose -> HTTP -> parse -> same numbers) is test-pinned, the
chaos storm drill reads its A/B rates through a real scrape instead of
hand-counting, and the soak audits itself through its own endpoint.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from fedcrack_tpu.obs.registry import REGISTRY, MetricsRegistry

log = logging.getLogger("fedcrack.obs.promexp")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
HEALTH_CONTENT_TYPE = "application/json; charset=utf-8"

_GIT_DESCRIBE: list[str | None] = []  # lazy one-shot cache ([] = not asked yet)


def git_describe() -> str | None:
    """``git describe --always --dirty`` of the installed tree, cached
    after the first call; None outside a git checkout (deployed wheels) —
    the /healthz body then simply omits a build id."""
    if not _GIT_DESCRIBE:
        describe: str | None = None
        try:
            import subprocess

            out = subprocess.run(
                ["git", "describe", "--always", "--dirty"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True,
                timeout=5,
            )
            if out.returncode == 0:
                describe = out.stdout.decode("utf-8", "replace").strip() or None
        except Exception:
            describe = None
        _GIT_DESCRIBE.append(describe)
    return _GIT_DESCRIBE[0]


class MetricsExporter:
    """One daemon-threaded HTTP endpoint over one registry."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.registry = registry if registry is not None else REGISTRY
        self._host = host
        self._port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.bound_port: int | None = None

    def start(self) -> int:
        """Bind and serve; returns the bound port (ephemeral when port=0)."""
        if self._httpd is not None:
            assert self.bound_port is not None
            return self.bound_port
        registry = self.registry
        t_started = time.monotonic()
        # Resolved ONCE at start, off the request path: a liveness probe
        # must never block on a subprocess (git can hang on a network
        # filesystem for longer than a load balancer's timeout).
        git_id = git_describe()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = registry.exposition().encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/healthz":
                    # JSON body (round 16) so a load balancer — and the
                    # soak — can tell "up" from "warm": family count > 0
                    # means the planes have instrumented, spans_installed
                    # means traces are being recorded.
                    from fedcrack_tpu.obs import spans as _spans

                    payload = {
                        "status": "ok",
                        "families": len(registry.families()),
                        "uptime_seconds": round(
                            time.monotonic() - t_started, 3
                        ),
                        "spans_installed": _spans.current() is not None,
                        "git": git_id,
                    }
                    body = (
                        json.dumps(payload, sort_keys=True) + "\n"
                    ).encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type", HEALTH_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404, "only /metrics and /healthz live here")

            def log_message(self, fmt: str, *args: Any) -> None:
                log.debug("metrics-http %s", fmt % args)

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self.bound_port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True,
        )
        self._thread.start()
        return self.bound_port

    @property
    def url(self) -> str:
        if self.bound_port is None:
            raise RuntimeError("exporter not started")
        return f"http://{self._host}:{self.bound_port}/metrics"

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._httpd = None
        self._thread = None

    def __enter__(self) -> "MetricsExporter":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


def start_exporter(
    port: int, registry: MetricsRegistry | None = None, host: str = "127.0.0.1"
) -> MetricsExporter | None:
    """The ``--metrics-port`` entry shared by server.py, the serve plane and
    the tools: 0/None disables (returns None); ``-1`` binds an ephemeral
    port (harnesses read ``exporter.bound_port``); a positive port binds it."""
    if not port:
        return None
    port = int(port)
    exporter = MetricsExporter(
        registry, host=host, port=0 if port < 0 else port
    )
    bound = exporter.start()
    log.info("serving /metrics on http://%s:%d/metrics", host, bound)
    return exporter


def _unescape_help(text: str) -> str:
    """Decode ``\\\\`` and ``\\n`` in ONE left-to-right pass — sequential
    ``str.replace`` calls mis-decode a literal backslash followed by 'n'
    (``\\\\n`` would first match as ``\\n``)."""
    out: list[str] = []
    i = 0
    while i < len(text):
        ch = text[i]
        if ch == "\\" and i + 1 < len(text):
            nxt = text[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_number(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    return float(text)


def _parse_labels(body: str) -> tuple[tuple[str, str], ...]:
    """``a="x",b="y"`` -> (("a","x"), ("b","y")) with escape handling."""
    pairs: list[tuple[str, str]] = []
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        name = body[i:eq].strip().lstrip(",").strip()
        assert body[eq + 1] == '"', f"unquoted label value near {body[eq:]!r}"
        j = eq + 2
        out: list[str] = []
        while True:
            ch = body[j]
            if ch == "\\":
                nxt = body[j + 1]
                out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
                j += 2
            elif ch == '"':
                j += 1
                break
            else:
                out.append(ch)
                j += 1
        pairs.append((name, "".join(out)))
        i = j
        while i < len(body) and body[i] in ", ":
            i += 1
    return tuple(pairs)


def parse_prometheus_text(text: str) -> dict:
    """Parse text-format v0.0.4 into
    ``{metric: {"type": ..., "help": ..., "samples": {labels_tuple: value}}}``
    where ``labels_tuple`` is the sorted ``(name, value)`` pair tuple and
    histogram series appear under their ``_bucket``/``_sum``/``_count``
    sample names (grouped back onto the base metric). Raises ``ValueError``
    on any line it cannot account for — the round-trip test treats an
    unparseable exposition as a failure, not a skip."""
    metrics: dict[str, dict] = {}
    types: dict[str, str] = {}
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            metrics.setdefault(
                name, {"type": None, "help": "", "samples": {}}
            )["help"] = _unescape_help(help_text)
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            metrics.setdefault(name, {"type": None, "help": "", "samples": {}})
            metrics[name]["type"] = kind.strip()
            types[name] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        try:
            if "{" in line:
                name = line[: line.index("{")]
                body = line[line.index("{") + 1 : line.rindex("}")]
                value_txt = line[line.rindex("}") + 1 :].strip().split()[0]
                labels = tuple(sorted(_parse_labels(body)))
            else:
                name, value_txt = line.split()[:2]
                labels = ()
            value = _parse_number(value_txt)
        except (ValueError, IndexError, AssertionError) as e:
            raise ValueError(f"unparseable exposition line {lineno}: {raw!r}") from e
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stem = name[: -len(suffix)] if name.endswith(suffix) else None
            if stem is not None and types.get(stem) == "histogram":
                base = stem
                labels = tuple(sorted(labels + (("__sample__", suffix),)))
                break
        metrics.setdefault(base, {"type": None, "help": "", "samples": {}})
        metrics[base]["samples"][labels] = value
    return metrics


def sample_value(
    parsed: dict, name: str, labels: dict[str, str] | None = None
) -> float | None:
    """One sample out of a :func:`parse_prometheus_text` result; None when
    the metric or label set is absent."""
    fam = parsed.get(name)
    if fam is None:
        return None
    key = tuple(sorted((labels or {}).items()))
    return fam["samples"].get(key)


def scrape(url: str, timeout_s: float = 5.0) -> dict:
    """HTTP GET + parse — the loop the soak and the storm drill close."""
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        body = resp.read().decode("utf-8")
    return parse_prometheus_text(body)
