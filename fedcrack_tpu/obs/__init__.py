"""Observability: structured metrics, wall-clock timing, profiler hooks.

The reference's observability is ``print`` banners (fl_server.py:111,119,126)
plus a per-round TensorBoard callback whose upload path is commented out
(client_fit_model.py:153-154, fl_client.py:110-118; SURVEY.md §5.1/§5.5).
Here both planes emit structured JSONL records — per-round loss/IoU,
wall-clock, and bytes moved on the control plane — and ``jax.profiler``
traces can wrap any training span for TPU timeline inspection.

Round 15 adds the live telemetry plane: a thread-safe metric registry with
one catalog across all planes (``registry``), Prometheus text-format
exposition over HTTP (``promexp``), correlated trace spans (``spans``) and
RSS/device-memory leak sentries (``sentries``).

Round 16 makes it an ops plane that notices: cross-process distributed
tracing (wire-safe ``TraceContext`` + version-lineage trace ids in
``spans``, stitched by ``tools/trace_stitch``), a crash flight recorder
(``flight`` — a bounded ring every plane feeds for free, dumped on
exceptions/SIGUSR2/failed audits), and the SLO watchdog (``watchdog`` —
declarative thresholds over the registry with a breach → flight-dump →
exit-code contract).
"""

from fedcrack_tpu.obs.flops import (
    device_peak_flops,
    mfu,
    resunet_forward_flops,
    train_step_flops,
)
from fedcrack_tpu.obs.metrics import (
    MetricsLogger,
    profiler_trace,
    read_metrics,
    stopwatch,
)
from fedcrack_tpu.obs.promexp import (
    MetricsExporter,
    parse_prometheus_text,
    scrape,
    start_exporter,
)
from fedcrack_tpu.obs.registry import REGISTRY, MetricsRegistry
from fedcrack_tpu.obs.sentries import LeakError, LeakSentry
from fedcrack_tpu.obs.spans import SpanRecorder, read_spans, span
from fedcrack_tpu.obs.tb import SummaryWriter, read_histograms, read_scalars

__all__ = [
    "LeakError",
    "LeakSentry",
    "MetricsExporter",
    "MetricsLogger",
    "MetricsRegistry",
    "REGISTRY",
    "SpanRecorder",
    "SummaryWriter",
    "parse_prometheus_text",
    "read_spans",
    "scrape",
    "span",
    "start_exporter",
    "read_histograms",
    "device_peak_flops",
    "mfu",
    "profiler_trace",
    "read_metrics",
    "read_scalars",
    "resunet_forward_flops",
    "stopwatch",
    "train_step_flops",
]
