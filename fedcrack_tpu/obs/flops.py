"""Analytic FLOPs model of the crack U-Net + MFU accounting.

Round 1 measured wall-clock only; a per-step time is uninterpretable without
knowing how much of the chip's peak it represents. This module walks the exact
topology of SURVEY.md §2.3 (reference: client_fit_model.py:92-150) and counts
matmul-equivalent FLOPs — the convolutions, which carry >99% of the arithmetic
and are the only ops that land on the MXU. Elementwise work (BN, ReLU,
residual adds, sigmoid/loss) is O(HW·C) against the convs' O(HW·C²·K²) and is
deliberately excluded; the analytic total is cross-checked against XLA's own
HLO cost analysis in tests/test_flops.py.

MFU is reported against the chip's **bf16 MXU peak** for both dtypes (the
standard convention — float32 runs the same systolic array via multi-pass,
so "fraction of the machine's ceiling" stays comparable across dtypes).

CANONICAL FLOPs, by design: this model deliberately ignores
``ModelConfig.stem_layout`` / ``res_layout``. The layout transforms
(models/resunet.py) re-express the same math with zero-extended kernels —
e.g. the packed residual projection nominally multiplies 4x the input
channels, 3/4 of them structural zeros — and counting those zero MACs
would inflate "achieved FLOP/s" for the transformed variants. Every
layout is charged the REFERENCE topology's FLOPs, so an A/B's MFU column
moves only when wall-clock does (the honesty requirement of bench.py's
layout A/B; pinned by tests/test_flops.py).

The same discipline covers the round-20 kernel planes
(``ServeConfig.kernel_plane``): a fused-int8 or fp8 forward changes bytes
moved and bit-width per MAC, not canonical MACs — every plane is charged
the reference topology's FLOPs so bf16-vs-int8-vs-fp8 MFU columns stay
comparable. Which plane actually answered is exported separately as the
``serve_kernel_plane_info`` labeled gauge (:func:`export_kernel_plane`).
"""

from __future__ import annotations

import os

import jax

from fedcrack_tpu.configs import ModelConfig

# One SGD step ≈ forward + backward; for conv stacks the backward pass is two
# conv-shaped passes (grad wrt activations + grad wrt kernels), so train-step
# FLOPs ≈ 3x forward. Optimizer/BN/loss work is elementwise and excluded.
TRAIN_STEP_FLOPS_MULTIPLIER = 3.0

# Per-jax.Device dense peak (TFLOP/s, bf16 on the MXU), keyed by substrings
# of jax.Device.device_kind. On v4+/v5e/v6e JAX exposes one device per chip,
# so these are per-chip numbers. On v2/v3 JAX exposes one device per CORE
# (two cores per chip), so those rows are per-core (half the often-quoted
# per-chip figure) to keep mfu() honest at jax.Device granularity. Override
# with FEDCRACK_PEAK_TFLOPS for kinds not listed (e.g. new hardware or a
# tunnel that reports an opaque kind).
_PEAK_TFLOPS_BF16 = (
    ("v6e", 918.0),
    ("v6 lite", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v5lite", 197.0),
    ("v4i", 138.0),
    ("v4", 275.0),
    ("v3", 61.5),   # per core: 123 TFLOP/s per chip / 2 cores
    ("v2", 22.5),   # per core: 45 TFLOP/s per chip / 2 cores
)


def _conv_flops(out_hw: int, c_in: int, c_out: int, k: int) -> float:
    """Dense KxK conv at SAME padding: 2 FLOPs (mul+add) per MAC."""
    return 2.0 * out_hw * out_hw * c_out * (k * k * c_in)


def resunet_forward_flops(config: ModelConfig | None = None, batch_size: int = 1) -> float:
    """Forward-pass FLOPs for one batch through the residual U-Net.

    Mirrors models/resunet.py layer by layer: stem conv /2; encoder blocks
    (depthwise 3x3 + pointwise 1x1) x2 + pool /2 + strided 1x1 residual;
    decoder blocks (3x3 transpose-conv, stride 1 == plain conv) x2 +
    low-resolution 1x1 residual + single upsample x2; 1x1 head.

    Layout flags (stem_layout/res_layout) are intentionally NOT consulted:
    transformed variants are charged the same canonical FLOPs (module
    docstring).
    """
    cfg = config or ModelConfig()
    s = cfg.img_size // 2  # after the stride-2 stem
    c = cfg.stem_features
    total = _conv_flops(s, cfg.in_channels, c, 3)

    for feat in cfg.encoder_features:
        # SeparableConv = depthwise 3x3 (per-channel) + pointwise 1x1.
        total += 2.0 * s * s * c * 9  # depthwise on c channels
        total += _conv_flops(s, c, feat, 1)  # pointwise c -> feat
        total += 2.0 * s * s * feat * 9
        total += _conv_flops(s, feat, feat, 1)
        s //= 2  # MaxPool(3x3, stride 2)
        # Residual: 1x1 stride-2 conv from the block input (c channels).
        total += _conv_flops(s, c, feat, 1)
        c = feat

    for feat in cfg.decoder_features:
        # Stride-1 ConvTranspose(3x3, SAME) costs the same as a 3x3 conv.
        total += _conv_flops(s, c, feat, 3)
        total += _conv_flops(s, feat, feat, 3)
        # Residual 1x1 conv runs at the LOW resolution: the model fuses
        # conv + add before the single upsample (resunet.py's decoder — a 1x1
        # conv commutes with nearest upsampling). Counting it post-upsample
        # would overcount executed FLOPs 4x on this branch and inflate MFU.
        total += _conv_flops(s, c, feat, 1)
        s *= 2  # UpSampling2D(2)
        c = feat

    # The head's 1x1 conv is ALSO deferred past the final upsample (same
    # commute, resunet.py): it executes at img_size/2, so count it there.
    total += _conv_flops(s // 2, c, cfg.num_classes, 1)
    return total * float(batch_size)


def train_step_flops(config: ModelConfig | None = None, batch_size: int = 1) -> float:
    """FLOPs for one SGD step (forward + backward) at the given batch size."""
    return TRAIN_STEP_FLOPS_MULTIPLIER * resunet_forward_flops(config, batch_size)


def device_peak_flops(device: jax.Device | None = None) -> float | None:
    """Per-``jax.Device`` bf16 dense peak in FLOP/s, or None when the kind
    is unknown. One device = one chip on v4+/v5e/v6e, one CORE on v2/v3
    (see the table above), so dividing achieved FLOP/s on one device by
    this is always apples-to-apples.

    ``FEDCRACK_PEAK_TFLOPS`` overrides (useful behind device tunnels whose
    ``device_kind`` string is opaque).
    """
    env = os.environ.get("FEDCRACK_PEAK_TFLOPS", "")
    if env:
        return float(env) * 1e12
    if device is None:
        device = jax.devices()[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    for needle, tflops in _PEAK_TFLOPS_BF16:
        if needle in kind:
            return tflops * 1e12
    return None


def mfu(step_time_s: float, flops_per_step: float, device: jax.Device | None = None) -> float | None:
    """Model FLOPs utilization: achieved FLOP/s over the chip's bf16 peak.

    None when the peak is unknown (non-TPU host, unrecognized device kind).
    """
    peak = device_peak_flops(device)
    if peak is None or step_time_s <= 0.0:
        return None
    return (flops_per_step / step_time_s) / peak


def export_kernel_plane(
    effective: str, *, requested: str | None = None, registry=None
) -> None:
    """Export which kernel plane answers quantized traffic as the
    ``serve_kernel_plane_info`` labeled gauge (Prometheus info-metric idiom:
    constant 1, state in the labels). The ``requested`` label keeps an
    fp8-request-degraded-to-reference visible in a scrape; earlier states'
    series drop to 0 so exactly one ``plane`` reads 1."""
    from fedcrack_tpu.obs.registry import REGISTRY

    reg = registry if registry is not None else REGISTRY
    fam = reg.gauge(
        "serve_kernel_plane_info",
        "which quantized-predict kernel plane is compiled in (constant-1 "
        "info gauge; plane=effective program body, requested=the "
        "ServeConfig ask — they differ when fp8 degraded to the r17 "
        "reference path on a backend without fp8 support)",
        labels=("plane", "requested"),
    )
    req = requested if requested is not None else effective
    for key, child in fam._series():
        if key != (effective, req):
            child.set(0)
    fam.labels(plane=effective, requested=req).set(1)
