"""SLO watchdog — declarative thresholds over the metric registry, enforced.

Round 15 gave every plane a live metric catalog; auditing it still meant
hand-coded snippets per harness. This module turns the catalog into
machine-checked SLOs: a rule set (shipped as JSON in ``configs/``, or the
built-in :data:`DEFAULT_RULES`) is evaluated over the registry's own
Prometheus exposition — the SAME text a dashboard would scrape, so the
watchdog can never disagree with what operators see — and a breach follows
the contract the ROADMAP's ops plane demands:

    breach → flight-recorder dump → nonzero exit.

Rule shape (one JSON object per rule)::

    {"name": "serve_p95",  "metric": "serve_request_seconds",
     "stat": "p95", "op": "<=", "threshold": 5.0}
    {"name": "updates_floor", "metric": "fed_updates_total",
     "labels": {"result": "accepted"}, "stat": "rate", "op": ">=",
     "threshold": 0.01}

``stat`` selects how the sample(s) reduce to one number:

- ``value`` — the sample (samples matching the ``labels`` subset are
  summed, so a label-free rule pools a labeled family's children);
- ``rate`` — per-second delta of a counter between this evaluation and the
  previous one (indeterminate on the first evaluation and under
  ``min_elapsed_s``);
- ``p50``/``p95``/``p99`` — histogram quantile from the cumulative buckets
  (children matching the ``labels`` subset are pooled; the answer
  interpolates linearly inside the winning bucket, capped at the highest
  finite bound — the Prometheus ``histogram_quantile`` convention);
- ``count``/``sum`` — a histogram's ``_count``/``_sum``.

A rule whose metric is absent is *indeterminate* (skipped) by default;
``"on_missing": "breach"`` makes absence itself a breach (for liveness
rules where silence is the failure). ``"consecutive": N`` is the
Prometheus ``for:`` clause's evaluation-count analog: the condition must
fail N evaluations IN A ROW before a breach is recorded — rate floors over
a bursty plane (a straggler storm gust, a mid-soak server kill→restart)
legitimately read zero for a window or two, and an SLO that pages on every
blip is an SLO nobody arms. ``audit()`` reduces a run to the
contract the soak/bench artifacts embed: every rule evaluated at least
once determinately, zero breaches, ``clean`` bool. Exit-code contract:
harnesses exit :data:`BREACH_EXIT` on any breach (distinct from the
generic audit failure's 1).
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time
from dataclasses import dataclass, field

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs import flight
from fedcrack_tpu.obs.promexp import parse_prometheus_text, scrape
from fedcrack_tpu.obs.registry import REGISTRY, MetricsRegistry

# The breach → dump → exit contract's exit code (CI greps for it; distinct
# from 1 = generic audit failure, 2 = usage error).
BREACH_EXIT = 3

_OPS = {
    "<=": lambda v, t: v <= t,
    "<": lambda v, t: v < t,
    ">=": lambda v, t: v >= t,
    ">": lambda v, t: v > t,
    "==": lambda v, t: v == t,
    "!=": lambda v, t: v != t,
}
_STATS = ("value", "rate", "p50", "p95", "p99", "count", "sum")


@dataclass(frozen=True)
class SloRule:
    """One declarative threshold over one metric."""

    name: str
    metric: str
    op: str
    threshold: float
    stat: str = "value"
    labels: dict = field(default_factory=dict)
    on_missing: str = "skip"        # "skip" (indeterminate) | "breach"
    min_elapsed_s: float = 1.0      # rate only: shortest meaningful window
    consecutive: int = 1            # failing evals in a row before a breach

    def __post_init__(self) -> None:
        if not self.name or not self.metric:
            raise ValueError("rule needs a name and a metric")
        if self.op not in _OPS:
            raise ValueError(f"rule {self.name!r}: unknown op {self.op!r}")
        if self.stat not in _STATS:
            raise ValueError(f"rule {self.name!r}: unknown stat {self.stat!r}")
        if self.on_missing not in ("skip", "breach"):
            raise ValueError(
                f"rule {self.name!r}: on_missing must be 'skip' or 'breach'"
            )
        if not math.isfinite(float(self.threshold)):
            raise ValueError(f"rule {self.name!r}: non-finite threshold")
        if self.consecutive < 1:
            raise ValueError(f"rule {self.name!r}: consecutive must be >= 1")


def load_rules(path: str) -> list[SloRule]:
    """Parse a ``configs/slo_*.json`` rule file: ``{"rules": [...]}``.
    Every malformed rule is a loud ValueError — a watchdog armed with a
    typo'd rule set would audit nothing while looking green."""
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    rules_raw = payload.get("rules")
    if not isinstance(rules_raw, list) or not rules_raw:
        raise ValueError(f"{path}: expected a non-empty 'rules' list")
    out = []
    for i, raw in enumerate(rules_raw):
        if not isinstance(raw, dict):
            raise ValueError(f"{path}: rules[{i}] is not an object")
        known = {
            "name", "metric", "op", "threshold", "stat", "labels",
            "on_missing", "min_elapsed_s", "consecutive",
        }
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"{path}: rules[{i}] unknown keys {sorted(unknown)}")
        out.append(SloRule(**raw))
    return out


def default_rules() -> list[SloRule]:
    """The built-in rule set (mirrored by ``configs/slo_default.json`` —
    test-pinned equal): the ROADMAP's SLO list shaped for the soak."""
    return [
        SloRule(
            name="serve_p95_seconds", metric="serve_request_seconds",
            stat="p95", op="<=", threshold=5.0,
        ),
        SloRule(
            name="staleness_p99_versions", metric="fed_update_staleness_versions",
            stat="p99", op="<=", threshold=32.0,
        ),
        SloRule(
            # 1 s windows × 4 consecutive failures = only ~4 s of SUSTAINED
            # starvation pages. A storm gust's empty window, or the soak's
            # deliberate server kill→restart (restart ~0.3-1 s + client
            # reconnect backoff ~1-2 s under load), recovers well inside
            # that; measured outages reached ~2 s of zero-rate windows on a
            # loaded CI host.
            name="updates_per_sec_floor", metric="fed_updates_total",
            labels={"result": "accepted"}, stat="rate", op=">=",
            threshold=0.01, min_elapsed_s=1.0, consecutive=4,
        ),
        SloRule(
            # <= 0, not == 0: the gauge reports -1 on jax builds that hide
            # the jit cache (unknown must not read as a breach).
            name="zero_serve_recompiles", metric="serve_recompiles_total",
            op="<=", threshold=0.0,
        ),
        SloRule(
            # Rate, not absolute: the process registry is shared (a test
            # run or bench session accumulates history before the watchdog
            # arms), so the SLO is "no NEW loud failures on my watch".
            name="zero_failed_requests", metric="serve_failed_requests_total",
            stat="rate", op="<=", threshold=0.0,
        ),
        SloRule(
            # Leak-sentry watermark ceiling (the sentries' growth-since-mark
            # audit stays the sharp check; this is the absolute backstop).
            name="rss_watermark_ceiling", metric="process_resident_watermark_bytes",
            op="<=", threshold=16.0 * 1024**3,
        ),
    ]


def _match(labels_key: tuple, want: dict) -> bool:
    """Does a sample's sorted (name, value) label tuple satisfy the rule's
    label subset?"""
    have = dict(labels_key)
    return all(have.get(k) == str(v) for k, v in want.items())


def _histogram_quantile(fam: dict, want: dict, q: float) -> float | None:
    """Pooled histogram quantile over every child matching the label
    subset: cumulative per-``le`` counts summed across children, then
    linear interpolation inside the winning bucket (highest finite bound
    for the +Inf bucket — the ``histogram_quantile`` convention)."""
    per_le: dict[float, float] = {}
    for key, value in fam["samples"].items():
        have = dict(key)
        if have.get("__sample__") != "_bucket":
            continue
        rest = {k: v for k, v in key if k not in ("__sample__", "le")}
        if not _match(tuple(sorted(rest.items())), want):
            continue
        le = math.inf if have["le"] == "+Inf" else float(have["le"])
        per_le[le] = per_le.get(le, 0.0) + value
    if not per_le:
        return None
    bounds = sorted(per_le)
    total = per_le[bounds[-1]]
    if total <= 0:
        return None
    target = (q / 100.0) * total
    prev_ub, prev_cum = 0.0, 0.0
    highest_finite = max((b for b in bounds if math.isfinite(b)), default=0.0)
    for ub in bounds:
        cum = per_le[ub]
        if cum >= target:
            if not math.isfinite(ub):
                return highest_finite
            if cum == prev_cum:
                return ub
            return prev_ub + (ub - prev_ub) * (target - prev_cum) / (cum - prev_cum)
        prev_ub, prev_cum = (ub if math.isfinite(ub) else prev_ub), cum
    return highest_finite


def _reduce(rule: SloRule, parsed: dict) -> float | None:
    """One rule's current value from a parsed exposition; None = absent."""
    fam = parsed.get(rule.metric)
    if fam is None:
        return None
    if rule.stat in ("p50", "p95", "p99"):
        return _histogram_quantile(fam, rule.labels, float(rule.stat[1:]))
    if rule.stat in ("count", "sum"):
        suffix = f"_{rule.stat}"
        total, seen = 0.0, False
        for key, value in fam["samples"].items():
            have = dict(key)
            if have.get("__sample__") != suffix:
                continue
            rest = {k: v for k, v in key if k != "__sample__"}
            if _match(tuple(sorted(rest.items())), rule.labels):
                total += value
                seen = True
        return total if seen else None
    # "value" / "rate": plain samples (children matching the subset sum).
    total, seen = 0.0, False
    for key, value in fam["samples"].items():
        if any(k == "__sample__" for k, _ in key):
            continue
        if _match(key, rule.labels):
            total += value
            seen = True
    return total if seen else None


class Watchdog:
    """Evaluate a rule set repeatedly; accumulate the audit."""

    def __init__(
        self,
        rules: list[SloRule] | None = None,
        registry: MetricsRegistry | None = None,
    ):
        self.rules = list(rules) if rules is not None else default_rules()
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self.registry = registry if registry is not None else REGISTRY
        self._lock = make_lock("obs.watchdog.eval")
        self._evaluations = 0
        self._determinate: dict[str, int] = {r.name: 0 for r in self.rules}
        self._fail_streak: dict[str, int] = {}
        self._last_counter: dict[str, tuple[float, float]] = {}
        self.breaches: list[dict] = []
        self._dumped = False

    def evaluate(self, parsed: dict | None = None) -> dict:
        """One pass over every rule. ``parsed`` is a
        :func:`parse_prometheus_text` result (e.g. from a real scrape);
        None evaluates the registry's own exposition. Returns the per-rule
        report and feeds the flight ring the sampled values (the
        metric-sample feed a post-mortem reads)."""
        if parsed is None:
            parsed = parse_prometheus_text(self.registry.exposition())
        now = time.monotonic()
        results = []
        with self._lock:
            self._evaluations += 1
            eval_idx = self._evaluations
            for rule in self.rules:
                streak = self._fail_streak.get(rule.name, 0)
                value = _reduce(rule, parsed)
                if rule.stat == "rate" and value is not None:
                    prev = self._last_counter.get(rule.name)
                    if prev is None:
                        self._last_counter[rule.name] = (value, now)
                        value = None
                    elif now - prev[1] < rule.min_elapsed_s:
                        # Keep the previous anchor: advancing it every
                        # evaluation would shrink every window below
                        # min_elapsed_s and leave the rule permanently
                        # indeterminate.
                        value = None
                    else:
                        rate = (value - prev[0]) / (now - prev[1])
                        self._last_counter[rule.name] = (value, now)
                        value = rate
                if value is None or (
                    isinstance(value, float) and math.isnan(value)
                ):
                    failing = rule.on_missing == "breach"
                    streak = streak + 1 if failing else streak
                    results.append(
                        {
                            "rule": rule.name,
                            "value": None,
                            "ok": False if failing else None,
                            "breach": failing and streak >= rule.consecutive,
                        }
                    )
                else:
                    self._determinate[rule.name] += 1
                    ok = _OPS[rule.op](float(value), float(rule.threshold))
                    streak = 0 if ok else streak + 1
                    results.append(
                        {
                            "rule": rule.name,
                            "value": float(value),
                            "ok": bool(ok),
                            # The `for:`-style clause: only a failure
                            # SUSTAINED for `consecutive` evaluations is a
                            # breach (a single empty rate window is not).
                            "breach": not ok and streak >= rule.consecutive,
                        }
                    )
                self._fail_streak[rule.name] = streak
            new_breaches = [
                {
                    "rule": r["rule"],
                    "value": r["value"],
                    "op": next(
                        x.op for x in self.rules if x.name == r["rule"]
                    ),
                    "threshold": next(
                        x.threshold for x in self.rules if x.name == r["rule"]
                    ),
                    "evaluation": eval_idx,
                }
                for r in results
                if r["breach"]
            ]
            self.breaches.extend(new_breaches[: max(0, 64 - len(self.breaches))])
        flight.note(
            "watchdog.eval",
            evaluation=eval_idx,
            values={r["rule"]: r["value"] for r in results},
            breaches=[b["rule"] for b in new_breaches] or None,
        )
        return {"evaluation": eval_idx, "results": results, "breaches": new_breaches}

    def enforce(self, parsed: dict | None = None) -> dict:
        """evaluate() + the breach contract: the FIRST breaching evaluation
        dumps the flight ring (reason names the rules), once per watchdog."""
        report = self.evaluate(parsed)
        if report["breaches"] and not self._dumped:
            self._dumped = True
            names = sorted({b["rule"] for b in report["breaches"]})
            flight.dump(f"watchdog breach: {', '.join(names)}")
        return report

    def audit(self) -> dict:
        """The run's verdict: the shape ``detail.observability.watchdog``
        embeds and CI gates on."""
        with self._lock:
            never = sorted(
                name for name, n in self._determinate.items() if n == 0
            )
            breaches = list(self.breaches)
            evaluations = self._evaluations
        return {
            "rules_evaluated": len(self.rules),
            "rules": sorted(r.name for r in self.rules),
            "evaluations": evaluations,
            "never_determinate": never,
            "all_rules_evaluated": evaluations > 0 and not never,
            "breaches": breaches,
            "clean": evaluations > 0 and not breaches and not never,
        }


def main(argv=None) -> int:
    """Standalone watchdog over a live ``/metrics`` endpoint:
    ``python -m fedcrack_tpu.obs.watchdog --rules configs/slo_default.json
    --url http://127.0.0.1:9109/metrics --interval 5 --count 12`` — exits
    ``BREACH_EXIT`` on any breach (after the flight dump, when a ring is
    armed), 0 on a clean audit."""
    p = argparse.ArgumentParser(
        prog="python -m fedcrack_tpu.obs.watchdog", description=__doc__
    )
    p.add_argument("--rules", default="", help="JSON rule file; empty = built-ins")
    p.add_argument("--url", required=True, help="the /metrics endpoint to watch")
    p.add_argument("--interval", type=float, default=5.0)
    p.add_argument("--count", type=int, default=2)
    p.add_argument("--flight-dump", default="", help="arm a flight ring dumping here")
    args = p.parse_args(argv)
    rules = load_rules(args.rules) if args.rules else None
    if args.flight_dump:
        flight.install(path=args.flight_dump)
    wd = Watchdog(rules)
    for i in range(max(1, args.count)):
        if i:
            time.sleep(args.interval)
        report = wd.enforce(scrape(args.url))
        for b in report["breaches"]:
            print(f"BREACH {b['rule']}: {b['value']} {b['op']} {b['threshold']} is false")
    audit = wd.audit()
    print(json.dumps(audit, indent=1, sort_keys=True))
    if audit["breaches"]:
        return BREACH_EXIT
    # Not clean without a breach = rules that never went determinate
    # (absent metrics): a configuration/coverage failure, not an SLO one.
    return 0 if audit["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
