"""Crash flight recorder — a bounded in-memory ring every plane feeds for free.

A failed multi-hour soak that arrives as final counters plus a traceback is
undebuggable: the question is always *what happened in the last few
seconds*. This module keeps exactly that — a fixed-capacity ring of recent
events (spans, fed-plane state transitions, chaos fault injections,
watchdog metric samples) that costs one global read per event when no ring
is installed and one deque append when one is, and is dumped to a JSON
artifact when something goes wrong:

- **unhandled exception** — ``sys.excepthook`` and ``threading.excepthook``
  are chained at :func:`install` (the previous hooks still run);
- **SIGUSR2** — an operator can demand a dump from a live, healthy process
  (installed only when the interpreter allows it, i.e. the main thread);
- **explicitly** — a failed soak audit or an SLO-watchdog breach calls
  :func:`dump` with its reason (:mod:`fedcrack_tpu.obs.watchdog` wires the
  breach → dump → exit-code contract).

The dump carries the ring's events (monotonic offsets from install time),
the reason, and a snapshot of the process metric registry's Prometheus
exposition — a red run ships with its last N seconds of history AND the
counters at the instant of death, not just whatever the harness printed.

Feeding is *free* for instrumented code: :func:`fedcrack_tpu.obs.spans.span`
tees every span into the ring (even when no span recorder is installed),
``transport.service.observe_transition`` notes update outcomes and
flushes, ``chaos.plan.FaultPlan.take`` notes every fault it hands out, and
the watchdog notes each evaluation's sampled values. New planes only need
:func:`note`.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.ioutils import atomic_write_bytes

DEFAULT_CAPACITY = 2048


class FlightRecorder:
    """The bounded ring itself; thread-safe, O(1) per event."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, path: str | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.path = path
        self._events: collections.deque = collections.deque(maxlen=self.capacity)
        self._lock = make_lock("obs.flight.ring")
        self._t0 = time.monotonic()
        self._seen = 0
        self.dumps: list[dict] = []

    def note(self, kind: str, **fields: Any) -> None:
        rec = {"kind": kind, "t": round(time.monotonic() - self._t0, 6)}
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            self._events.append(rec)
            self._seen += 1

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def dump(self, reason: str, path: str | None = None) -> str:
        """Write the ring (+ a registry exposition snapshot) as one JSON
        artifact via the atomic writer; returns the path. Never raises —
        a dump failing must not mask the failure being dumped."""
        target = path or self.path or os.path.join(".", "flight_dump.json")
        exposition = ""
        try:
            from fedcrack_tpu.obs.registry import REGISTRY

            exposition = REGISTRY.exposition()
        except Exception:  # the registry must never block a crash dump
            pass
        with self._lock:
            events = list(self._events)
            seen = self._seen
        payload = {
            "reason": reason,
            # Interval math in events is monotonic ("t"); the wall clock is
            # the display-only dump timestamp, per the obs convention.
            # fedlint: disable=DET001 -- human-readable dump timestamp
            "ts": time.time(),
            "capacity": self.capacity,
            "events_seen": seen,
            "events": events,
            "metrics_exposition": exposition,
        }
        try:
            atomic_write_bytes(
                target,
                json.dumps(payload, sort_keys=True, default=str).encode("utf-8"),
            )
        except Exception:
            return target
        self.dumps.append({"reason": reason, "path": target})
        return target


# ---- the module-level ring (sanitizer idiom: zero-cost when off) ----

_ring: FlightRecorder | None = None
_ring_lock = make_lock("obs.flight.install")
_prev_excepthook = None
_prev_threading_hook = None
_prev_sigusr2: Any = None
_hooks_armed = False


def _on_excepthook(exc_type, exc, tb) -> None:
    ring = _ring
    if ring is not None:
        ring.dump(
            "unhandled exception: "
            + "".join(traceback.format_exception_only(exc_type, exc)).strip()
        )
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _on_threading_excepthook(args) -> None:
    ring = _ring
    if ring is not None:
        ring.dump(
            f"unhandled exception in thread {args.thread.name if args.thread else '?'}: "
            + "".join(
                traceback.format_exception_only(args.exc_type, args.exc_value)
            ).strip()
        )
    if _prev_threading_hook is not None:
        _prev_threading_hook(args)


def _on_sigusr2(signum, frame) -> None:
    ring = _ring
    if ring is not None:
        ring.dump("SIGUSR2")
    prev = _prev_sigusr2
    if callable(prev):
        prev(signum, frame)


def install(
    path: str | None = None,
    capacity: int = DEFAULT_CAPACITY,
    hooks: bool = True,
) -> FlightRecorder:
    """Arm the process flight recorder (replacing any existing ring) and,
    with ``hooks``, chain the exception hooks + SIGUSR2 dump trigger.
    ``path`` is where :func:`dump` lands by default."""
    global _ring, _prev_excepthook, _prev_threading_hook, _prev_sigusr2
    global _hooks_armed
    ring = FlightRecorder(capacity=capacity, path=path)
    with _ring_lock:
        _ring = ring
        if hooks and not _hooks_armed:
            _prev_excepthook = sys.excepthook
            sys.excepthook = _on_excepthook
            _prev_threading_hook = threading.excepthook
            threading.excepthook = _on_threading_excepthook
            try:
                _prev_sigusr2 = signal.signal(signal.SIGUSR2, _on_sigusr2)
            except (ValueError, AttributeError, OSError):
                # Not the main thread / no SIGUSR2 on this platform: the
                # exception hooks still arm; the signal trigger is optional.
                _prev_sigusr2 = None
            _hooks_armed = True
    return ring


def uninstall() -> None:
    """Disarm the ring and restore whatever hooks install() replaced."""
    global _ring, _prev_excepthook, _prev_threading_hook, _prev_sigusr2
    global _hooks_armed
    with _ring_lock:
        _ring = None
        if _hooks_armed:
            if _prev_excepthook is not None:
                sys.excepthook = _prev_excepthook
                _prev_excepthook = None
            if _prev_threading_hook is not None:
                threading.excepthook = _prev_threading_hook
                _prev_threading_hook = None
            if _prev_sigusr2 is not None:
                try:
                    signal.signal(signal.SIGUSR2, _prev_sigusr2)
                except (ValueError, AttributeError, OSError):
                    pass
                _prev_sigusr2 = None
            _hooks_armed = False


def current() -> FlightRecorder | None:
    return _ring


def note(kind: str, **fields: Any) -> None:
    """Feed one event into the installed ring; one global read when off —
    instrumentation sites call this unconditionally."""
    ring = _ring
    if ring is not None:
        ring.note(kind, **fields)


def dump(reason: str, path: str | None = None) -> str | None:
    """Dump the installed ring (None when no ring is armed)."""
    ring = _ring
    if ring is None:
        return None
    return ring.dump(reason, path=path)
