"""TensorBoard scalar event files, written without TensorFlow.

The reference emits real TB event files every round via the Keras callback
(reference: client_fit_model.py:153-154) so a human can point TensorBoard at
the log directory. The JSONL metrics sink (obs/metrics.py) is this repo's
structured record of truth, but it is not TB-readable; this module restores
the "open it in TensorBoard" workflow with a ~100-line writer that speaks
the TFRecord + Event-proto wire format directly — no tensorflow import on
the production path (TF is a test-only cross-check here).

Format notes (stable since TF 1.x, verified against TensorBoard 2.20's
event_accumulator in tests):

- A file is a sequence of TFRecords: ``uint64 len | uint32 masked_crc(len)
  | data | uint32 masked_crc(data)``, CRC32C (Castagnoli) with TF's mask
  ``((crc >> 15 | crc << 17) + 0xa282ead8)``. The native runtime's hardware
  CRC32C (fedcrack_tpu.native) does the checksumming.
- Each record is a serialized ``Event`` proto; scalars ride
  ``Event{wall_time(1:double), step(2:int64), summary(5){value(1){
  tag(1:string), simple_value(2:float)}}}``, hand-encoded below (the
  message is tiny and frozen — a protobuf dependency would be overkill).
- The first record is ``Event{wall_time, file_version="brain.Event:2"}``.
"""

from __future__ import annotations

import itertools
import os
import socket
import struct
import threading
import time

from fedcrack_tpu.native import crc32c

_MASK_DELTA = 0xA282EAD8
# Filename uniquifier: same-second writers on one host (e.g. a co-located
# server and client both pointed at the same --tb-dir) must never append
# into one file — interleaved records corrupt each other's CRC framing.
_FILE_COUNTER = itertools.count()


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + _MASK_DELTA & 0xFFFFFFFF


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _field_bytes(number: int, payload: bytes) -> bytes:
    return _varint((number << 3) | 2) + _varint(len(payload)) + payload


def _field_double(number: int, value: float) -> bytes:
    return _varint((number << 3) | 1) + struct.pack("<d", value)


def _field_float(number: int, value: float) -> bytes:
    return _varint((number << 3) | 5) + struct.pack("<f", value)


def _field_varint(number: int, value: int) -> bytes:
    return _varint(number << 3) + _varint(value & 0xFFFFFFFFFFFFFFFF)


def _scalar_event(tag: str, value: float, step: int, wall_time: float) -> bytes:
    summary_value = (
        _field_bytes(1, tag.encode("utf-8")) + _field_float(2, float(value))
    )
    summary = _field_bytes(1, summary_value)
    return (
        _field_double(1, wall_time)
        + _field_varint(2, int(step))
        + _field_bytes(5, summary)
    )


def _packed_doubles(number: int, values) -> bytes:
    return _field_bytes(number, b"".join(struct.pack("<d", float(v)) for v in values))


def _histo_event(
    tag: str, histo: "HistoData", step: int, wall_time: float
) -> bytes:
    """Event{wall_time, step, summary{value{tag(1), histo(5)}}} where histo is
    TF's HistogramProto: min(1:double), max(2), num(3), sum(4),
    sum_squares(5), bucket_limit(6: packed double), bucket(7: packed double)
    — the wire shape Keras' histogram_freq=1 callback writes
    (reference: client_fit_model.py:153-154)."""
    proto = (
        _field_double(1, histo.min)
        + _field_double(2, histo.max)
        + _field_double(3, histo.num)
        + _field_double(4, histo.sum)
        + _field_double(5, histo.sum_squares)
        + _packed_doubles(6, histo.bucket_limit)
        + _packed_doubles(7, histo.bucket)
    )
    summary_value = _field_bytes(1, tag.encode("utf-8")) + _field_bytes(5, proto)
    summary = _field_bytes(1, summary_value)
    return (
        _field_double(1, wall_time)
        + _field_varint(2, int(step))
        + _field_bytes(5, summary)
    )


class HistoData:
    """Bucketized distribution in TF HistogramProto shape. ``bucket[i]``
    counts values in ``(bucket_limit[i-1], bucket_limit[i]]``; the arrays are
    equal-length, as TensorBoard's event_accumulator requires."""

    __slots__ = ("min", "max", "num", "sum", "sum_squares", "bucket_limit", "bucket")

    def __init__(self, values, bins: int = 30):
        import numpy as np

        flat = np.asarray(values, dtype=np.float64).reshape(-1)
        flat = flat[np.isfinite(flat)]
        self.num = float(flat.size)
        if flat.size == 0:
            self.min = self.max = self.sum = self.sum_squares = 0.0
            self.bucket_limit = [0.0]
            self.bucket = [0.0]
            return
        self.min = float(flat.min())
        self.max = float(flat.max())
        self.sum = float(flat.sum())
        self.sum_squares = float(np.square(flat).sum())
        if self.min == self.max:
            # Degenerate distribution: one bucket holding everything, its
            # upper edge nudged so the (lo, hi] interval is non-empty.
            self.bucket_limit = [self.max + max(1e-12, abs(self.max) * 1e-7)]
            self.bucket = [self.num]
            return
        counts, edges = np.histogram(flat, bins=bins, range=(self.min, self.max))
        self.bucket_limit = [float(e) for e in edges[1:]]
        self.bucket = [float(c) for c in counts]


def _version_event(wall_time: float) -> bytes:
    return _field_double(1, wall_time) + _field_bytes(3, b"brain.Event:2")


class SummaryWriter:
    """Append-only TB scalar writer; thread-safe, one event file per logdir.

    ``SummaryWriter(d).add_scalar("round/loss", 0.12, step=3)`` produces a
    file TensorBoard's scalars dashboard loads directly.
    """

    def __init__(self, logdir: str | os.PathLike):
        logdir = os.fspath(logdir)
        os.makedirs(logdir, exist_ok=True)
        name = (
            # nothing computes on this; it is TB's file-naming convention
            # fedlint: disable=DET001 -- wall-clock creation time in the name
            f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
            f".{os.getpid()}.{next(_FILE_COUNTER)}"
        )
        self.path = os.path.join(logdir, name)
        self._f = open(self.path, "ab")
        self._lock = threading.Lock()
        # fedlint: disable=DET001 -- TB displays events on a wall-clock axis
        self._write(_version_event(time.time()))

    def _write(self, event: bytes) -> None:
        header = struct.pack("<Q", len(event))
        record = (
            header
            + struct.pack("<I", _masked_crc(header))
            + event
            + struct.pack("<I", _masked_crc(event))
        )
        with self._lock:
            self._f.write(record)
            self._f.flush()

    def add_scalar(
        self, tag: str, value: float, step: int, wall_time: float | None = None
    ) -> None:
        self._write(
            _scalar_event(
                # fedlint: disable=DET001 -- TB's wall-time display axis
                tag, value, step, time.time() if wall_time is None else wall_time
            )
        )

    def add_histogram(
        self,
        tag: str,
        values,
        step: int,
        wall_time: float | None = None,
        bins: int = 30,
    ) -> None:
        """Log the distribution of ``values`` (any array-like; flattened,
        non-finite entries dropped) — the reference's per-epoch weight
        histograms (histogram_freq=1, client_fit_model.py:153-154)."""
        self._write(
            _histo_event(
                tag,
                HistoData(values, bins=bins),
                step,
                # fedlint: disable=DET001 -- TB's wall-time display axis
                time.time() if wall_time is None else wall_time,
            )
        )

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_scalars(path: str | os.PathLike) -> list[tuple[str, float, int]]:
    """Minimal event-file reader: ``[(tag, value, step), ...]`` — the
    self-contained round-trip oracle (tests also cross-check with the real
    TensorBoard event_accumulator). Verifies record CRCs."""
    out = []
    for step, value in _summary_values(path):
        tag, val = "", None
        for number, wire, payload in _parse_fields(value):
            if number == 1 and wire == 2:
                tag = payload.decode("utf-8")
            elif number == 2 and wire == 5:  # simple_value
                (val,) = struct.unpack("<f", payload)
        if val is not None:
            out.append((tag, val, step))
    return out


def read_histograms(path: str | os.PathLike) -> list[tuple[str, dict, int]]:
    """Histogram counterpart of :func:`read_scalars`:
    ``[(tag, {min,max,num,sum,sum_squares,bucket_limit,bucket}, step), ...]``.
    Verifies record CRCs like the scalar reader."""
    out = []
    for step, value in _summary_values(path):
        tag, histo = "", None
        for number, wire, payload in _parse_fields(value):
            if number == 1 and wire == 2:
                tag = payload.decode("utf-8")
            elif number == 5 and wire == 2:  # histo
                histo = _parse_histo(payload)
        if histo is not None:
            out.append((tag, histo, step))
    return out


def _parse_histo(buf: bytes) -> dict:
    names = {1: "min", 2: "max", 3: "num", 4: "sum", 5: "sum_squares"}
    out = {"min": 0.0, "max": 0.0, "num": 0.0, "sum": 0.0, "sum_squares": 0.0,
           "bucket_limit": [], "bucket": []}
    for number, wire, value in _parse_fields(buf):
        if number in names and wire == 1:
            (out[names[number]],) = struct.unpack("<d", value)
        elif number in (6, 7) and wire == 2:  # packed double
            key = "bucket_limit" if number == 6 else "bucket"
            out[key] = [
                struct.unpack_from("<d", value, i)[0]
                for i in range(0, len(value), 8)
            ]
    return out


def _summary_values(path: str | os.PathLike):
    """The one event walker both readers share: yields ``(step, bytes)`` per
    Summary.Value in file order. The event's step field may be encoded
    before or after the summary, so values are collected per event and
    yielded with the event's final step."""
    for event in _records(path):
        step = 0
        values = []
        for number, wire, value in _parse_fields(event):
            if number == 2 and wire == 0:
                step = value
            elif number == 5 and wire == 2:  # summary
                for n2, w2, v2 in _parse_fields(value):
                    if n2 == 1 and w2 == 2:  # Summary.Value
                        values.append(v2)
        for v in values:
            yield step, v


def _records(path: str | os.PathLike):
    """CRC-verified TFRecord payloads of an event file."""
    with open(os.fspath(path), "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        header = data[pos : pos + 8]
        (len_crc,) = struct.unpack_from("<I", data, pos + 8)
        if _masked_crc(header) != len_crc:
            raise ValueError(f"corrupt length CRC at byte {pos}")
        event = data[pos + 12 : pos + 12 + length]
        (data_crc,) = struct.unpack_from("<I", data, pos + 12 + length)
        if _masked_crc(event) != data_crc:
            raise ValueError(f"corrupt event CRC at byte {pos}")
        pos += 12 + length + 4
        yield event


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _parse_fields(buf: bytes):
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        number, wire = key >> 3, key & 7
        if wire == 0:
            value, pos = _read_varint(buf, pos)
        elif wire == 1:
            value = buf[pos : pos + 8]
            pos += 8
        elif wire == 2:
            size, pos = _read_varint(buf, pos)
            value = buf[pos : pos + size]
            pos += size
        elif wire == 5:
            value = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield number, wire, value
