"""Leak sentries — RSS + device-memory watermarks with ``assert_steady``.

The RecompileSentry pattern (``analysis.sanitizers``) applied to memory: a
long-lived session (the soak, a production serve fleet) must reach steady
state and STAY there — a drifting resident set or device-memory watermark
is a leak even when every request succeeds. :class:`LeakSentry` samples

- **host RSS** via ``/proc/self/statm`` (falling back to
  ``resource.getrusage`` peak-RSS on hosts without procfs), and
- **device memory in use** via ``jax.Device.memory_stats()`` summed over
  local devices (CPU backends report nothing — the gauge stays 0 and the
  device half of the audit is vacuously steady there; on TPU it is the HBM
  leak detector),

tracks the high-watermark of each, exports all four series as collect-time
gauges (``process_resident_bytes``, ``process_resident_watermark_bytes``,
``device_memory_in_use_bytes``, ``device_memory_watermark_bytes``), and —
after :meth:`mark` pins the steady-state baseline — :meth:`assert_steady`
raises :class:`LeakError` when growth since the mark exceeds the configured
slack. Sampling is explicit (``sample()``), so harness loops control the
cadence and determinism; nothing spawns threads here.
"""

from __future__ import annotations

import os
import time
from typing import Any

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs.registry import REGISTRY, MetricsRegistry

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


class LeakError(AssertionError):
    """A watched memory series grew past its steady-state slack."""


def rss_bytes() -> int:
    """Current resident set size of this process, in bytes."""
    try:
        with open("/proc/self/statm", encoding="ascii") as f:
            fields = f.read().split()
        return int(fields[1]) * _PAGE_SIZE
    except (OSError, IndexError, ValueError):
        import resource

        # ru_maxrss is the PEAK (KiB on linux); a peak is still a usable
        # watermark signal on procfs-less hosts.
        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024


def device_memory_bytes() -> int:
    """Sum of ``bytes_in_use`` over local jax devices; 0 when the backend
    exposes no memory stats (CPU)."""
    try:
        import jax

        total = 0
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", None)
            if stats is None:
                continue
            try:
                s = stats()
            except Exception:
                continue
            if s:
                total += int(s.get("bytes_in_use", 0))
        return total
    except Exception:
        return 0


class LeakSentry:
    """Watermark tracker + steady-state assertion over host and device
    memory. ``registry=None`` exports against the process default."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        rss_slack_bytes: int = 192 * 1024 * 1024,
        device_slack_bytes: int = 64 * 1024 * 1024,
        sample_on_collect: bool = False,
    ):
        self._lock = make_lock("obs.sentries.leak")
        self.rss_slack_bytes = int(rss_slack_bytes)
        self.device_slack_bytes = int(device_slack_bytes)
        self._last = {"rss": 0, "device": 0}
        self._high = {"rss": 0, "device": 0}
        self._mark: dict[str, int] | None = None
        # sample_on_collect: every scrape refreshes the reading (throttled
        # to one sample per window so four gauges share one measurement).
        # For sessions with no natural sampling hook (refscale_federation)
        # this keeps the exported watermarks LIVE instead of frozen at the
        # startup reading; harnesses that sample explicitly (the soak)
        # leave it off for deterministic cadence.
        self._sample_on_collect = bool(sample_on_collect)
        self._last_sample_t = 0.0
        reg = registry if registry is not None else REGISTRY
        reg.gauge(
            "process_resident_bytes",
            "host RSS at the last sentry sample",
        ).set_function(lambda: self._collect()["rss"])
        reg.gauge(
            "process_resident_watermark_bytes",
            "high-watermark host RSS over the sentry's lifetime",
        ).set_function(lambda: self._high["rss"])
        reg.gauge(
            "device_memory_in_use_bytes",
            "sum of device bytes_in_use at the last sentry sample "
            "(0 on backends without memory_stats)",
        ).set_function(lambda: self._collect()["device"])
        reg.gauge(
            "device_memory_watermark_bytes",
            "high-watermark device memory over the sentry's lifetime",
        ).set_function(lambda: self._high["device"])
        self.sample()

    def sample(self) -> dict[str, int]:
        """Take one measurement; updates the watermarks. Returns the
        current ``{"rss": ..., "device": ...}`` reading."""
        reading = {"rss": rss_bytes(), "device": device_memory_bytes()}
        with self._lock:
            self._last = dict(reading)
            self._last_sample_t = time.monotonic()
            for k, v in reading.items():
                if v > self._high[k]:
                    self._high[k] = v
        return reading

    def _collect(self) -> dict[str, int]:
        """Gauge-callback read: the cached reading, refreshed first when
        ``sample_on_collect`` and the throttle window (0.5 s) has passed."""
        if self._sample_on_collect:
            with self._lock:
                stale = time.monotonic() - self._last_sample_t > 0.5
            if stale:
                self.sample()
        with self._lock:
            return dict(self._last)

    def mark(self) -> dict[str, int]:
        """Steady state begins now: growth past (mark + slack) is a leak."""
        reading = self.sample()
        with self._lock:
            self._mark = dict(reading)
        return reading

    def watermarks(self) -> dict[str, int]:
        with self._lock:
            return dict(self._high)

    def deltas(self) -> dict[str, int]:
        """Growth of the CURRENT reading over the mark (not the watermark:
        a transient spike that drained back is allowed; still-resident
        growth is what leaks look like)."""
        current = self.sample()
        with self._lock:
            if self._mark is None:
                raise RuntimeError("deltas() before mark()")
            return {k: current[k] - self._mark[k] for k in current}

    def steady(self) -> bool:
        d = self.deltas()
        return (
            d["rss"] <= self.rss_slack_bytes
            and d["device"] <= self.device_slack_bytes
        )

    def assert_steady(self) -> None:
        d = self.deltas()
        problems = []
        if d["rss"] > self.rss_slack_bytes:
            problems.append(
                f"RSS grew {d['rss']} B past the mark "
                f"(slack {self.rss_slack_bytes} B)"
            )
        if d["device"] > self.device_slack_bytes:
            problems.append(
                f"device memory grew {d['device']} B past the mark "
                f"(slack {self.device_slack_bytes} B)"
            )
        if problems:
            raise LeakError(
                "memory not steady since mark(): " + "; ".join(problems)
                + " — a long-lived session must plateau, not climb"
            )

    def summary(self) -> dict[str, Any]:
        """JSON-safe audit block for soak artifacts."""
        with self._lock:
            out: dict[str, Any] = {
                "last": dict(self._last),
                "watermark": dict(self._high),
                "mark": dict(self._mark) if self._mark else None,
            }
        if self._mark is not None:
            out["deltas"] = self.deltas()
            out["steady"] = (
                out["deltas"]["rss"] <= self.rss_slack_bytes
                and out["deltas"]["device"] <= self.device_slack_bytes
            )
        return out
