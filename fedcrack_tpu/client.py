"""Federated client entry point: ``python -m fedcrack_tpu.client``.

The reference equivalent is ``python fl_client.py`` (fl_client.py:178-188):
open a channel and run one federated session. The local dataset comes from
``--image-dir/--mask-dir`` (paired crack images, reference layout) or
``--synthetic N`` (generated fixtures). After the final round the client runs
prediction + crack quantification on its validation split — the reference
intended this but crashed on a missing method (client_fit_model.py:215,
SURVEY.md §2.2(5)).
"""

from __future__ import annotations

import argparse
import logging
import sys
import zlib

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.data.pipeline import dataset_from_source, reference_split
from fedcrack_tpu.train.federated import make_train_fn
from fedcrack_tpu.transport.client import FedClient, default_cname


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(message)s")
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", help="JSON FedConfig file")
    p.add_argument("--host")
    p.add_argument("--port", type=int)
    p.add_argument("--name", help="client name (default: random unique)")
    p.add_argument("--image-dir")
    p.add_argument("--mask-dir")
    p.add_argument("--synthetic", type=int, default=0, help="use N generated samples")
    p.add_argument(
        "--transport-dtype",
        choices=("uint8", "float32"),
        default="uint8",
        help="host->device staging dtype for file datasets; uint8 ships 1/4 "
        "the bytes and is bit-identical (normalization happens on device)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--num-clients",
        type=int,
        default=None,
        help="total cohort size for data sharding: each client takes a "
        "disjoint shard of the train split (cfg.data.partition: iid or "
        "crack-density skew — the reference gave every client the same "
        "data). Defaults to the config's cohort_size when --client-index "
        "is given.",
    )
    p.add_argument(
        "--client-index", type=int, default=None, help="this client's shard row"
    )
    p.add_argument("--predict-dir", help="write final-round mask predictions here")
    p.add_argument("--metrics", dest="metrics_path", help="JSONL metrics file")
    p.add_argument(
        "--tb-dir",
        dest="tb_dir",
        help="TensorBoard event-file directory for per-round local-fit "
        "scalars (the reference's TB callback, client_fit_model.py:153-154)",
    )
    p.add_argument(
        "--profile-dir",
        dest="profile_dir",
        help="jax.profiler trace dir wrapping each round's local fit",
    )
    p.add_argument(
        "--auth-token",
        dest="auth_token",
        help="shared enrollment token (must match the server's)",
    )
    p.add_argument(
        "--allow-insecure-token",
        dest="allow_insecure_token",
        action="store_const",
        const=True,
        default=None,
        help="accept --auth-token over a plaintext channel (the secret then "
        "travels in cleartext on every message; loopback/testing only)",
    )
    p.add_argument(
        "--tls-ca",
        dest="tls_ca",
        help="root CA (PEM) to verify the server over TLS; plaintext if unset",
    )
    p.add_argument("--tls-cert", dest="tls_cert", help="client certificate for mTLS (PEM)")
    p.add_argument("--tls-key", dest="tls_key", help="client private key for mTLS (PEM)")
    p.add_argument(
        "--max-message-mb",
        type=int,
        dest="max_message_mb",
        help="gRPC send/receive cap in MiB (must cover the server's dense "
        "weight broadcast regardless of the negotiated upload codec)",
    )
    p.add_argument(
        "--dp-clip-norm",
        type=float,
        dest="dp_clip_norm",
        help="update-level local DP (McMahan et al. 2018): clip this "
        "round's (trained - base) delta to this L2 norm before upload "
        "(0 disables)",
    )
    p.add_argument(
        "--dp-noise-multiplier",
        type=float,
        dest="dp_noise_multiplier",
        help="update-level DP noise: one seeded Gaussian N(0, "
        "(sigma*clip)^2) draw added to the clipped delta; the seed is "
        "derived from (dp_seed, name, round) so retried uploads are "
        "bit-identical",
    )
    p.add_argument(
        "--dp-seed",
        type=int,
        dest="dp_seed",
        help="root seed of the per-(client, round) DP noise derivation",
    )
    args = p.parse_args(argv)

    # Flags merge into the RAW config dict before FedConfig construction, so
    # __post_init__ validation sees the final merged config (a --tls-ca or
    # --allow-insecure-token flag must be able to rescue a config file that
    # would fail the plaintext-token check on its own).
    if args.config:
        import json

        with open(args.config) as f:
            raw = json.load(f)
    else:
        raw = {}
    overrides = {
        k: v
        for k, v in [
            ("host", args.host),
            ("port", args.port),
            ("metrics_path", args.metrics_path),
            ("tb_dir", args.tb_dir),
            ("profile_dir", args.profile_dir),
            ("auth_token", args.auth_token),
            ("allow_insecure_token", args.allow_insecure_token),
            ("tls_ca", args.tls_ca),
            ("tls_cert", args.tls_cert),
            ("tls_key", args.tls_key),
            ("max_message_mb", args.max_message_mb),
            ("dp_clip_norm", args.dp_clip_norm),
            ("dp_noise_multiplier", args.dp_noise_multiplier),
            ("dp_seed", args.dp_seed),
        ]
        if v is not None
    }
    raw.update(overrides)
    cfg = FedConfig.from_dict(raw)

    batch = cfg.data.batch_size
    if args.num_clients is not None:
        num_clients = args.num_clients
    elif args.client_index is not None:
        num_clients = cfg.cohort_size  # the presets' cohort IS the shard count
    else:
        num_clients = 1
    if num_clients > 1 and args.client_index is None:
        # Defaulting to shard 0 here would pin EVERY client to the same
        # shard and silently leave the rest of the data untrained.
        p.error("--num-clients > 1 requires --client-index")
    client_index = args.client_index if args.client_index is not None else 0
    cname = args.name or default_cname()
    data_seed = args.seed + client_index
    if args.synthetic and args.client_index is None and cfg.cohort_size > 1:
        # Without --client-index every synthetic cohort member would get the
        # same seed and train IDENTICAL data — the reference flaw the
        # sharding work fixes, silently reproduced by the quickstart. Derive
        # the seed from the unique client name instead so each member
        # synthesizes a distinct shard.
        data_seed = args.seed + zlib.crc32(cname.encode())
        logging.warning(
            "synthetic data with no --client-index in a %d-member cohort: "
            "deriving the data seed (%d) from client name %r so cohort "
            "members train distinct shards; pass --client-index for "
            "reproducible sharding",
            cfg.cohort_size,
            data_seed,
            cname,
        )
    if num_clients == 1 and cfg.cohort_size > 1 and not args.synthetic:
        logging.warning(
            "data sharding is OFF (every client would train the same data, "
            "like the reference): pass --client-index (and optionally "
            "--num-clients) so each of the %d cohort members takes a "
            "disjoint shard",
            cfg.cohort_size,
        )

    def local_shard(pairs):
        # Train side of the reference's seeded split
        # (client_fit_model.py:76-82), then this client's disjoint shard:
        # IID or crack-density skew (BASELINE.md config 4). Every client
        # computes the same deterministic assignment and picks its row.
        from fedcrack_tpu.data.sharding import shard_pairs

        train_pairs, _ = reference_split(
            pairs, cfg.data.train_samples, cfg.data.split_seed
        )
        return shard_pairs(
            train_pairs,
            num_clients,
            client_index,
            partition=cfg.data.partition,
            alpha=cfg.data.skew_alpha,
            seed=cfg.data.split_seed,
        )

    try:
        dataset = dataset_from_source(
            # Synthetic shards differ per client through the seed.
            args.synthetic,
            args.image_dir,
            args.mask_dir,
            img_size=cfg.model.img_size,
            batch_size=batch,
            seed=data_seed,
            num_workers=cfg.data.num_workers,
            prefetch=cfg.data.prefetch,
            pair_filter=local_shard,
            transport_dtype=args.transport_dtype,
        )
    except ValueError as e:
        p.error(str(e))

    metrics_logger = None
    if cfg.metrics_path or cfg.tb_dir:
        import os

        from fedcrack_tpu.obs import MetricsLogger

        metrics_logger = MetricsLogger(
            cfg.metrics_path or os.devnull, tb_dir=cfg.tb_dir or None
        )
    train_fn, holder = make_train_fn(
        cfg, dataset, batch, seed=args.seed, metrics_logger=metrics_logger
    )
    if cfg.dp_clip_norm > 0:
        # Update-level local DP (privacy plane, round 23): clip + noise the
        # round delta on the host before it ever reaches the wire — the
        # server and other clients only see the privatized update. The
        # noise key derives from (dp_seed, name, round), so a retried
        # upload of the same round is bit-identical, never double-noised.
        from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
        from fedcrack_tpu.privacy.dpsgd import dp_update_host

        inner_train_fn = train_fn

        def train_fn(blob, rnd, *rest):
            out_blob, n_samples, metrics = inner_train_fn(blob, rnd, *rest)
            base = tree_from_bytes(blob)
            trained = tree_from_bytes(out_blob, template=base)
            private = dp_update_host(
                trained,
                base,
                clip_norm=cfg.dp_clip_norm,
                noise_multiplier=cfg.dp_noise_multiplier,
                dp_seed=cfg.dp_seed,
                cname=cname,
                round_idx=rnd,
            )
            return tree_to_bytes(private), n_samples, metrics

    client = FedClient(cfg, train_fn, cname=cname)
    result = client.run_session()
    if metrics_logger is not None:
        metrics_logger.log(
            "session",
            enrolled=result.enrolled,
            rounds_completed=result.rounds_completed,
        )
        metrics_logger.close()
    if cfg.metrics_path and result.enrolled:
        # Ship the complete per-round metrics JSONL — session summary
        # included, hence after the logger closes — to the coordinator's log
        # sink (reference C2.1/C1.5: its 'L' upload path existed but was
        # never called, fl_client.py:110-118). Best-effort: the server only
        # lingers briefly after FIN.
        try:
            client.upload_file(cfg.metrics_path)
        except Exception:
            logging.warning("metrics upload failed", exc_info=True)
    logging.info(
        "session done: enrolled=%s rounds=%d", result.enrolled, result.rounds_completed
    )
    for entry in result.history:
        logging.info("round metrics: %s", entry)

    if args.predict_dir and result.final_weights is not None:
        from fedcrack_tpu.tools.quantify import predict_and_quantify

        report = predict_and_quantify(
            holder["state"], dataset, out_dir=args.predict_dir
        )
        logging.info("crack quantification: %s", report)
    return 0 if result.enrolled else 1


if __name__ == "__main__":
    sys.exit(main())
