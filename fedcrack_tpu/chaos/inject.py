"""Fault-plan hooks for the transport client and the mesh driver.

Injection points, all zero-cost when no plan is attached:

- ``ClientChaos`` plugs into :class:`fedcrack_tpu.transport.client.FedClient`
  (``chaos=`` ctor arg). ``before_send`` runs inside the retry loop right
  before each RPC; ``after_reply`` runs on the reply before it is returned.
  Between them they express every client-side fault: crashes before/during/
  after the weight upload, straggler sleeps, transient UNAVAILABLE flaps
  (which must be survived by the retry schedule), and the four payload
  poisonings (corrupt / truncate / NaN / stale-round replay) that the
  server's update sanitation must catch.
- ``MeshChaos`` is a ``fault_injector`` for
  :func:`fedcrack_tpu.parallel.driver.run_mesh_federation`: called as
  ``injector(round_idx, attempt)`` before each round attempt, it either
  raises :class:`InjectedDeviceFailure` (preemption) or returns a transform
  that poisons the round output with NaNs (silent numerical corruption).

Server kill-and-restart is deliberately NOT a hook: a dead process cannot
run one. The harnesses (tests/test_chaos.py, tools/chaos_drill.py) kill the
serving loop itself and boot a fresh ``FedServer`` over the same state
directory — the recovery path under test is the statefile restore, not an
in-process simulation of it.

Injected crashes surface as :class:`InjectedCrash` — an ordinary exception
escaping the client session, exactly like the trainer exceptions real client
deaths produce in the existing fault tests.
"""

from __future__ import annotations

import time

import grpc

from fedcrack_tpu.chaos.plan import (
    CRASH_AFTER_UPLOAD,
    CRASH_BEFORE_UPLOAD,
    CRASH_DURING_UPLOAD,
    CORRUPT_COMPRESSED_FRAME,
    CORRUPT_PAYLOAD,
    MESH_DEVICE_FAIL,
    MESH_NONFINITE,
    NAN_UPDATE,
    NETWORK_FLAP,
    SCALED_UPDATE,
    SECAGG_DROPOUT,
    SERVE_DEVICE_LOSS,
    SERVE_STREAM_RESET,
    SERVE_SWAP_MIDFLIGHT,
    STALE_REPLAY,
    STRAGGLER_DELAY,
    TRUNCATE_PAYLOAD,
    FaultPlan,
)

# SCALED_UPDATE's amplification factor: large enough that a x-scaled real
# update is unmistakably outside any honest cohort's norm spread, small
# enough that float32 stays finite for any realistic weight magnitude.
SCALE_FACTOR = 1000.0


class InjectedCrash(Exception):
    """The planned death of a client process (raised out of the session)."""


class InjectedDeviceFailure(Exception):
    """A planned mesh-plane device/host loss (raised out of the round)."""


class InjectedRpcError(grpc.RpcError):
    """A synthetic transient transport failure. Carries UNAVAILABLE — the
    code real gRPC raises for a flapping network — so the client's
    retryable/non-retryable split treats it exactly like the real thing."""

    def __init__(self, message: str = "injected network flap"):
        super().__init__(message)
        self._message = message

    def code(self) -> grpc.StatusCode:
        return grpc.StatusCode.UNAVAILABLE

    def details(self) -> str:
        return self._message


def _round_of(msg) -> int | None:
    """The protocol round a ClientMessage speaks about, if any."""
    kind = msg.WhichOneof("msg")
    if kind == "done":
        return int(msg.done.round)
    if kind == "training":
        return int(msg.training.round)
    if kind == "poll":
        return int(msg.poll.round)
    return None


def _poison_weights(blob: bytes, mode: str) -> bytes:
    if mode == CORRUPT_COMPRESSED_FRAME:
        from fedcrack_tpu.compress import is_frame

        if not is_frame(blob):
            # A raw msgpack blob has no checksum: one flipped bit inside a
            # float payload is valid msgpack, almost always finite, and
            # would sail through shape/finiteness sanitation into FedAvg —
            # a SILENT corruption, not the rejected one this fault kind
            # asserts. On a null-codec cohort degrade to the structural
            # mangle, which the server's decode gate deterministically
            # rejects, keeping the fault's contract ("never averaged").
            return _poison_weights(blob, CORRUPT_PAYLOAD)
        # One flipped bit INSIDE the encoded frame body (past the magic +
        # CRC header), the failure a lossy link actually delivers: the
        # frame still LOOKS like a frame, so only the CRC check can catch
        # it — which is exactly the claim under test.
        pos = max(8, (3 * len(blob)) // 4)
        pos = min(pos, len(blob) - 1)
        return blob[:pos] + bytes([blob[pos] ^ 0x10]) + blob[pos + 1 :]
    if mode == TRUNCATE_PAYLOAD:
        return blob[: max(1, len(blob) // 2)]
    if mode == CORRUPT_PAYLOAD:
        # Mangle the msgpack STRUCTURE (leading map/key bytes), not a float
        # payload byte: structural damage is what checksums-free transports
        # actually deliver detectably, and it deterministically fails the
        # server's decode instead of landing plausible garbage values.
        head = bytes(b ^ 0xFF for b in blob[:8])
        return head + blob[8:]
    if mode == NAN_UPDATE:
        import numpy as np

        from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes
        import jax

        from fedcrack_tpu.compress import decode_frame, encode_frame, is_frame

        if is_frame(blob):
            # A compressed cohort: the blob is an FCWF frame, not msgpack —
            # poison INSIDE the frame and re-frame it, so the wire carries
            # a CRC-VALID frame whose reconstruction is non-finite. This is
            # the fault's meaning under compression: the CRC must pass and
            # the validate_update sanitation gate must be the thing that
            # refuses it (the CRC-failure case is CORRUPT_COMPRESSED_FRAME).
            frame = decode_frame(blob)
            leaves = [dict(spec) for spec in frame.leaves]
            payload = bytearray(frame.payload)
            off, poisoned = 0, False
            for spec in leaves:
                shape = spec.get("shape") or []
                n = 1
                for s in shape:
                    n *= int(s)
                if spec.get("enc") == "int8":
                    if not poisoned and spec.get("scales"):
                        n_scales = len(spec["scales"]) // 4
                        spec["scales"] = np.full(
                            n_scales, np.inf, np.float32
                        ).tobytes()
                        poisoned = True
                    off += n
                else:  # topk: k int32 indices then k float32 values
                    k = int(spec.get("k", 0))
                    if not poisoned and k:
                        payload[off + 4 * k : off + 8 * k] = np.full(
                            k, np.nan, np.float32
                        ).tobytes()
                        poisoned = True
                    off += 8 * k
            return encode_frame(
                frame.codec, frame.round, frame.base_version, leaves,
                bytes(payload),
            )
        tree = tree_from_bytes(blob)
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        poisoned = []
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            if i == 0 and arr.dtype.kind == "f":
                arr = np.full_like(arr, np.nan)
            poisoned.append(arr)
        return tree_to_bytes(jax.tree_util.tree_unflatten(treedef, poisoned))
    if mode == SCALED_UPDATE:
        # Adversarial amplification (round 18, Blanchard et al.): the
        # client's REAL trained weights x SCALE_FACTOR — every value
        # finite, every shape exact, so sanitation ACCEPTS it and FedAvg
        # averages it in. Only the health ledger's flush-time anomaly
        # score (norm/cosine robust-z) can flag it — which is the claim
        # the scaled-update drill pins.
        import numpy as np

        import jax

        from fedcrack_tpu.compress import decode_frame, encode_frame, is_frame
        from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes

        if is_frame(blob):
            # Scale INSIDE the frame and re-frame with a fresh CRC: int8
            # leaves amplify through their dequant scales, topk leaves
            # through their float value region — the frame stays CRC-valid
            # and decodes to the x-scaled reconstruction.
            frame = decode_frame(blob)
            leaves = [dict(spec) for spec in frame.leaves]
            payload = bytearray(frame.payload)
            off = 0
            for spec in leaves:
                shape = spec.get("shape") or []
                n = 1
                for s in shape:
                    n *= int(s)
                if spec.get("enc") == "int8":
                    if spec.get("scales"):
                        scales = np.frombuffer(spec["scales"], np.float32)
                        spec["scales"] = (
                            scales * np.float32(SCALE_FACTOR)
                        ).tobytes()
                    off += n
                else:  # topk: k int32 indices then k float32 values
                    k = int(spec.get("k", 0))
                    if k:
                        vals = np.frombuffer(
                            payload[off + 4 * k : off + 8 * k], np.float32
                        )
                        payload[off + 4 * k : off + 8 * k] = (
                            vals * np.float32(SCALE_FACTOR)
                        ).tobytes()
                    off += 8 * k
            return encode_frame(
                frame.codec, frame.round, frame.base_version, leaves,
                bytes(payload),
            )
        tree = tree_from_bytes(blob)
        scaled = jax.tree_util.tree_map(
            lambda a: (
                np.asarray(a) * np.asarray(SCALE_FACTOR, np.asarray(a).dtype)
                if np.asarray(a).dtype.kind == "f"
                else np.asarray(a)
            ),
            tree,
        )
        return tree_to_bytes(scaled)
    raise ValueError(f"not a payload poison: {mode}")


class ClientChaos:
    """Per-client fault hook; attach one instance per injected FedClient."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._flap_left = 0
        self._crash_armed = False

    # -- FedClient hook points --

    def before_send(self, cname: str, msg) -> None:
        """May raise (crash/flap), sleep (straggler), or mutate ``msg`` in
        place (payload poisons). Runs INSIDE the retry loop: a raised flap
        goes through the same except-path a real UNAVAILABLE would."""
        if self._crash_armed:
            raise InjectedCrash(f"{cname}: crash after upload")
        rnd = _round_of(msg)
        fault = self.plan.take(NETWORK_FLAP, client=cname, round=rnd)
        if fault is not None:
            self._flap_left = fault.count
        if self._flap_left > 0:
            self._flap_left -= 1
            raise InjectedRpcError(f"{cname}: injected flap")
        if msg.WhichOneof("msg") != "done":
            return
        if self.plan.take(CRASH_BEFORE_UPLOAD, client=cname, round=rnd) is not None:
            raise InjectedCrash(f"{cname}: crash before upload (round {rnd})")
        if self.plan.take(SECAGG_DROPOUT, client=cname, round=rnd) is not None:
            # Masker dropout (round 23): by this point the client's seed is
            # in the frozen roster and every survivor masked against it —
            # dying here forces the server's seed-recovery step.
            raise InjectedCrash(
                f"{cname}: secagg masker dropout (round {rnd})"
            )
        fault = self.plan.take(STRAGGLER_DELAY, client=cname, round=rnd)
        if fault is not None:
            time.sleep(fault.delay_s)
        for mode in (
            CORRUPT_PAYLOAD,
            TRUNCATE_PAYLOAD,
            NAN_UPDATE,
            CORRUPT_COMPRESSED_FRAME,
            SCALED_UPDATE,
        ):
            if self.plan.take(mode, client=cname, round=rnd) is not None:
                msg.done.weights = _poison_weights(msg.done.weights, mode)
        if self.plan.take(STALE_REPLAY, client=cname, round=rnd) is not None:
            msg.done.round = max(1, int(msg.done.round) - 1)

    def after_reply(self, cname: str, msg, reply) -> None:
        """Crash AFTER the server processed the upload: ``during`` dies here
        (the client never learns its report landed), ``after`` arms a crash
        for the next call (the client knew, then died)."""
        if msg.WhichOneof("msg") != "done":
            return
        rnd = _round_of(msg)
        if self.plan.take(CRASH_DURING_UPLOAD, client=cname, round=rnd) is not None:
            raise InjectedCrash(f"{cname}: crash during upload (round {rnd})")
        if self.plan.take(CRASH_AFTER_UPLOAD, client=cname, round=rnd) is not None:
            self._crash_armed = True


class MeshChaos:
    """``fault_injector`` for the mesh driver's bounded-retry round loop."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __call__(self, round_idx: int, attempt: int):
        """Called before each attempt of round ``round_idx``. Raises for a
        device failure; returns a ``(variables, metrics) -> (variables,
        metrics)`` poison for silent corruption; None for a clean attempt.
        One-shot semantics mean the post-failure replay runs clean."""
        if self.plan.take(MESH_DEVICE_FAIL, round=round_idx) is not None:
            raise InjectedDeviceFailure(
                f"injected device failure (round {round_idx}, attempt {attempt})"
            )
        if self.plan.take(MESH_NONFINITE, round=round_idx) is not None:
            return _nan_poison
        return None


class ServeChaos:
    """``chaos=`` hook for :class:`fedcrack_tpu.serve.batcher.MicroBatcher`.

    Called as ``on_batch(bucket, batch_index, attempt)`` between the
    worker's weights snapshot and the batch dispatch — exactly the window
    where a hot swap or a device loss is most dangerous:

    - ``SERVE_SWAP_MIDFLIGHT`` (matched on ``round == batch_index``) calls
      ``swap_hook()`` (typically ``manager.poll_once``), installing a new
      model AFTER the in-flight batch snapshotted its weights. The barrier
      contract says the batch must still answer entirely from its snapshot
      (no torn reads) — pinned by the chaos serving test.
    - ``SERVE_DEVICE_LOSS`` raises :class:`InjectedDeviceFailure`; the
      batcher retries the batch with a fresh snapshot and no request is
      dropped. Faults fire only on ``attempt`` 0 so the retry runs clean
      (the plan's one-shot semantics would guarantee that anyway; the guard
      keeps a multi-fault plan from burning two faults on one batch).
    """

    def __init__(self, plan: FaultPlan, swap_hook=None):
        self.plan = plan
        self.swap_hook = swap_hook

    def on_batch(self, bucket: int, batch_index: int, attempt: int) -> None:
        if attempt > 0:
            return
        if self.plan.take(SERVE_SWAP_MIDFLIGHT, round=batch_index) is not None:
            if self.swap_hook is not None:
                self.swap_hook()
        if self.plan.take(SERVE_DEVICE_LOSS, round=batch_index) is not None:
            raise InjectedDeviceFailure(
                f"injected serving device loss (bucket {bucket}, "
                f"batch {batch_index}, attempt {attempt})"
            )


class StreamChaos:
    """``chaos=`` hook for the video-stream plane
    (:class:`fedcrack_tpu.serve.stream.StreamSession`).

    Called as ``on_frame(stream_id, frame_index, session)`` at the top of
    each frame, BEFORE the snapshot is pinned or any tile is hashed.
    ``SERVE_STREAM_RESET`` (matched on ``round == frame_index``, 0-based)
    calls ``session.reset()`` — the per-stream tile cache is dropped
    mid-stream, so the target frame must be served as a full-tile re-run.
    The drilled claim (tools/chaos_drill.run_stream_reset_drill): the reset
    changes LATENCY, never bytes — every frame including the reset frame
    stays byte-identical to stateless ``predict_tiled``, and no accepted
    frame is dropped.
    """

    def __init__(self, plan: FaultPlan, manager=None):
        self.plan = plan
        self.manager = manager

    def on_frame(self, stream_id: str, frame_index: int, session) -> None:
        if self.plan.take(SERVE_STREAM_RESET, round=frame_index) is not None:
            session.reset()
            if self.manager is not None:
                self.manager.record_reset()


def _nan_poison(variables, metrics):
    import jax
    import jax.numpy as jnp

    def nanify(tree):
        return jax.tree_util.tree_map(
            lambda a: (
                jnp.full_like(a, jnp.nan)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else a
            ),
            tree,
        )

    return nanify(variables), nanify(metrics)
