"""Deterministic fault plans: WHAT breaks, WHERE, and WHEN.

A :class:`FaultPlan` is an explicit, seed-reproducible schedule of injected
failures. It is pure bookkeeping — the plan never touches the transport or
the mesh itself; the hooks in :mod:`fedcrack_tpu.chaos.inject` consult it at
well-defined points and act on what it returns. Faults are ONE-SHOT: the
first hook that matches a fault consumes it (:meth:`FaultPlan.take`), so a
retried call or a replayed round does not re-trip the same failure — which
is exactly what makes bounded-retry recovery testable.

Determinism contract: a plan built from ``FaultPlan.generate(seed, ...)``
with the same arguments always produces the same fault schedule, and a
scenario driven by the same plan + the same cohort is replayable.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Iterable

# ---- fault kinds ----
# Transport plane (client-side hooks; fedcrack_tpu.transport.client).
CRASH_BEFORE_UPLOAD = "crash_before_upload"    # dies before TrainDone is sent
CRASH_DURING_UPLOAD = "crash_during_upload"    # dies after send, before the reply
CRASH_AFTER_UPLOAD = "crash_after_upload"      # dies on the first call after reporting
STRAGGLER_DELAY = "straggler_delay"            # sleeps delay_s before reporting
NETWORK_FLAP = "network_flap"                  # next `count` RPCs fail UNAVAILABLE
CORRUPT_PAYLOAD = "corrupt_payload"            # TrainDone weights bytes mangled
TRUNCATE_PAYLOAD = "truncate_payload"          # TrainDone weights cut in half
NAN_UPDATE = "nan_update"                      # TrainDone weights re-encoded with NaNs
STALE_REPLAY = "stale_replay"                  # TrainDone re-tagged with round-1
# Bit-flip INSIDE an encoded compressed frame (round 12): one payload bit
# flips after the client framed + CRC'd its update — the server's frame
# decode must reject it (checksum mismatch) before any reconstruction, and
# the round must still reach quorum without the poisoned upload.
CORRUPT_COMPRESSED_FRAME = "corrupt_compressed_frame"
# Adversarially AMPLIFIED update (round 18, Blanchard et al.'s threat
# model): the client's real trained weights scaled by a large finite
# factor — shape-correct, fully finite, so it PASSES sanitation and is
# averaged in; the health ledger's flush-time anomaly score is what flags
# it (drilled by tools/chaos_drill.run_scaled_update_drill).
SCALED_UPDATE = "scaled_update"
# Secure-aggregation masker dropout (round 23, privacy plane): the client
# dies in the exact window the Bonawitz recovery round exists for — AFTER
# its masking seed entered the frozen roster (every survivor's upload
# carries uncancelled pairwise masks against it) but BEFORE its own masked
# upload. Mechanically a crash-before-upload, as its own kind so the
# secagg drill schedules/asserts the privacy-plane scenario explicitly;
# drilled by tools/chaos_drill.run_secagg_dropout_drill, which pins the
# unmasked cohort average bit-for-bit against the survivors' plaintext
# fixed-point sum after seed recovery.
SECAGG_DROPOUT = "secagg_dropout"

# Mesh plane (driver hook; fedcrack_tpu.parallel.driver fault_injector).
MESH_DEVICE_FAIL = "mesh_device_fail"          # round dispatch raises (preemption)
MESH_NONFINITE = "mesh_nonfinite"              # round output poisoned with NaNs

# Serving plane (batcher hook; fedcrack_tpu.serve.batcher chaos=). `round`
# is the 0-based batch index within the bucket worker.
SERVE_SWAP_MIDFLIGHT = "serve_swap_midflight"  # install a new model while a batch is in flight
SERVE_DEVICE_LOSS = "serve_device_loss"        # batch dispatch raises (device loss)

# Video-stream plane (round 19). A mid-stream session drop: the per-stream
# tile cache (serve/stream.py) is wiped BEFORE the target frame is served,
# so that frame must fall back to a full-tile re-run. `round` is the
# 0-based frame index within the stream. Consumed by
# serve.stream.StreamChaos.on_frame; drilled by
# tools/chaos_drill.run_stream_reset_drill, which pins zero wrong bytes
# and zero dropped frames across the reset.
SERVE_STREAM_RESET = "serve_stream_reset"

# Serve-fleet plane (round 17). Scenario-harness kind like the edge crash:
# a "crashed" replica runs no hook, so tools/chaos_drill.run_replica_crash_drill
# and tests/test_fleet.py consume this from the plan, call
# FleetRouter.kill_replica mid-load (queued requests drain to survivors with
# their original futures — zero accepted requests dropped), and then prove
# the fleet-wide two-phase swap still lands on the survivors. `round` is the
# replica index to kill.
SERVE_REPLICA_CRASH = "serve_replica_crash"

# Elastic-fleet plane (round 22). Scenario-harness kinds consumed by
# tools/chaos_drill.run_elastic_fleet_drill:
# - REPLICA_CRASH_DURING_SCALE: a replica crash fired CONCURRENTLY with an
#   autoscaler scale-down — two drains race on the same router, and the
#   drill pins that zero accepted requests drop either way. `round` is the
#   replica index to crash.
# - SHADOW_REPLICA_CRASH: the shadow candidate lane dies mid-staging (its
#   batcher closed under the live mirror). The drill pins that production
#   answers and latency are untouched and the verdict degrades to a loud
#   rollback, never a promote. `round` is 0 (one shadow lane at a time).
REPLICA_CRASH_DURING_SCALE = "replica_crash_during_scale"
SHADOW_REPLICA_CRASH = "shadow_replica_crash"

# Aggregation-tree plane (round 13). Like the server kill, a dead edge
# process cannot run an in-process hook — this kind is consumed by the
# scenario harnesses (tools/chaos_drill.run_edge_crash_drill,
# tests/test_chaos.py), which kill the edge aggregator mid-round and
# restart it from its statefile (fed.tree.EdgeAggregator.restore). The
# plan still schedules and records it, so a scenario asserts the kill
# actually fired instead of silently matching nothing.
EDGE_AGGREGATOR_CRASH = "edge_aggregator_crash"  # edge tier dies mid-round, restarts from statefile

# Async-federation plane (round 14). A straggler STORM: every client in
# the cohort draws per-iteration training delays from one seeded
# heavy-tail (Pareto) distribution — the workload shape FedBuff exists
# for, where a sync barrier's round wall is the per-round MAX delay while
# buffered aggregation flushes on the K fastest. Scenario-harness kind
# like the edge crash: `FaultPlan.storm` schedules the per-(client,
# iteration) STRAGGLER_DELAY faults plus ONE storm marker the drill
# consumes, so an artifact proves the storm actually ran (and both arms
# of a sync-vs-buffered A/B replay the identical delay schedule).
STRAGGLER_STORM = "straggler_storm"

CLIENT_KINDS = frozenset(
    {
        CRASH_BEFORE_UPLOAD,
        CRASH_DURING_UPLOAD,
        CRASH_AFTER_UPLOAD,
        STRAGGLER_DELAY,
        NETWORK_FLAP,
        CORRUPT_PAYLOAD,
        TRUNCATE_PAYLOAD,
        NAN_UPDATE,
        STALE_REPLAY,
        CORRUPT_COMPRESSED_FRAME,
        SCALED_UPDATE,
        SECAGG_DROPOUT,
    }
)
MESH_KINDS = frozenset({MESH_DEVICE_FAIL, MESH_NONFINITE})
SERVE_KINDS = frozenset({SERVE_SWAP_MIDFLIGHT, SERVE_DEVICE_LOSS})
# Scenario-harness kinds: consumed by scripted drills (a dead process runs
# no hook); `client` carries the edge id.
TREE_KINDS = frozenset({EDGE_AGGREGATOR_CRASH})
STORM_KINDS = frozenset({STRAGGLER_STORM})
FLEET_KINDS = frozenset(
    {SERVE_REPLICA_CRASH, REPLICA_CRASH_DURING_SCALE, SHADOW_REPLICA_CRASH}
)
STREAM_KINDS = frozenset({SERVE_STREAM_RESET})
ALL_KINDS = (
    CLIENT_KINDS
    | MESH_KINDS
    | SERVE_KINDS
    | TREE_KINDS
    | STORM_KINDS
    | FLEET_KINDS
    | STREAM_KINDS
)


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled failure.

    ``round`` is the protocol round (1-based) for client faults and the
    driver round index (0-based) for mesh faults — each plane's natural
    numbering. ``client`` is the target cname (None for mesh faults).
    """

    kind: str
    round: int
    client: str | None = None
    delay_s: float = 0.0     # STRAGGLER_DELAY: how long to stall
    count: int = 1           # NETWORK_FLAP: how many consecutive RPCs fail

    def __post_init__(self) -> None:
        if self.kind not in ALL_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.count < 1:
            raise ValueError(f"count must be >= 1, got {self.count}")


class FaultPlan:
    """A consumable schedule of :class:`Fault` s.

    Mutability is deliberate and single-threaded-per-target: each injected
    client owns its own hook object, and hooks consume faults under the
    caller's thread. The plan records everything it fired in ``triggered``
    (order of consumption), so scenario tests can assert that the schedule
    actually ran instead of silently matching nothing.
    """

    def __init__(self, faults: Iterable[Fault] = ()):
        self.pending: list[Fault] = list(faults)
        self.triggered: list[Fault] = []

    def __len__(self) -> int:
        return len(self.pending)

    def take(
        self,
        kind: str,
        *,
        client: str | None = None,
        round: int | None = None,
    ) -> Fault | None:
        """Consume and return the first pending fault matching ``kind``,
        ``client`` and ``round``; None when nothing matches. A fault with
        ``client=None`` matches any client; every fault pins a round, so a
        hook point that cannot see one (``round=None`` — e.g. an enroll
        message) never matches."""
        for i, f in enumerate(self.pending):
            if f.kind != kind:
                continue
            if f.client is not None and f.client != client:
                continue
            if round is None or f.round != round:
                continue
            del self.pending[i]
            self.triggered.append(f)
            # Flight-recorder feed (round 16): every fault actually handed
            # out lands in the bounded ring, so a red run's dump shows the
            # injections that preceded it (one global read when no ring).
            from fedcrack_tpu.obs import flight

            flight.note(
                "chaos.fault", fault=f.kind, client=f.client, round=f.round
            )
            return f
        return None

    @classmethod
    def generate(
        cls,
        seed: int,
        *,
        n_rounds: int,
        clients: Iterable[str],
        kinds: Iterable[str] | None = None,
        n_faults: int = 3,
        max_delay_s: float = 0.5,
    ) -> "FaultPlan":
        """A seeded random schedule over the given rounds x clients — the
        long-horizon soak's input. Client kinds draw a (client, round) pair;
        mesh kinds draw a 0-based round. Same seed, same schedule."""
        rng = random.Random(seed)
        kind_pool = sorted(kinds if kinds is not None else CLIENT_KINDS)
        names = sorted(clients)
        faults = []
        for _ in range(n_faults):
            kind = rng.choice(kind_pool)
            if kind in MESH_KINDS or kind in SERVE_KINDS or kind in STREAM_KINDS:
                # These planes use a 0-based index (driver round / batch /
                # frame).
                faults.append(Fault(kind=kind, round=rng.randrange(n_rounds)))
            else:
                faults.append(
                    Fault(
                        kind=kind,
                        round=rng.randint(1, n_rounds),
                        client=rng.choice(names) if names else None,
                        delay_s=round(rng.uniform(0.05, max_delay_s), 3),
                        count=rng.randint(1, 2),
                    )
                )
        return cls(faults)

    @classmethod
    def storm(
        cls,
        seed: int,
        *,
        clients: Iterable[str],
        n_iterations: int,
        tail_alpha: float = 1.2,
        scale_s: float = 0.04,
        cap_s: float = 0.6,
        gust_p: float = 0.25,
        gust_floor: float = 0.5,
    ) -> "FaultPlan":
        """A seeded straggler STORM (round 14): one heavy-tail
        STRAGGLER_DELAY per (client, iteration), drawn from a MIXTURE —
        with probability ``gust_p`` a "storm gust" uniform in
        ``[gust_floor, 1] * cap_s`` (a client effectively down for the
        round), otherwise the Pareto body ``min(cap_s, scale_s *
        Pareto(tail_alpha))``. The gust component keeps the per-round MAX
        over any real cohort near ``cap_s`` with high probability — the
        wall a sync barrier serializes on — while the K fastest draws
        (what a buffered flush waits for) stay near ``scale_s``; a pure
        Pareto tail has the same expectations but seed-to-seed variance
        that would make A/B artifacts flaky. Plus one STRAGGLER_STORM
        marker fault (round 1) the drill consumes so the artifact proves
        the storm fired. Same seed, same schedule — the sync and buffered
        arms of an A/B replay identical delays: the sync arm reads
        iteration r as its protocol round r, the buffered arm as the
        client's r-th pull→train→push loop."""
        if n_iterations < 1:
            raise ValueError(f"n_iterations must be >= 1, got {n_iterations}")
        if tail_alpha <= 0 or scale_s <= 0 or cap_s <= 0:
            raise ValueError(
                "tail_alpha, scale_s and cap_s must be positive, got "
                f"{tail_alpha}/{scale_s}/{cap_s}"
            )
        if not 0.0 <= gust_p <= 1.0 or not 0.0 < gust_floor <= 1.0:
            raise ValueError(
                f"gust_p in [0, 1] and gust_floor in (0, 1] required, got "
                f"{gust_p}/{gust_floor}"
            )
        names = sorted(clients)
        if not names:
            raise ValueError("storm needs at least one client")
        faults = [Fault(kind=STRAGGLER_STORM, round=1)]
        for name in names:
            # Per-client stream seeded from (seed, name): a client's delay
            # sequence is independent of cohort size or of the other
            # clients' draw order.
            rng = random.Random(f"{seed}/{name}")
            for it in range(1, n_iterations + 1):
                if rng.random() < gust_p:
                    delay = cap_s * rng.uniform(gust_floor, 1.0)
                else:
                    delay = min(cap_s, scale_s * rng.paretovariate(tail_alpha))
                faults.append(
                    Fault(
                        kind=STRAGGLER_DELAY,
                        round=it,
                        client=name,
                        delay_s=round(delay, 4),
                    )
                )
        return cls(faults)
