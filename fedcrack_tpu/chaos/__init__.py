"""Deterministic fault injection for both federation planes.

The reference system hangs forever when any client dies mid-round
(fl_server.py's collect barrier, SURVEY.md §2.4/§5.3). This package makes
the opposite claim TESTABLE: every failure mode the port hardens against is
a seeded, replayable chaos scenario — client crashes at each upload phase,
stragglers, network flaps, poisoned payloads (corrupt / truncated / NaN /
stale-replay), mid-round server kill-and-restart, mesh-plane
preemption / silent numerical corruption, and serving-plane faults
(hot-swap installed mid-batch, device loss during a served batch —
``ServeChaos`` for the round-10 serving plane's batcher).

Split: :mod:`plan` is the pure, seeded fault schedule;
:mod:`inject` adapts it to the transport client (``FedClient(chaos=...)``)
and the mesh driver (``run_mesh_federation(fault_injector=...)``). Nothing
here runs in production paths unless a plan is explicitly attached — the
hooks are a ``None`` check when disabled.

The scenario suite lives in tests/test_chaos.py (tier-1, CPU, seconds);
``python -m fedcrack_tpu.tools.chaos_drill`` runs the kill→restart recovery
drill standalone and times it (bench.py's ``detail.chaos_recovery``).
"""

from fedcrack_tpu.chaos.inject import (
    ClientChaos,
    InjectedCrash,
    InjectedDeviceFailure,
    InjectedRpcError,
    MeshChaos,
    ServeChaos,
)
from fedcrack_tpu.chaos.plan import (
    ALL_KINDS,
    CLIENT_KINDS,
    CRASH_AFTER_UPLOAD,
    CRASH_BEFORE_UPLOAD,
    CRASH_DURING_UPLOAD,
    CORRUPT_PAYLOAD,
    MESH_DEVICE_FAIL,
    MESH_KINDS,
    MESH_NONFINITE,
    NAN_UPDATE,
    NETWORK_FLAP,
    SECAGG_DROPOUT,
    SERVE_DEVICE_LOSS,
    SERVE_KINDS,
    SERVE_SWAP_MIDFLIGHT,
    STALE_REPLAY,
    STRAGGLER_DELAY,
    TRUNCATE_PAYLOAD,
    Fault,
    FaultPlan,
)

__all__ = [
    "ALL_KINDS",
    "CLIENT_KINDS",
    "CRASH_AFTER_UPLOAD",
    "CRASH_BEFORE_UPLOAD",
    "CRASH_DURING_UPLOAD",
    "CORRUPT_PAYLOAD",
    "ClientChaos",
    "Fault",
    "FaultPlan",
    "InjectedCrash",
    "InjectedDeviceFailure",
    "InjectedRpcError",
    "MESH_DEVICE_FAIL",
    "MESH_KINDS",
    "MESH_NONFINITE",
    "MeshChaos",
    "NAN_UPDATE",
    "NETWORK_FLAP",
    "SECAGG_DROPOUT",
    "SERVE_DEVICE_LOSS",
    "SERVE_KINDS",
    "SERVE_SWAP_MIDFLIGHT",
    "STALE_REPLAY",
    "STRAGGLER_DELAY",
    "ServeChaos",
    "TRUNCATE_PAYLOAD",
]
