"""Edge-tier gRPC plumbing: the upstream half of a hierarchical aggregator.

An edge node speaks DOWN to its leaves as an aggregator
(:class:`fedcrack_tpu.fed.tree.EdgeAggregator`) and UP to the root as an
ordinary protocol client: it enrolls under its edge id, pulls the round
base, and reports its shard's partial average as one ``TrainDone`` whose
``sample_count`` is the shard's sample SUM — the root's existing
sample-weighted FedAvg then reduces edge partials to exactly the flat
weighted mean, with no root-side changes. This module is that upstream
half as a minimal synchronous caller (one message per call on the shared
bidi method, the reference's own usage pattern); the full
:class:`fedcrack_tpu.transport.client.FedClient` stays the LEAF driver —
an edge needs none of its training loop, polling or chaos hooks.
"""

from __future__ import annotations

from typing import Any, Callable

from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.transport.codec import decode_scalar_map


def raw_caller(
    port: int, host: str = "127.0.0.1", timeout_s: float = 10.0
) -> tuple[Any, Callable]:
    """One-message-per-call raw client on the shared bidi method: returns
    ``(channel, call)`` where ``call(ClientMessage) -> ServerMessage``.
    The scripted-harness workhorse (tools/chaos_drill, the tree drills) —
    deterministic, no retry schedule, fails loudly."""
    import grpc

    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import METHOD, SERVICE_NAME

    channel = grpc.insecure_channel(f"{host}:{port}")
    method = channel.stream_stream(
        f"/{SERVICE_NAME}/{METHOD}",
        request_serializer=pb.ClientMessage.SerializeToString,
        response_deserializer=pb.ServerMessage.FromString,
    )

    def call(msg):
        return next(iter(method(iter([msg]), timeout=timeout_s, wait_for_ready=True)))

    return channel, call


class EdgeRelay:
    """The edge→root control-plane session: enroll, pull the round base,
    push the partial, adopt the root's new global.

    The root sees a cohort of edge ids — quorum, deadline shrink,
    statefile recovery and update sanitation all apply to edges exactly as
    they would to clients (the r8 machinery generalizing per tier is the
    point, not an accident)."""

    def __init__(self, edge_id: str, port: int, host: str = "127.0.0.1",
                 timeout_s: float = 10.0):
        self.edge_id = edge_id
        self._channel, self._call = raw_caller(port, host, timeout_s)

    def _msg(self):
        from fedcrack_tpu.transport import transport_pb2 as pb

        return pb.ClientMessage(cname=self.edge_id)

    def enroll(self) -> dict:
        """Register the edge in the root's cohort; returns the handshake
        config map (current_round / model_version / codec knobs)."""
        msg = self._msg()
        msg.ready.SetInParent()
        rep = self._call(msg)
        if rep.status != R.SW:
            raise RuntimeError(
                f"edge {self.edge_id} not enrolled at root: {rep.status}"
            )
        return dict(decode_scalar_map(rep.config))

    def pull(self) -> bytes:
        """The root's current broadcast blob — the round base this edge's
        leaves train against and framed deltas decode against."""
        msg = self._msg()
        msg.pull.SetInParent()
        return self._call(msg).weights

    def push_partial(
        self,
        round_idx: int,
        blob: bytes,
        total_samples: int,
        trace_ctx: str = "",
    ) -> tuple[str, bytes, dict]:
        """Report the shard's partial average for ``round_idx``. Returns
        ``(status, new_global_blob_or_empty, config)`` — RESP_ARY/FIN carry
        the root's round average, which the edge adopts as its leaves'
        next base (never its own partial). ``trace_ctx`` (round 16) is the
        edge flush span's wire context (``EdgeAggregator.last_partial_ctx``
        / ``flush_partial``'s ``info["trace_ctx"]``), carried in-band so
        the root re-parents the edge onto its flush span exactly like a
        client push."""
        from fedcrack_tpu.transport.codec import encode_scalar_map

        msg = self._msg()
        msg.done.round = int(round_idx)
        msg.done.weights = blob
        msg.done.sample_count = int(total_samples)
        if trace_ctx:
            encode_scalar_map(msg.done.metrics, {"__trace": trace_ctx})
        rep = self._call(msg)
        return rep.status, rep.weights, dict(decode_scalar_map(rep.config))

    def poll(self, model_version: int, round_idx: int) -> tuple[str, bytes, dict]:
        """Version poll against the root (WAIT until the round closes)."""
        msg = self._msg()
        msg.poll.model_version = int(model_version)
        msg.poll.round = int(round_idx)
        rep = self._call(msg)
        return rep.status, rep.weights, dict(decode_scalar_map(rep.config))

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "EdgeRelay":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
