from fedcrack_tpu.transport.client import FedClient  # noqa: F401
from fedcrack_tpu.transport.service import FedServer  # noqa: F401
