"""The federated client driver.

The reference's client lifecycle is one 100-line function driving six RPC
call sites with hardcoded sleeps (reference: fl_client.py:77-175, SURVEY.md
§3.2). Here the same phases — enroll → pull → train → report → poll/advance —
are a small loop around an injected ``train_fn``, so the driver is testable
with a fake trainer and the real TPU trainer plugs in unchanged.

Each control message is one short-lived call on the shared bidi method
(mirroring the reference's usage pattern of one ``stub.transport(...)`` per
message). Transient channel errors retry with jittered exponential backoff
under a per-call retry budget, while non-retryable codes (bad request, bad
credentials) surface immediately — the reference crashed on any hiccup.
The retry schedule is exercised under injected flaps and server restarts
by the chaos suite (``FedClient(chaos=...)`` attaches a
``fedcrack_tpu.chaos`` fault hook; None in production).
"""

from __future__ import annotations

import logging
import os
import random
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import grpc

from fedcrack_tpu.compress import get_codec
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.registry import REGISTRY
from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.native import crc32c
from fedcrack_tpu.transport import transport_pb2 as pb
from fedcrack_tpu.transport.codec import decode_scalar_map, encode_scalar_map
from fedcrack_tpu.transport.service import METHOD, SERVICE_NAME, channel_options

log = logging.getLogger("fedcrack.client")

# train_fn(weights_blob, round[, hparams]) -> (weights_blob, sample_count,
# metrics). The optional third parameter receives the server's in-band
# training hyperparameters from the enroll handshake (local_epochs,
# learning_rate, fedprox_mu); two-parameter trainers are also accepted.
TrainFn = Callable[..., tuple[bytes, int, dict[str, float]]]

# The reference chunked file uploads at 100 MB (fl_client.py:36); 4 MiB keeps
# each control message small while still amortizing the per-call overhead.
DEFAULT_CHUNK_BYTES = 4 * 1024 * 1024

# gRPC codes that a retry can never fix: the request itself is wrong
# (malformed, unknown method) or the peer has decided about THIS caller
# (bad credentials, policy). Retrying them on the transient-failure schedule
# — which the client previously did for every code — just burns the retry
# budget and hammers the server with requests it already refused.
NON_RETRYABLE_CODES = frozenset(
    {
        grpc.StatusCode.INVALID_ARGUMENT,
        grpc.StatusCode.UNIMPLEMENTED,
        grpc.StatusCode.PERMISSION_DENIED,
        grpc.StatusCode.UNAUTHENTICATED,
        grpc.StatusCode.FAILED_PRECONDITION,
        grpc.StatusCode.OUT_OF_RANGE,
    }
)


def default_cname() -> str:
    """A fresh unique client name — the reference drew client{randint(1,100000)}
    with possible collisions (fl_client.py:26)."""
    return f"client-{uuid.uuid4().hex[:8]}"


@dataclass
class SessionResult:
    cname: str
    rounds_completed: int = 0
    final_weights: bytes | None = None
    enrolled: bool = False
    history: list[dict] = field(default_factory=list)


class FedClient:
    def __init__(
        self,
        config: FedConfig,
        train_fn: TrainFn,
        cname: str | None = None,
        port: int | None = None,
        poll_period_s: float | None = None,
        max_retries: int = 5,
        call_timeout_s: float = 300.0,
        retry_budget_s: float = 120.0,
        upload_paths: Sequence[str] = (),
        chaos: Any | None = None,
    ):
        self.config = config
        self.train_fn = train_fn
        import inspect

        try:
            n_params = len(inspect.signature(train_fn).parameters)
        except (TypeError, ValueError):
            n_params = 2
        self._train_takes_hparams = n_params >= 3
        # Server hyperparameters from the enroll handshake (set in
        # run_session; exposed for callers/tests).
        self.server_hparams: dict[str, Any] = {}
        # Upload codec, replaced by the negotiated one at enroll. Null until
        # then — today's raw bytes.
        self.codec = get_codec("null")
        # Files shipped to the server's log sink after the final round
        # (reference C2.1: the 'L' chunked uploader, fl_client.py:35-50 —
        # present there but its call site was commented out; enabled here).
        self.upload_paths = tuple(upload_paths)
        self.cname = cname or default_cname()
        self.port = port if port is not None else config.port
        self.poll_period_s = (
            poll_period_s if poll_period_s is not None else config.poll_period_s
        )
        self.max_retries = max_retries
        self.call_timeout_s = call_timeout_s
        # Total retry budget per CALL: however the attempt/backoff schedule
        # is configured, one call never spends more than this much wall
        # clock retrying (stragglers must eventually fail, not hang).
        self.retry_budget_s = retry_budget_s
        # Deterministic per-client jitter source: backoff sleeps are spread
        # over [0.5, 1.5) x the nominal delay so a cohort knocked over by
        # one server restart does not stampede back in lockstep.
        self._jitter = random.Random(self.cname)
        # Optional fault injector (fedcrack_tpu.chaos.inject.ClientChaos);
        # None costs one attribute check per call.
        self._chaos = chaos

    # -- wire helpers --

    def _connect(self) -> tuple[grpc.Channel, Any]:
        target = f"{self.config.host}:{self.port}"
        options = channel_options(self.config.max_message_mb)
        if self.config.tls_ca:
            # TLS channel, verifying the server against the configured root.
            # When the server demands client certs (mTLS), this client
            # presents its own tls_cert/tls_key. The reference always
            # dialed an insecure channel (fl_client.py:181).
            with open(self.config.tls_ca, "rb") as f:
                ca = f.read()
            key = cert = None
            if self.config.tls_cert and self.config.tls_key:
                with open(self.config.tls_key, "rb") as f:
                    key = f.read()
                with open(self.config.tls_cert, "rb") as f:
                    cert = f.read()
            creds = grpc.ssl_channel_credentials(
                root_certificates=ca, private_key=key, certificate_chain=cert
            )
            channel = grpc.secure_channel(target, creds, options=options)
        else:
            if self.config.auth_token and not self.config.allow_insecure_token:
                # Role-aware re-check at the actual channel build: the config
                # validation accepts auth_token + tls_cert/tls_key (a valid
                # SERVER config), but a CLIENT encrypts only via tls_ca — a
                # client reusing the server's config file would otherwise
                # pass validation and still ship the secret in cleartext.
                raise ValueError(
                    "auth_token over a plaintext client channel: set tls_ca "
                    "to verify the server over TLS, or allow_insecure_token "
                    "for loopback/testing"
                )
            channel = grpc.insecure_channel(target, options=options)
        method = channel.stream_stream(
            f"/{SERVICE_NAME}/{METHOD}",
            request_serializer=pb.ClientMessage.SerializeToString,
            response_deserializer=pb.ServerMessage.FromString,
        )
        return channel, method

    def _call(self, method, msg: pb.ClientMessage) -> pb.ServerMessage:
        delay = 0.2
        deadline = time.monotonic() + self.retry_budget_s
        for attempt in range(self.max_retries):
            try:
                if self._chaos is not None:
                    # Inside the try: an injected flap takes the same
                    # except-path a real UNAVAILABLE would.
                    self._chaos.before_send(self.cname, msg)
                # wait_for_ready rides out a server that is still importing
                # JAX / building its global model before binding the port
                responses = method(
                    iter([msg]),
                    timeout=self.call_timeout_s,
                    wait_for_ready=True,
                )
                for resp in responses:
                    if self._chaos is not None:
                        self._chaos.after_reply(self.cname, msg, resp)
                    return resp
                raise RuntimeError("stream closed without a reply")
            except grpc.RpcError as e:
                code = e.code()
                if code in NON_RETRYABLE_CODES:
                    # A retry cannot fix these; surface them immediately
                    # instead of spending the whole schedule re-asking.
                    raise
                sleep_s = delay * (0.5 + self._jitter.random())
                if (
                    attempt == self.max_retries - 1
                    or time.monotonic() + sleep_s > deadline
                ):
                    raise
                log.warning("rpc failed (%s); retrying in %.1fs", code, sleep_s)
                REGISTRY.counter(
                    "client_retries_total",
                    "transient-RPC retries spent by the transport client "
                    "(non-retryable codes surface immediately, uncounted)",
                ).inc()
                time.sleep(sleep_s)
                delay = min(delay * 2, 5.0)
        raise AssertionError("unreachable")

    def _msg(self) -> pb.ClientMessage:
        return pb.ClientMessage(cname=self.cname, token=self.config.auth_token)

    def _count_wire(self, direction: str, n_bytes: int, codec: str | None = None) -> None:
        """Transport-plane byte accounting: uploads are labeled with the
        negotiated codec (the r12 compression win is visible per codec),
        broadcasts/pulls with 'raw'."""
        if n_bytes:
            REGISTRY.counter(
                "client_wire_bytes_total",
                "weight bytes moved by the transport client, by direction "
                "and codec",
                labels=("direction", "codec"),
            ).labels(
                direction=direction, codec=codec or "raw"
            ).inc(n_bytes)

    def _count_resync(self) -> None:
        REGISTRY.counter(
            "client_resyncs_total",
            "NOT_WAIT resyncs absorbed (upload never averaged; codec "
            "cross-round state rolled back)",
        ).inc()

    # -- the session --

    def run_session(self) -> SessionResult:
        result = SessionResult(cname=self.cname)
        channel, method = self._connect()
        try:
            # Phase 1: enroll (reference 'R', fl_client.py:84-96)
            msg = self._msg()
            msg.ready.SetInParent()
            enroll_cfg: dict[str, Any] = {"current_round": 0}
            if self.config.secagg:
                # Secure aggregation (round 23): ship the masking seed
                # in-band at enroll. The seed is the deterministic
                # name-derived one BOTH ends can compute, so a server
                # that never saw this key (or a client that never sent
                # it) still lands on the same roster entry.
                from fedcrack_tpu.privacy.secagg import client_seed

                enroll_cfg["__secagg_seed"] = client_seed(self.cname)
            encode_scalar_map(msg.ready.config, enroll_cfg)
            with tracing.span("client.enroll", cname=self.cname):
                rep = self._call(method, msg)
            cfg = decode_scalar_map(rep.config)
            if rep.status != R.SW:
                log.info("%s not enrolled: %s", self.cname, rep.status)
                return result
            result.enrolled = True
            current_round = int(cfg["current_round"])
            max_rounds = int(cfg["max_train_round"])
            model_version = int(cfg["model_version"])
            self.server_hparams = {
                k: cfg[k]
                for k in (
                    "local_epochs",
                    "learning_rate",
                    "fedprox_mu",
                    "wire_dtype",
                    "update_codec",
                    "topk_fraction",
                )
                if k in cfg
            }
            # Compressed update transport (round 12): the server advertises
            # the upload codec in-band like every other hyperparameter. The
            # codec instance is PER CLIENT and lives for the whole session —
            # TopKDelta's error-feedback accumulator is cross-round state.
            self.codec = get_codec(
                str(cfg.get("update_codec", "null") or "null"),
                topk_fraction=float(cfg.get("topk_fraction", 0.01) or 0.01),
                client_tag=self.cname,
            )
            # Secure aggregation (round 23): the SERVER's advertisement
            # decides, like update_codec — a client launched without the
            # flag still masks when the federation demands it (the roster
            # entry falls back to the same name-derived seed both ends
            # compute).
            secagg_on = bool(cfg.get("secagg", False))
            secagg_bits = int(cfg.get("secagg_bits", 24) or 24)

            if str(cfg.get("mode", "sync") or "sync") == "buffered":
                # Async federation (round 14): the server runs FedBuff
                # buffered aggregation — no round barrier to block on, so
                # the session becomes a continuous pull→train→push loop.
                return self._run_buffered(
                    method, result, max_rounds=int(cfg["max_train_round"])
                )

            # Phase 2: pull global weights (reference 'P', fl_client.py:99-102)
            msg = self._msg()
            msg.pull.SetInParent()
            with tracing.span(
                "client.pull",
                trace=tracing.version_trace(model_version),
                cname=self.cname,
            ):
                weights = self._call(method, msg).weights
            self._count_wire("down", len(weights))

            while True:
                # One trace id per update lifecycle, derived from the base
                # version every party learns in-band (spans.version_trace):
                # the flush that averages this round's uploads, the swap
                # installing it and the first batch served from it all join
                # the same trace — stitchable by tools/trace_stitch.py.
                trace = tracing.version_trace(model_version)
                # Phase 3: announce training (reference 'T', fl_client.py:106-107)
                msg = self._msg()
                msg.training.round = current_round
                rep_t = self._call(method, msg)
                roster: dict[str, int] | None = None
                if secagg_on:
                    # The masking roster freezes at ENROLL->RUNNING; an
                    # eager client can announce before the window closes,
                    # so poll the notice until the reply carries it. The
                    # roster is per-FEDERATION (frozen once); re-fetching
                    # per round keeps the loop stateless across the
                    # silent-cohort reopen, which re-freezes it.
                    import json as _json

                    deadline = time.monotonic() + self.retry_budget_s
                    while True:
                        tcfg = decode_scalar_map(rep_t.config)
                        if "__secagg_roster" in tcfg:
                            roster = {
                                str(n): int(s)
                                for n, s in _json.loads(
                                    tcfg["__secagg_roster"]
                                ).items()
                            }
                            break
                        if time.monotonic() >= deadline:
                            raise RuntimeError(
                                "secagg masking roster never arrived: the "
                                "round machine did not reach RUNNING within "
                                f"{self.retry_budget_s:.0f}s"
                            )
                        time.sleep(self.poll_period_s)
                        msg = self._msg()
                        msg.training.round = current_round
                        rep_t = self._call(method, msg)

                # Phase 4: local fit (reference: manage_train, §3.3)
                # `weights` at this point is the round BASE — the global
                # blob the server broadcast for this round. Delta codecs
                # encode (trained - base) against it, pinned server-side by
                # the frame's base_version == this round's model_version.
                round_base = weights
                train_ctx = tracing.TraceContext(
                    trace, f"train:{self.cname}:r{current_round}"
                )
                with tracing.span(
                    "client.train",
                    trace=trace,
                    cname=self.cname,
                    round=current_round,
                    ctx=train_ctx.to_wire(),
                ) as train_span:
                    if self._train_takes_hparams:
                        weights, n_samples, metrics = self.train_fn(
                            weights, current_round, self.server_hparams
                        )
                    else:
                        weights, n_samples, metrics = self.train_fn(
                            weights, current_round
                        )

                # Phase 5: report (reference 'D', fl_client.py:124-127).
                # The upload is the codec's encoding; local `weights` stay
                # the full trained blob (the codec only shapes the wire).
                if secagg_on:
                    # Pairwise-masked fixed-point upload (round 23): the
                    # round index folds into every roster seed so no
                    # one-time pad repeats across rounds. Config
                    # validation pins update_codec="null" under secagg,
                    # so no codec cross-round state exists to roll back.
                    from fedcrack_tpu.fed.serialization import tree_from_bytes
                    from fedcrack_tpu.privacy.secagg import (
                        mask_update,
                        round_roster,
                    )

                    upload = mask_update(
                        tree_from_bytes(weights),
                        cname=self.cname,
                        n_samples=n_samples,
                        roster=round_roster(roster, current_round),
                        bits=secagg_bits,
                    )
                else:
                    upload = self.codec.encode_update(
                        weights,
                        round_base,
                        round=current_round,
                        base_version=model_version,
                    )
                result.history.append(
                    {
                        "round": current_round,
                        "upload_bytes": len(upload),
                        **metrics,
                    }
                )
                msg = self._msg()
                msg.done.round = current_round
                msg.done.weights = upload
                msg.done.sample_count = n_samples
                encode_scalar_map(
                    msg.done.metrics,
                    {k: float(v) for k, v in metrics.items()},
                )
                # In-band trace propagation (round 16): the push's wire
                # context rides the metrics map like every other in-band
                # field — the server re-parents it onto the flush span.
                # Attached only when tracing is live; the key never
                # collides with a training metric (floats only above).
                push_ctx = tracing.TraceContext(
                    trace, f"push:{self.cname}:r{current_round}"
                )
                if tracing.current() is not None:
                    encode_scalar_map(
                        msg.done.metrics, {"__trace": push_ctx.to_wire()}
                    )
                wire_codec = "secagg" if secagg_on else self.codec.name
                self._count_wire("up", len(upload), wire_codec)
                with tracing.span(
                    "client.push",
                    trace=trace,
                    parent=train_span.span_id if train_span else None,
                    cname=self.cname,
                    upload_bytes=len(upload),
                    codec=wire_codec,
                    ctx=push_ctx.to_wire(),
                ):
                    rep = self._call(method, msg)

                if rep.status == R.NOT_WAIT:
                    # Straggler past quorum: a NOT_WAIT on the TrainDone
                    # reply ITSELF means the round closed WITHOUT this
                    # upload (rounds.py stale-round resync) — whatever
                    # cross-round state the codec committed at encode (the
                    # top-k mass dropped from the error-feedback
                    # accumulator) was never applied to the global. Give it
                    # back, or it is lost forever. A NOT_WAIT from the
                    # post-accept poll below is the OPPOSITE case — the
                    # accepted upload WAS averaged and a new round is ready
                    # — so the rollback must key on the direct reply only
                    # (rolling back aggregated mass would re-transmit it
                    # next round: applied twice, not 'only delayed').
                    self.codec.rollback_last()
                    self._count_resync()
                if rep.status == R.RESP_ACY:
                    rep = self._poll(method, model_version, current_round)
                if rep.status == R.REJECTED:
                    raise RuntimeError(
                        f"server rejected update: {decode_scalar_map(rep.config)}"
                    )
                # RESP_ARY / NOT_WAIT / FIN all carry the round average
                if rep.weights:
                    weights = rep.weights
                result.rounds_completed = current_round
                cfg = decode_scalar_map(rep.config)
                if rep.status == R.FIN or current_round >= max_rounds:
                    result.final_weights = weights
                    self._upload_all(method)
                    return result
                current_round = int(cfg["current_round"])
                model_version = int(cfg["model_version"])
        finally:
            channel.close()

    # -- the buffered-async session (round 14) --

    def _run_buffered(self, method, result: SessionResult, max_rounds: int) -> SessionResult:
        """The FedBuff client loop: pull the current global (the reply's
        config names the version it IS — the base the upload's delta is
        pinned to), train, push, repeat — never waiting on a round close.
        A ``NOT_WAIT`` push reply is the server's resync (the update was
        too stale and will never be averaged — codec cross-round state
        rolls back, exactly the sync straggler contract); ``REJECTED`` is
        sanitation failing loudly; ``FIN`` carries the final global."""
        push_seq = 0
        while True:
            msg = self._msg()
            msg.pull.SetInParent()
            with tracing.span("client.pull", trace="buffered", cname=self.cname):
                rep = self._call(method, msg)
            weights = rep.weights
            self._count_wire("down", len(weights))
            pcfg = decode_scalar_map(rep.config)
            base_version = int(pcfg.get("model_version", 0))
            current_round = int(pcfg.get("current_round", 1))
            # Buffered sessions push many times per client: the lifecycle
            # trace keys on the PULLED base version (the flush that folds
            # this update publishes base+k on the same lineage), and the
            # push sequence keeps the wire context unique per upload.
            trace = tracing.version_trace(base_version)
            if current_round > max_rounds:
                # The federation finished between our last push and this
                # pull: the blob IS the final global.
                result.final_weights = weights
                self._upload_all(method)
                return result

            push_seq += 1
            train_ctx = tracing.TraceContext(
                trace, f"train:{self.cname}:n{push_seq}"
            )
            with tracing.span(
                "client.train",
                trace=trace,
                cname=self.cname,
                round=current_round,
                ctx=train_ctx.to_wire(),
            ) as train_span:
                if self._train_takes_hparams:
                    trained, n_samples, metrics = self.train_fn(
                        weights, current_round, self.server_hparams
                    )
                else:
                    trained, n_samples, metrics = self.train_fn(
                        weights, current_round
                    )

            upload = self.codec.encode_update(
                trained,
                weights,
                round=current_round,
                base_version=base_version,
            )
            msg = self._msg()
            msg.done.round = current_round
            msg.done.weights = upload
            msg.done.sample_count = n_samples
            encode_scalar_map(
                msg.done.metrics, {k: float(v) for k, v in metrics.items()}
            )
            push_ctx = tracing.TraceContext(
                trace, f"push:{self.cname}:n{push_seq}"
            )
            if tracing.current() is not None:
                encode_scalar_map(
                    msg.done.metrics, {"__trace": push_ctx.to_wire()}
                )
            self._count_wire("up", len(upload), self.codec.name)
            with tracing.span(
                "client.push",
                trace=trace,
                parent=train_span.span_id if train_span else None,
                cname=self.cname,
                upload_bytes=len(upload),
                codec=self.codec.name,
                ctx=push_ctx.to_wire(),
            ):
                rep = self._call(method, msg)
            result.history.append(
                {
                    "round": current_round,
                    "base_version": base_version,
                    "upload_bytes": len(upload),
                    "status": rep.status,
                    **metrics,
                }
            )
            if rep.status == R.NOT_WAIT:
                # Resync: this upload was refused (too stale / lost base)
                # and will never be averaged — give the codec its
                # cross-round mass back (see the sync-path comment above).
                self.codec.rollback_last()
                self._count_resync()
            elif rep.status == R.REJECTED:
                raise RuntimeError(
                    f"server rejected update: {decode_scalar_map(rep.config)}"
                )
            elif rep.status in (R.RESP_ACY, R.RESP_ARY):
                result.rounds_completed += 1
            if rep.status == R.FIN:
                result.final_weights = rep.weights or weights
                self._upload_all(method)
                return result

    # -- chunked file upload (reference 'L', fl_client.py:35-50) --

    def _upload_all(self, method) -> None:
        """Best-effort: a failed log upload never fails the session."""
        for path in self.upload_paths:
            try:
                self.upload_file(path, method=method)
            # This loop iterates FILES, not attempts — a failed upload is
            # logged and never re-asked, so there is no retry to audit.
            # fedlint: disable=TRANS001 -- per-file loop, not a retry loop
            except (OSError, grpc.RpcError, RuntimeError):
                log.warning("log upload failed for %s", path, exc_info=True)

    def upload_file(
        self,
        path: str,
        title: str | None = None,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        method=None,
    ) -> None:
        """Stream a file to the server's log sink in chunks. The final chunk
        carries ``last=True`` so the server flushes it to ``logs_dir``."""
        channel = None
        if method is None:
            channel, method = self._connect()
        try:
            title = title or os.path.basename(path)
            size = os.path.getsize(path)
            offset = 0
            with open(path, "rb") as f:
                while True:
                    data = f.read(chunk_bytes)
                    last = offset + len(data) >= size
                    msg = self._msg()
                    msg.log.title = title
                    msg.log.data = data
                    msg.log.offset = offset
                    msg.log.last = last
                    # Integrity framing per chunk (hardware CRC32C when the
                    # native runtime is built); the reference's chunker had
                    # none (fl_client.py:35-50). The server rejects
                    # mismatches, so a corrupt chunk fails loudly here
                    # instead of silently landing bad bytes in the sink.
                    msg.log.crc32c = crc32c(data)
                    rep = self._call(method, msg)
                    if rep.status != "OK":
                        # e.g. the server lost its buffer (restart/flush) and
                        # rejected a gapped offset — surface it instead of
                        # streaming the rest into the void.
                        raise RuntimeError(
                            f"log upload of {path!r} rejected at offset "
                            f"{offset}: {rep.title}"
                        )
                    offset += len(data)
                    if last:
                        break
        finally:
            if channel is not None:
                channel.close()

    def _poll(self, method, model_version: int, current_round: int) -> pb.ServerMessage:
        """Version-poll until the round closes (reference: 20 s loop,
        fl_client.py:136-155)."""
        while True:
            time.sleep(self.poll_period_s)
            msg = self._msg()
            msg.poll.model_version = model_version
            msg.poll.round = current_round
            rep = self._call(method, msg)
            if rep.status != R.WAIT:
                return rep
