"""Protoc-less regeneration of ``transport_pb2.py``.

This container ships the protobuf runtime but neither ``protoc`` nor
``grpcio-tools``, so the generated module cannot be rebuilt the normal way.
``transport.proto`` stays the human-readable source of truth; this script
maintains the generated module by *descriptor surgery*: it parses the
serialized ``FileDescriptorProto`` embedded in the current
``transport_pb2.py``, appends any messages/services defined below that are
missing, and rewrites the module around the new serialized descriptor.
Existing message encodings are untouched (their descriptor bytes pass
through verbatim), so wire compatibility with older peers is preserved by
construction.

A schema test (tests/test_serve.py) asserts the generated descriptor matches
``transport.proto``'s serving-plane section, so the two cannot drift
silently.

Run: ``python -m fedcrack_tpu.transport.regen_pb2``
"""

from __future__ import annotations

import os

from google.protobuf import descriptor_pb2 as dp

HERE = os.path.dirname(os.path.abspath(__file__))
PB2_PATH = os.path.join(HERE, "transport_pb2.py")

F = dp.FieldDescriptorProto


def _field(name, number, ftype, *, optional=False, oneof_index=None, type_name=None):
    f = F(name=name, number=number, type=ftype, label=F.LABEL_OPTIONAL)
    if optional:
        f.proto3_optional = True
    if oneof_index is not None:
        f.oneof_index = oneof_index
    if type_name is not None:
        f.type_name = type_name
    return f


def _predict_request() -> dp.DescriptorProto:
    msg = dp.DescriptorProto(name="PredictRequest")
    msg.field.extend(
        [
            _field("client_id", 1, F.TYPE_STRING),
            _field("request_id", 2, F.TYPE_UINT64),
            _field("height", 3, F.TYPE_INT32),
            _field("width", 4, F.TYPE_INT32),
            _field("channels", 5, F.TYPE_INT32),
            _field("image", 6, F.TYPE_BYTES),
            _field("offset", 7, F.TYPE_INT64),
            _field("last", 8, F.TYPE_BOOL),
            # proto3 optional = a one-field synthetic oneof, mirroring
            # LogChunk.crc32c's presence semantics.
            _field("crc32c", 9, F.TYPE_FIXED32, optional=True, oneof_index=0),
            _field("threshold", 10, F.TYPE_FLOAT),
            _field("deadline_ms", 11, F.TYPE_FLOAT),
        ]
    )
    msg.oneof_decl.add(name="_crc32c")
    return msg


def _predict_response() -> dp.DescriptorProto:
    msg = dp.DescriptorProto(name="PredictResponse")
    msg.field.extend(
        [
            _field("request_id", 1, F.TYPE_UINT64),
            _field("status", 2, F.TYPE_STRING),
            _field("mask", 3, F.TYPE_BYTES),
            _field("model_version", 4, F.TYPE_INT32),
            _field("latency_ms", 5, F.TYPE_FLOAT),
            _field("queue_ms", 6, F.TYPE_FLOAT),
            _field("height", 7, F.TYPE_INT32),
            _field("width", 8, F.TYPE_INT32),
            _field("title", 9, F.TYPE_STRING),
        ]
    )
    return msg


def _stream_open() -> dp.DescriptorProto:
    msg = dp.DescriptorProto(name="StreamOpen")
    msg.field.extend(
        [
            _field("height", 1, F.TYPE_INT32),
            _field("width", 2, F.TYPE_INT32),
            _field("channels", 3, F.TYPE_INT32),
            _field("threshold", 4, F.TYPE_FLOAT),
            _field("track", 5, F.TYPE_BOOL),
            _field("smooth_alpha", 6, F.TYPE_FLOAT),
        ]
    )
    return msg


def _stream_frame() -> dp.DescriptorProto:
    msg = dp.DescriptorProto(name="StreamFrame")
    msg.field.extend(
        [
            _field("frame_id", 1, F.TYPE_UINT64),
            _field("image", 2, F.TYPE_BYTES),
            _field("offset", 3, F.TYPE_INT64),
            _field("last", 4, F.TYPE_BOOL),
            _field("crc32c", 5, F.TYPE_FIXED32, optional=True, oneof_index=0),
        ]
    )
    msg.oneof_decl.add(name="_crc32c")
    return msg


def _stream_close() -> dp.DescriptorProto:
    return dp.DescriptorProto(name="StreamClose")


def _stream_request() -> dp.DescriptorProto:
    msg = dp.DescriptorProto(name="StreamRequest")
    msg.field.extend(
        [
            _field("stream_id", 1, F.TYPE_STRING),
            _field(
                "open", 2, F.TYPE_MESSAGE,
                oneof_index=0, type_name=".fedcrack.StreamOpen",
            ),
            _field(
                "frame", 3, F.TYPE_MESSAGE,
                oneof_index=0, type_name=".fedcrack.StreamFrame",
            ),
            _field(
                "close", 4, F.TYPE_MESSAGE,
                oneof_index=0, type_name=".fedcrack.StreamClose",
            ),
        ]
    )
    msg.oneof_decl.add(name="msg")
    return msg


def _stream_response() -> dp.DescriptorProto:
    msg = dp.DescriptorProto(name="StreamResponse")
    msg.field.extend(
        [
            _field("frame_id", 1, F.TYPE_UINT64),
            _field("status", 2, F.TYPE_STRING),
            _field("mask", 3, F.TYPE_BYTES),
            _field("model_version", 4, F.TYPE_INT32),
            _field("latency_ms", 5, F.TYPE_FLOAT),
            _field("height", 6, F.TYPE_INT32),
            _field("width", 7, F.TYPE_INT32),
            _field("title", 8, F.TYPE_STRING),
            _field("tiles_total", 9, F.TYPE_INT32),
            _field("tiles_computed", 10, F.TYPE_INT32),
            _field("cache_hits", 11, F.TYPE_INT32),
            _field("full_rerun", 12, F.TYPE_BOOL),
            _field("tracks_json", 13, F.TYPE_STRING),
        ]
    )
    return msg


def _serve_plane() -> dp.ServiceDescriptorProto:
    svc = dp.ServiceDescriptorProto(name="ServePlane")
    svc.method.add(
        name="Predict",
        input_type=".fedcrack.PredictRequest",
        output_type=".fedcrack.PredictResponse",
        client_streaming=True,
        server_streaming=True,
    )
    svc.method.add(
        name="StreamPredict",
        input_type=".fedcrack.StreamRequest",
        output_type=".fedcrack.StreamResponse",
        client_streaming=True,
        server_streaming=True,
    )
    return svc


def current_serialized_pb() -> bytes:
    """The serialized FileDescriptorProto embedded in the checked-in module,
    read without importing it (imports would register the OLD descriptor in
    the default pool and poison this process)."""
    import ast

    with open(PB2_PATH) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and getattr(node.func, "attr", "") == "AddSerializedFile"
        ):
            return ast.literal_eval(node.args[0])
    raise RuntimeError(f"no AddSerializedFile call found in {PB2_PATH}")


def build_file_descriptor() -> dp.FileDescriptorProto:
    fdp = dp.FileDescriptorProto.FromString(current_serialized_pb())
    have_msgs = {m.name for m in fdp.message_type}
    for make in (
        _predict_request,
        _predict_response,
        _stream_open,
        _stream_frame,
        _stream_close,
        _stream_request,
        _stream_response,
    ):
        msg = make()
        if msg.name not in have_msgs:
            fdp.message_type.append(msg)
    have_svcs = {s.name for s in fdp.service}
    if "ServePlane" not in have_svcs:
        fdp.service.append(_serve_plane())
    else:
        # The service already exists from an earlier round: append any
        # methods defined here that it is missing (same pass-through rule
        # as messages — existing method descriptors are untouched).
        for svc in fdp.service:
            if svc.name != "ServePlane":
                continue
            have_methods = {m.name for m in svc.method}
            for m in _serve_plane().method:
                if m.name not in have_methods:
                    svc.method.append(m)
    return fdp


TEMPLATE = '''# -*- coding: utf-8 -*-
# Generated by fedcrack_tpu/transport/regen_pb2.py — protoc-less descriptor
# surgery over the previous generated module (this image has no protoc /
# grpcio-tools). Source of truth: transport.proto.  DO NOT EDIT BY HAND.
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()




DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'transport_pb2', globals())
# @@protoc_insertion_point(module_scope)
'''


def main() -> None:
    fdp = build_file_descriptor()
    blob = fdp.SerializeToString()
    with open(PB2_PATH, "w") as f:
        f.write(TEMPLATE.format(blob=blob))
    print(f"wrote {PB2_PATH} ({len(blob)} descriptor bytes)")


if __name__ == "__main__":
    main()
