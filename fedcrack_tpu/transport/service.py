"""The gRPC control-plane server.

Replaces the reference's threaded servicer + global mutable state
(reference: fl_server.py:209-226 — a 10-thread executor mutating module
globals with no locks, SURVEY.md §2.2(6)) with an asyncio server whose only
shared state is the immutable ``ServerState``, advanced under one lock: a
single-writer round machine by construction. The weight payloads on this
plane are msgpack pytrees; on a TPU pod the data plane moves to ICI
collectives (``fedcrack_tpu.parallel``) and this server carries control
traffic only.

The service is bound by hand (no grpc_python_plugin codegen): one
stream-stream method handler registered under the proto's full name.
Both send and receive caps are raised — the reference's send cap was lost
to a ``'grcp.'`` typo (fl_server.py:215, SURVEY.md §2.2(7)).
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import logging
import os
import re
import time
from typing import Any, AsyncIterator, Callable

import grpc

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.health import ledger as _health_ledger
from fedcrack_tpu.obs import flight
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.registry import DEFAULT_VERSIONS_BUCKETS, REGISTRY
from fedcrack_tpu.transport import transport_pb2 as pb
from fedcrack_tpu.transport.codec import event_from_message, message_from_reply

log = logging.getLogger("fedcrack.server")


def _reason_class(reason: str) -> str:
    """Collapse a free-form rejection message into a stable label value —
    label cardinality must stay bounded (a per-message label would mint one
    time series per distinct error string)."""
    r = reason.lower()
    if "not in cohort" in r:
        return "not_in_cohort"
    if "stale" in r:  # "too stale: ...", "stale round", un-retained base
        return "stale"
    if "rejected" in r or "frame" in r:
        return "sanitation"
    return "other"


def observe_transition(
    prev: R.ServerState,
    state: R.ServerState,
    event: R.Event,
    reply: R.Reply,
    wall_s: float,
) -> None:
    """Diff ONE state transition into the process metric registry — the
    fed-plane instrumentation point. The round machines (``fed/rounds``,
    ``fed/buffered``) stay pure functions; the single-writer ``_apply``
    already sees every (prev, next) pair, so the metrics are a projection
    of the same transitions the statefile and history record — they cannot
    drift from protocol truth. Counter bumps are dict ops + a leaf lock
    (microseconds); nothing here touches the reply path's latency budget.
    """
    if isinstance(event, R.TrainDone):
        # Flight-recorder feed (round 16): one compact event per update
        # outcome — a post-mortem's last-N-seconds view of the fed plane.
        flight.note(
            "fed.update",
            cname=event.cname,
            round=event.round,
            status=reply.status,
            bytes=len(event.blob),
        )
        updates = REGISTRY.counter(
            "fed_updates_total",
            "client updates by outcome: accepted into the round/buffer, "
            "resynced (NOT_WAIT, never averaged), or rejected by reason",
            labels=("result",),
        )
        REGISTRY.counter(
            "fed_wire_bytes_total",
            "weight bytes crossing the control plane (up = client uploads, "
            "down = broadcast pulls)",
            labels=("direction",),
        ).labels(direction="up").inc(len(event.blob))
        if reply.status in (R.RESP_ACY, R.RESP_ARY) or (
            # The upload that closes the FINAL round is aggregated and
            # answered FIN directly (a late upload after FIN carries no
            # version bump and stays uncounted — it was never averaged).
            reply.status == R.FIN
            and state.model_version != prev.model_version
        ):
            updates.labels(result="accepted").inc()
        elif reply.status == R.NOT_WAIT:
            updates.labels(result="resync").inc()
            REGISTRY.counter(
                "fed_resyncs_total",
                "NOT_WAIT resyncs: uploads refused past quorum close or "
                "past max_staleness, sender handed the current global",
            ).inc()
        elif reply.status == R.REJECTED:
            reason = _reason_class(str(reply.config.get("reason", "")))
            updates.labels(result=f"rejected_{reason}").inc()
    elif isinstance(event, R.PullWeights) and reply.blob:
        REGISTRY.counter(
            "fed_wire_bytes_total",
            "weight bytes crossing the control plane (up = client uploads, "
            "down = broadcast pulls)",
            labels=("direction",),
        ).labels(direction="down").inc(len(reply.blob))
    REGISTRY.gauge(
        "fed_buffer_fill_total",
        "accepted-but-unflushed updates in the FedBuff buffer (0 in sync "
        "mode)",
    ).set(len(state.buffer))
    if state.config.mode == "buffered" and state.config.buffer_k > 0:
        REGISTRY.gauge(
            "fed_buffer_fill_ratio",
            "buffer fill as a fraction of buffer_k (1.0 = flush imminent)",
        ).set(len(state.buffer) / state.config.buffer_k)
    if state.model_version != prev.model_version:
        flight.note(
            "fed.flush",
            version=state.model_version,
            round=prev.current_round,
            wall_s=round(wall_s, 6),
        )
        REGISTRY.counter(
            "fed_global_versions_total",
            "global model version publishes (sync aggregations + buffered "
            "flushes)",
        ).inc(state.model_version - prev.model_version)
        REGISTRY.counter(
            "fed_rounds_total",
            "completed aggregations (one history entry each)",
        ).inc()
        REGISTRY.histogram(
            "fed_flush_seconds",
            "wall clock of the version-publishing transition (the sorted "
            "fold + FedOpt step + re-serialization)",
        ).observe(wall_s)
        entry = state.history[-1] if state.history else {}
        staleness = entry.get("staleness")
        if isinstance(staleness, (list, tuple)):
            hist = REGISTRY.histogram(
                "fed_update_staleness_versions",
                "staleness (model versions behind the global) of each "
                "update at the flush that averaged it",
                buckets=DEFAULT_VERSIONS_BUCKETS,
            )
            for s in staleness:
                hist.observe(float(s))
        # Health ledger export (round 18): every flush just re-scored the
        # cohort's update geometry — publish the bounded anomaly gauges.
        # Telemetry must never break the protocol: the export is pure dict
        # math but the try keeps a malformed restored ledger non-fatal.
        try:
            _health_ledger.export_anomaly_metrics(state.ledger)
        except Exception:
            log.exception("anomaly metric export failed (non-fatal)")

SERVICE_NAME = "fedcrack.FedControl"
METHOD = "Session"


def _safe_component(name: str) -> str:
    """One path component from an untrusted wire string: separators and
    parent references become underscores, never a traversal. Injective:
    any name the sanitizer had to rewrite gets a suffix hashed from the
    original bytes, so distinct wire names ('a/b' vs 'a_b') can never
    collapse onto one file and overwrite each other."""
    cleaned = name.replace("\\", "_").replace("/", "_").replace("..", "_")
    cleaned = cleaned.strip() or "_"
    cleaned = cleaned.lstrip(".") or "_"
    # Names that already look like a hash-suffixed rewrite are suffixed too:
    # otherwise sending the literal "sanitized.digest" form of another
    # client's unsafe name (the digest is computable by anyone) would land
    # on that client's file. Branch ranges stay disjoint — identity output
    # never matches the tail pattern, suffixed output always does. 16 hex
    # chars (64 bits) keeps the collision out of brute-force range — with 8
    # an attacker could enumerate variants cleaning to the same stem until
    # the truncated digest matched a victim's. The 8-hex alternative keeps
    # guarding files a pre-16-hex server wrote into a persisted logs_dir.
    if cleaned != name or re.search(r"\.[0-9a-f]{8}(?:[0-9a-f]{8})?$", cleaned):
        digest = hashlib.sha256(name.encode("utf-8", "surrogatepass")).hexdigest()[:16]
        cleaned = f"{cleaned}.{digest}"
    return cleaned


def _load_best(path: str) -> dict | None:
    """Seed best-model tracking from an existing best file's sidecar, so a
    resumed server never overwrites a better on-disk model with its first
    post-restart eval. A sidecar whose sha256 does not match the model file
    (crash between the pair's two renames) is ignored — the torn pair is
    then eligible for replacement by the next eval."""
    import hashlib as _hashlib
    import json
    import math

    side = f"{path}.json"
    try:
        with open(side) as f:
            entry = json.load(f)
        with open(path, "rb") as f:
            blob = f.read()
    except (OSError, ValueError):
        return None
    if entry.get("sha256") != _hashlib.sha256(blob).hexdigest():
        log.warning("best-model sidecar %s does not match %s; ignoring", side, path)
        return None
    loss = entry.get("loss")
    if not isinstance(loss, (int, float)) or not math.isfinite(loss):
        return None
    return entry


def _write_best(path: str, blob: bytes, entry: dict) -> None:
    """Persist the best global model (msgpack bytes) plus a JSON sidecar with
    the eval metrics that earned it. Each file lands via the shared atomic
    writer (write-temp + fsync + rename — a kill between write and rename
    leaves the old file intact plus an ignorable temp, pinned by the chaos
    suite), so neither is ever torn; the pair is two renames, so the sidecar
    carries a sha256 of the blob — a crash between the renames is detectable
    by hashing the model file against its sidecar."""
    import hashlib as _hashlib
    import json

    from fedcrack_tpu.ioutils import atomic_write_bytes

    atomic_write_bytes(path, blob)
    side = f"{path}.json"
    payload = json.dumps(
        {**entry, "sha256": _hashlib.sha256(blob).hexdigest()}, sort_keys=True
    )
    atomic_write_bytes(side, payload.encode("utf-8"))


def channel_options(max_message_mb: int) -> list[tuple[str, int]]:
    cap = max_message_mb * 1024 * 1024
    return [
        ("grpc.max_send_message_length", cap),
        ("grpc.max_receive_message_length", cap),
    ]


class FedServer:
    """Owns the round state machine and serves it over gRPC."""

    def __init__(
        self,
        config: FedConfig,
        global_variables: Any,
        clock: Callable[[], float] = time.monotonic,
        tick_period_s: float = 1.0,
        checkpointer: Any | None = None,
        metrics: Any | None = None,
        eval_fn: Callable[[bytes], dict] | None = None,
    ):
        self.config = config
        self.state = R.initial_state(config, global_variables)
        self._checkpointer = checkpointer
        if checkpointer is not None:
            # Resume from the latest checkpoint when one exists: keep the
            # round counter / version / averaged weights, re-open enrollment
            # (SURVEY.md §5.4 — the reference server forgot rounds on restart).
            from fedcrack_tpu.ckpt import restore_server_state

            resumed = restore_server_state(checkpointer, config, global_variables)
            if resumed is not None:
                log.info(
                    "resuming from checkpoint: round %d, model_version %d",
                    resumed.current_round,
                    resumed.model_version,
                )
                self.state = resumed
        self._state_path = config.state_path or None
        if self._state_path is not None:
            # Mid-round durable state (config.state_path): strictly finer-
            # grained than the orbax round-boundary checkpoint — it also
            # holds cohort/phase/received. Prefer it unless the checkpoint
            # is NEWER (a statefile left over from an older run); at equal
            # model_version the statefile wins because only it can carry
            # the current round's already-received updates.
            from fedcrack_tpu.ckpt import load_state_file

            mid = load_state_file(self._state_path, config)
            if mid is not None and mid.model_version >= self.state.model_version:
                log.info(
                    "resuming mid-round state: round %d, phase %s, "
                    "%d update(s) already received",
                    mid.current_round,
                    mid.phase,
                    len(mid.received),
                )
                self.state = mid
        # Startup contract for the configurable message cap (round 12): the
        # largest message either direction ever carries — the dense
        # broadcast blob down, or the worst-case update (dense for "null",
        # the codec's frame bound otherwise) up — must fit the configured
        # gRPC cap, or the federation would boot and then die on the first
        # weight transfer. Fail at construction, where the operator reads
        # the config error, not mid-round.
        import jax
        import numpy as np

        from fedcrack_tpu.compress import FRAME_OVERHEAD_BYTES, encoded_bytes_model

        cap = config.max_message_mb * 1024 * 1024
        # Leaf-aware worst case: encoded_bytes_model prices the per-leaf
        # floors (topk's k >= 1, manifest entries) a dense-length fraction
        # misses on many-small-leaf models; 64 B/leaf covers manifest keys
        # and zlib-level-1 expansion on incompressible payloads. The dense
        # blob stays in the max: legacy raw uploads are always accepted.
        leaf_sizes = [
            int(np.asarray(leaf).size)
            for leaf in jax.tree_util.tree_leaves(self.state.template)
        ]
        frame_budget = (
            encoded_bytes_model(
                leaf_sizes, config.update_codec, topk_fraction=config.topk_fraction
            )
            + FRAME_OVERHEAD_BYTES
            + 64 * len(leaf_sizes)
        )
        budget = max(
            len(self.state.global_blob),
            len(self.state.broadcast_blob),
            frame_budget,
        )
        if budget > cap:
            raise ValueError(
                f"max_message_mb={config.max_message_mb} cannot carry this "
                f"model: worst-case weight message is {budget} bytes "
                f"({budget / (1024 * 1024):.1f} MiB) under "
                f"update_codec={config.update_codec!r} — raise "
                "max_message_mb (server and clients must agree)"
            )
        self._metrics = metrics
        # Per-round evaluation of the freshly aggregated global model
        # (the reference designed this — trainNextRound, fl_server.py:27-37 —
        # but its call site is commented out; here it runs for real).
        # eval_fn(global_blob) -> {"loss": ..., "iou": ..., ...}.
        self._eval_fn = eval_fn
        self.eval_history: list[dict] = []
        # Best-global-model retention by eval loss (config.best_path) — the
        # federated analog of the reference's best-val ModelCheckpoint
        # (test/Segmentation.py:177-179). Seeded from the existing file's
        # sidecar so restarts can't regress what's on disk.
        self.best_eval: dict | None = (
            _load_best(config.best_path) if config.best_path else None
        )
        if self.best_eval is not None:
            log.info(
                "resuming best-model tracking: loss %.6f from round %s",
                self.best_eval["loss"],
                self.best_eval.get("round"),
            )
        self._best_lock = asyncio.Lock()
        self._clock = clock
        self._tick_period_s = tick_period_s
        self._lock = asyncio.Lock()
        # Serializes checkpoint writes: orbax CheckpointManager is not
        # thread-safe and saves must land in version order.
        self._ckpt_lock = asyncio.Lock()
        # Statefile snapshots coalesce latest-wins: _apply parks the newest
        # state in _state_pending and every queued save task drains whatever
        # is newest WHEN IT RUNS (or nothing, if an earlier task already
        # wrote it). A burst of N membership/upload changes costs one or two
        # full-state writes, not N — same durability, no fsync amplification.
        # The lock serializes the writes themselves; only the event loop
        # touches _state_pending.
        self._state_lock = asyncio.Lock()
        self._state_pending: R.ServerState | None = None
        self._bg_tasks: set[asyncio.Task] = set()
        # Cross-process trace links (round 16): the wire context each
        # client's latest accepted upload carried, re-parented onto the
        # flush span that averages it. Pure observability — never
        # persisted (a restart degrades the flush to fewer links, exactly
        # the dropped-context contract), so statefile bytes stay a pure
        # function of protocol state.
        self._trace_links: dict[str, str] = {}
        self._server: grpc.aio.Server | None = None
        self._tick_task: asyncio.Task | None = None
        self.bound_port: int | None = None
        self.finished = asyncio.Event()
        if self.state.phase == R.PHASE_FINISHED:
            # A restore can land directly on FINISHED; serve_until_finished
            # must not wait for an aggregation that will never come.
            self.finished.set()

    # -- state advancement (the only two writers, both under the lock) --

    @staticmethod
    def _persist_sig(state: R.ServerState) -> tuple:
        """What a mid-round snapshot must not miss: membership, phase,
        round/version, and WHICH updates are held. Log-chunk churn is
        deliberately excluded — snapshotting the whole state per 4 MiB
        upload chunk would turn the log path into a disk-write amplifier
        (logs still ride along with the next membership/upload change)."""
        return (
            state.phase,
            state.current_round,
            state.model_version,
            tuple(sorted(state.received)),
            state.cohort,
            state.departed,
            state.failed_rounds,
            tuple(sorted(state.rejected)),
            # Buffered-async mode (round 14): WHICH updates sit in the
            # buffer and WHAT each client last pulled must both be durable
            # — a restarted server decodes the next framed delta against
            # the pulled record, and a mid-buffer kill must resume with the
            # accepted updates intact. Both empty in sync mode (zero extra
            # snapshots there).
            tuple(sorted((e["cname"], e["seq"]) for e in state.buffer)),
            tuple(sorted(state.pulled.items())),
            # Privacy plane (round 23): the enroll-time secagg seeds, the
            # frozen masking roster, and the DP accountant's step counts.
            # Seeds usually land with a cohort change, but a re-sent seed
            # alone must still snapshot — the unmask step after a restart
            # reconstructs masks from exactly these.
            tuple(sorted(state.secagg_seeds.items())),
            tuple(sorted(state.secagg_roster.items())),
            tuple(sorted(state.privacy_steps.items())),
        )

    async def _apply(self, event: R.Event) -> R.Reply:
        async with self._lock:
            prev_state = self.state
            prev_version = self.state.model_version
            prev_sig = (
                self._persist_sig(self.state) if self._state_path else None
            )
            t_apply = time.perf_counter()
            self.state, reply = R.transition(self.state, event)
            apply_s = time.perf_counter() - t_apply
            if self.state.phase == R.PHASE_FINISHED:
                self.finished.set()
            state = self.state
            if (
                isinstance(event, R.TrainDone)
                and event.trace_ctx
                and reply.status in (R.RESP_ACY, R.RESP_ARY, R.FIN)
                and tracing.TraceContext.from_wire(event.trace_ctx) is not None
            ):
                # Accepted upload carrying a parseable wire context: stamp
                # it for the flush that will average it. A malformed
                # context was already degraded to "" at the transport edge
                # or fails from_wire here — parentless, never an error.
                self._trace_links[event.cname] = event.trace_ctx
        try:
            observe_transition(prev_state, state, event, reply, apply_s)
        except Exception:  # telemetry must never break the protocol
            log.exception("metric observation failed; protocol unaffected")
        if state.model_version != prev_version:
            # Zero-duration correlation marker: the flush/aggregation span
            # (the transition itself was timed above). Round 16: it lives
            # on the version-lineage trace with the DETERMINISTIC context
            # `flush:vV` (spans.flush_context — the serve plane links its
            # swap to it from the statefile's version alone), and carries
            # the originating clients' wire contexts as `links`, so ONE
            # trace id follows client train → push → flush → swap → first
            # batch served.
            entry = state.history[-1] if state.history else {}
            links = []
            for cname in entry.get("clients", ()):
                wire = self._trace_links.pop(cname, None)
                if wire is not None:
                    links.append(wire)
            fctx = tracing.flush_context(state.model_version)
            with tracing.span(
                "fed.flush",
                trace=fctx.trace,
                ctx=fctx.to_wire(),
                links=sorted(links),
                version=state.model_version,
                round=prev_state.current_round,
                apply_s=round(apply_s, 6),
            ):
                pass
        if self._state_path and self._persist_sig(state) != prev_sig:
            # Durable mid-round state: persisted off the serving path like
            # the checkpoint — a stalled disk must not freeze the protocol,
            # and a failed save must not swallow the reply.
            self._state_pending = state
            task = asyncio.create_task(self._save_state_file())
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
        if self._metrics is not None and state.model_version != prev_version:
            # One structured record per completed round (SURVEY.md §5.5 —
            # the reference printed banners instead). Offloaded like the
            # checkpoint save: a stalled flush must not freeze the loop.
            entry = state.history[-1]
            # bytes_per_round mirrors the mesh plane's RoundRecord counter
            # name (round 12): the wire bytes this round's uploads cost.
            task = asyncio.create_task(
                asyncio.to_thread(
                    self._metrics.log,
                    "round",
                    bytes_per_round=entry.get("bytes_received"),
                    **entry,
                )
            )
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
        if self._eval_fn is not None and state.model_version != prev_version:
            task = asyncio.create_task(self._run_eval(state))
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
        if self._checkpointer is not None and state.model_version != prev_version:
            # Aggregation happened: persist as a background task so the
            # barrier-completing client's RESP_ARY reply (and the tick loop)
            # never stalls on disk I/O. The checkpoint lock keeps saves
            # single-flight and in version order (tasks start in creation
            # order and asyncio.Lock wakes waiters FIFO). Durability is
            # best-effort relative to protocol liveness: a failed save must
            # not swallow the reply.
            task = asyncio.create_task(self._save_checkpoint(state))
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
        return reply

    async def _save_state_file(self) -> None:
        from fedcrack_tpu.ckpt import save_state_file

        async with self._state_lock:
            state = self._state_pending
            if state is None:
                return  # an earlier task already wrote a newer snapshot
            self._state_pending = None
            try:
                await asyncio.to_thread(save_state_file, self._state_path, state)
            except Exception:
                log.exception(
                    "statefile save failed for round %d", state.current_round
                )

    async def _save_checkpoint(self, state: R.ServerState) -> None:
        from fedcrack_tpu.ckpt import save_server_state

        async with self._ckpt_lock:
            try:
                await asyncio.to_thread(save_server_state, self._checkpointer, state)
            except Exception:
                log.exception(
                    "checkpoint save failed for model_version %d",
                    state.model_version,
                )

    async def _run_eval(self, state: R.ServerState) -> None:
        """Evaluate the round's aggregated model off the serving path."""
        rnd = state.history[-1]["round"] if state.history else state.current_round
        try:
            result = await asyncio.to_thread(self._eval_fn, state.global_blob)
        except Exception:
            log.exception("server-side eval failed for round %s", rnd)
            return
        entry = {"round": rnd, "model_version": state.model_version, **result}
        self.eval_history.append(entry)
        log.info("global model eval: %s", entry)
        if self._metrics is not None:
            await asyncio.to_thread(self._metrics.log, "server_eval", **entry)
        if self.config.best_path and "loss" in result:
            import math

            # Compare-and-write under one lock: per-round eval tasks can
            # overlap, and the best file must never mix rounds. Non-finite
            # losses never qualify — a NaN admitted as "best" would compare
            # False against every later loss and pin the file forever.
            loss = result["loss"]
            async with self._best_lock:
                if math.isfinite(loss) and (
                    self.best_eval is None or loss < self.best_eval["loss"]
                ):
                    try:
                        await asyncio.to_thread(
                            _write_best, self.config.best_path, state.global_blob, entry
                        )
                    except Exception:
                        # best_eval deliberately NOT updated: a failed write
                        # must leave later (worse-than-this, better-than-disk)
                        # rounds eligible to replace what's actually on disk.
                        log.exception("best-model save failed for round %s", rnd)
                    else:
                        self.best_eval = entry
                        log.info(
                            "new best global model (loss %.6f, round %s) -> %s",
                            result["loss"], rnd, self.config.best_path,
                        )

    async def _tick_forever(self) -> None:
        """Drives pure time effects: enrollment-window close and round
        deadlines (the reference used a background Timer thread mutating
        globals, fl_server.py:40-52)."""
        while True:
            await asyncio.sleep(self._tick_period_s)
            await self._apply(R.Tick(now=self._clock()))

    # -- gRPC plumbing --

    async def _session(
        self, request_iterator: AsyncIterator[pb.ClientMessage], context
    ) -> AsyncIterator[pb.ServerMessage]:
        token = self.config.auth_token
        async for msg in request_iterator:
            if token and not hmac.compare_digest(
                msg.token.encode("utf-8"), token.encode("utf-8")
            ):
                # Authentication precedes ALL protocol processing: an
                # unauthenticated Ready/TrainDone/LogChunk never reaches the
                # state machine (the reference accepted anything that could
                # reach the port, fl_client.py:181). The stream terminates
                # after the rejection: on a kept-open stream every further
                # message (up to max_message_mb) would be fully received and
                # parsed before its token check, letting an unauthenticated
                # peer sustain bandwidth/memory pressure on one RPC.
                yield pb.ServerMessage(status=R.REJECTED, title="unauthenticated")
                return
            try:
                # Decode (and CRC-verify log chunks) off the event loop: the
                # pure-Python CRC fallback costs ~0.3 s/MiB, which inline
                # would stall every other client's stream and the
                # round-deadline ticks behind one large upload.
                event = await asyncio.to_thread(
                    event_from_message, msg, now=self._clock()
                )
            except (ValueError, TypeError) as e:
                yield pb.ServerMessage(status=R.REJECTED, title=str(e))
                continue
            reply = await self._apply(event)
            if (
                isinstance(event, R.LogChunk)
                and msg.log.last
                and reply.status == "OK"  # a rejected chunk must not flush
                and self.config.logs_dir
            ):
                # Final chunk of an upload: flush the accumulated bytes to
                # the log sink (reference C1.5 wrote client TensorBoard
                # events under ./logs with string-surgery re-rooting,
                # fl_server.py:84-89; here the path is sanitized).
                await self._flush_log(event.cname, event.title)
            log.debug("%s -> %s", type(event).__name__, reply.status)
            yield message_from_reply(reply)

    async def _flush_log(self, cname: str, title: str) -> None:
        data = self.state.logs.get(f"{cname}/{title}")
        if data is None:
            return
        path = os.path.join(
            self.config.logs_dir, _safe_component(cname), _safe_component(title)
        )

        def write() -> None:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "wb") as f:
                f.write(data)

        try:
            await asyncio.to_thread(write)
            log.info("log upload %s/%s -> %s (%d bytes)", cname, title, path, len(data))
        except OSError:
            log.exception("failed to flush log upload %s/%s", cname, title)
            return
        async with self._lock:
            # Drop the flushed buffer so memory does not grow with uploads —
            # unless a fresh upload for the same title already started.
            if self.state.logs.get(f"{cname}/{title}") == data:
                self.state = R.drop_log(self.state, cname, title)

    def _build(self) -> grpc.aio.Server:
        # Config validation BEFORE any aio construction: misconfiguration
        # must surface as its own error, not whatever state the thread's
        # event loop happens to be in.
        if self.config.tls_ca and not (self.config.tls_cert and self.config.tls_key):
            # tls_ca alone is a CLIENT configuration (root to verify the
            # server). A server launched with it but no cert/key would
            # silently bind plaintext while the operator believes mTLS is
            # on — the exact failure mode the cert/key pairing check
            # prevents.
            raise ValueError(
                "server has tls_ca but no tls_cert/tls_key: client-cert "
                "enforcement (mTLS) requires the server's own TLS identity"
            )
        server = grpc.aio.server(options=channel_options(self.config.max_message_mb))
        handler = grpc.stream_stream_rpc_method_handler(
            self._session,
            request_deserializer=pb.ClientMessage.FromString,
            response_serializer=pb.ServerMessage.SerializeToString,
        )
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, {METHOD: handler}),)
        )
        address = f"{self.config.host}:{self.config.port}"
        if self.config.tls_cert and self.config.tls_key:
            # TLS server credentials (the reference served an insecure port
            # only, fl_server.py:218). With tls_ca set too, client certs
            # are required — mTLS across the trust boundary.
            with open(self.config.tls_key, "rb") as f:
                key = f.read()
            with open(self.config.tls_cert, "rb") as f:
                cert = f.read()
            ca = None
            if self.config.tls_ca:
                with open(self.config.tls_ca, "rb") as f:
                    ca = f.read()
            creds = grpc.ssl_server_credentials(
                [(key, cert)],
                root_certificates=ca,
                require_client_auth=ca is not None,
            )
            self.bound_port = server.add_secure_port(address, creds)
        else:
            self.bound_port = server.add_insecure_port(address)
        return server

    async def start(self) -> int:
        """Bind + serve; returns the bound port (0 in config -> ephemeral)."""
        self._server = self._build()
        await self._server.start()
        self._tick_task = asyncio.create_task(self._tick_forever())
        log.info("serving on %s:%s", self.config.host, self.bound_port)
        return self.bound_port

    async def stop(self, grace: float = 1.0) -> None:
        if self._tick_task is not None:
            self._tick_task.cancel()
        # Drain in-flight checkpoint saves before shutdown.
        if self._bg_tasks:
            await asyncio.gather(*tuple(self._bg_tasks), return_exceptions=True)
        if self._server is not None:
            await self._server.stop(grace)

    async def serve_until_finished(
        self, extra_grace_s: float | None = None
    ) -> R.ServerState:
        """Run a full federation: serve until the round machine reaches FIN,
        linger so every client can learn FIN and pull the final weights, then
        stop. The default grace covers two client poll periods — a slower
        client's next version poll must find the server alive, or it is
        stranded retrying against a dead port."""
        if extra_grace_s is None:
            extra_grace_s = max(5.0, 2.0 * self.config.poll_period_s + 5.0)
        await self.start()
        await self.finished.wait()
        await asyncio.sleep(extra_grace_s)
        await self.stop()
        return self.state


class ServerThread:
    """Runs a :class:`FedServer` on its own asyncio loop in a daemon thread —
    the in-process harness for tests, benchmarks and notebooks."""

    def __init__(self, server: FedServer):
        import threading

        self.server = server
        self.loop = asyncio.new_event_loop()
        self.port: int | None = None
        self._started = threading.Event()
        self._killed = False
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.port = self.loop.run_until_complete(self.server.start())
        self._started.set()
        self.loop.run_forever()

    def __enter__(self) -> "ServerThread":
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("server failed to start")
        return self

    def kill(self) -> None:
        """Simulate a process death mid-federation (the chaos harness's
        server-kill fault): the gRPC ports close with ZERO grace — in-flight
        client RPCs fail the way they would against a SIGKILLed process —
        and the loop stops without draining background tasks, so no
        goodbye checkpoint is written. Durable state is whatever the atomic
        statefile writer had already renamed. A killed ServerThread's
        context exit is a no-op; boot a fresh FedServer over the same
        state/checkpoint paths to model the restart."""
        if self._killed:
            return
        self._killed = True

        def _die():
            async def seq():
                try:
                    if self.server._server is not None:
                        # 0-grace: abort streams now (a dead process would
                        # not finish them either); the port must actually
                        # close so the restarted server can rebind it.
                        await self.server._server.stop(0)
                finally:
                    self.loop.stop()

            asyncio.ensure_future(seq())

        self.loop.call_soon_threadsafe(_die)
        self._thread.join(timeout=10)

    def __exit__(self, *exc) -> None:
        if self._killed:
            return
        fut = asyncio.run_coroutine_threadsafe(self.server.stop(grace=0.5), self.loop)
        try:
            fut.result(timeout=5)
        finally:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._thread.join(timeout=5)

    @property
    def state(self) -> R.ServerState:
        return self.server.state
