"""Protobuf <-> state-machine translation.

The transport layer is a dumb adapter: every inbound ``ClientMessage``
becomes exactly one ``fed.rounds`` event (stamped with the server clock),
and every ``Reply`` becomes one ``ServerMessage``. All protocol logic lives
in ``fed/rounds.py``; nothing here inspects state.
"""

from __future__ import annotations

from typing import Any, Mapping

from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.transport import transport_pb2 as pb


def encode_scalar_map(target, values: Mapping[str, Any]) -> None:
    """Fill a proto map<string, Scalar> from a python dict."""
    for key, val in values.items():
        scalar = target[key]
        if isinstance(val, bool):
            scalar.as_bool = val
        elif isinstance(val, int):
            scalar.as_int = val
        elif isinstance(val, float):
            scalar.as_double = val
        elif isinstance(val, str):
            scalar.as_string = val
        elif isinstance(val, bytes):
            scalar.as_bytes = val
        else:
            raise TypeError(f"unsupported scalar {key}={val!r} ({type(val).__name__})")


def decode_scalar_map(source) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for key, scalar in source.items():
        kind = scalar.WhichOneof("value")
        out[key] = getattr(scalar, kind) if kind else None
    return out


def event_from_message(msg: pb.ClientMessage, now: float) -> R.Event:
    """One inbound proto message -> one state-machine event."""
    kind = msg.WhichOneof("msg")
    cname = msg.cname
    if kind == "ready":
        # In-band secagg seed exchange (round 23): the client's masking
        # seed rides the enroll config under "__secagg_seed". Anything
        # that is not an int degrades to "no seed" — the server then
        # falls back to the deterministic name-derived seed, so a
        # malformed scalar can never strand an enrollment.
        secagg_seed = None
        if "__secagg_seed" in msg.ready.config:
            scalar = msg.ready.config["__secagg_seed"]
            if scalar.WhichOneof("value") == "as_int":
                secagg_seed = int(scalar.as_int)
        return R.Ready(cname=cname, now=now, secagg_seed=secagg_seed)
    if kind == "pull":
        return R.PullWeights(cname=cname, now=now)
    if kind == "training":
        return R.TrainingNotice(cname=cname, now=now)
    if kind == "log":
        if msg.log.HasField("crc32c"):
            from fedcrack_tpu.native import crc32c

            got = crc32c(msg.log.data)
            if got != msg.log.crc32c:
                raise ValueError(
                    f"log chunk checksum mismatch for {msg.log.title!r} at "
                    f"offset {msg.log.offset}: computed {got:#010x}, "
                    f"declared {msg.log.crc32c:#010x}"
                )
        return R.LogChunk(
            cname=cname,
            title=msg.log.title,
            data=msg.log.data,
            now=now,
            offset=msg.log.offset,
        )
    if kind == "done":
        # In-band trace context (round 16): the push's wire context rides
        # the metrics map under "__trace". Anything that is not a plain
        # string degrades to "no context" — a corrupted context must cost
        # the sender its span parentage, never the upload.
        trace_ctx = ""
        if "__trace" in msg.done.metrics:
            scalar = msg.done.metrics["__trace"]
            if scalar.WhichOneof("value") == "as_string":
                trace_ctx = scalar.as_string
        return R.TrainDone(
            cname=cname,
            round=msg.done.round,
            blob=msg.done.weights,
            num_samples=msg.done.sample_count,
            now=now,
            trace_ctx=trace_ctx,
        )
    if kind == "poll":
        return R.VersionPoll(
            cname=cname,
            model_version=msg.poll.model_version,
            round=msg.poll.round,
            now=now,
        )
    raise ValueError(f"empty or unknown ClientMessage (oneof={kind!r})")


def message_from_reply(reply: R.Reply) -> pb.ServerMessage:
    out = pb.ServerMessage(status=reply.status)
    if reply.config:
        encode_scalar_map(out.config, reply.config)
    if reply.blob is not None:
        out.weights = reply.blob
    if reply.title is not None:
        out.title = reply.title
    return out
