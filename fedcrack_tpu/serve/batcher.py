"""Dynamic micro-batching for the serving plane.

Requests for one bucket accumulate until ``max_batch`` lanes are waiting or
``max_delay_ms`` has elapsed since the oldest queued request, then run as ONE
compiled bucket program invocation (padded to the compiled batch). One worker
thread per bucket keeps the device pipeline full without ever interleaving
two batches of the same program.

Weight-version semantics (the hot-swap contract, test-pinned in
tests/test_serve.py): the worker takes ONE weights snapshot per batch — the
**request-boundary barrier** — immediately before dispatch, and every request
in that batch is answered from that snapshot. A swap installed while a batch
is in flight affects only subsequent batches; no batch ever mixes versions
(no torn reads), and no in-flight request is dropped.

Accounting: per-request queue + total latency through a bounded
:class:`fedcrack_tpu.obs.metrics.StreamingPercentiles` reservoir (p50/p95/p99),
per-request deadline misses (requests past deadline are still served — the
SLO counter is the signal, dropping is a policy this plane does not adopt),
and the swap gap (idle time between the last pre-swap batch and the first
post-swap batch). Optionally tees per-batch records into a
``MetricsLogger``.

Chaos: a :class:`fedcrack_tpu.chaos.inject.ServeChaos` hook runs between the
snapshot and the dispatch of every batch. It may force a swap mid-flight
(the snapshot already taken must win — exactly the torn-read scenario the
barrier exists to prevent) or raise an injected device failure, which the
worker retries with a fresh snapshot; requests survive both.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.metrics import StreamingPercentiles
from fedcrack_tpu.obs.registry import REGISTRY

# Bounded batch retries under injected/real device failures: a request is
# only failed (never silently dropped) when every attempt raised.
MAX_BATCH_ATTEMPTS = 3


@dataclass
class PredictResult:
    """What the front door needs to answer one request."""

    probs: np.ndarray          # [S, S, 1] float32 bucket-resolution output
    model_version: int
    queue_ms: float
    latency_ms: float
    deadline_missed: bool


@dataclass
class _Request:
    image: np.ndarray          # [S, S, 3] uint8, already bucket-shaped
    t_submit: float
    deadline_s: float | None   # absolute monotonic deadline, None = none
    trace: str = ""            # correlation id (req-NNNNNN) for span joins
    future: Future = field(default_factory=Future)


class StaticWeights:
    """Minimal weights source for swap-less serving and tests: a constant
    (version, variables) snapshot matching the hot-swap manager's API."""

    def __init__(self, variables: Any, version: int = 0):
        self._snap = (version, variables)

    def snapshot(self) -> tuple[int, Any]:
        return self._snap


class MicroBatcher:
    """Per-bucket micro-batching over one :class:`InferenceEngine`.

    ``weights`` is any object with ``snapshot() -> (version, variables)`` —
    :class:`StaticWeights` or the hot-swap ``ModelVersionManager``.
    """

    def __init__(
        self,
        engine: Any,
        weights: Any,
        *,
        max_delay_ms: float | None = None,
        metrics: Any | None = None,
        chaos: Any | None = None,
        reservoir_capacity: int = 4096,
        replica: int | None = None,
    ):
        self.engine = engine
        self.weights = weights
        # Fleet identity (round 17): stamped on every serve.batch span so a
        # stitched trace shows WHICH replica served a request; None keeps
        # the single-replica span shape byte-identical to round 10.
        self.replica = replica
        self.max_batch = engine.max_batch
        cfg_delay = engine.serve_config.max_delay_ms
        self.max_delay_s = (
            cfg_delay if max_delay_ms is None else max_delay_ms
        ) / 1e3
        self._metrics = metrics
        self._chaos = chaos
        self._queues: dict[int, queue.Queue] = {
            size: queue.Queue() for size in engine.bucket_sizes
        }
        self.latency = StreamingPercentiles(reservoir_capacity)
        self.queue_latency = StreamingPercentiles(reservoir_capacity)
        self._lock = make_lock("serve.batcher.stats")
        self._counts = {
            "submitted": 0,
            "completed": 0,
            "failed": 0,
            "deadline_missed": 0,
            "batches": 0,
            "batch_retries": 0,
        }
        self._per_bucket: dict[int, int] = {s: 0 for s in engine.bucket_sizes}
        self._versions_served: dict[int, int] = {}
        # Registry families cached once (the catalog names are literals per
        # OBS001); per-request updates are then one leaf-lock bump each.
        self._m_requests = REGISTRY.counter(
            "serve_requests_total",
            "requests completed per bucket program",
            labels=("bucket",),
        )
        self._m_latency = REGISTRY.histogram(
            "serve_request_seconds",
            "submit-to-answer latency per bucket (queue + dispatch)",
            labels=("bucket",),
        )
        self._m_queue_wait = REGISTRY.histogram(
            "serve_queue_seconds",
            "submit-to-dispatch queue wait per bucket",
            labels=("bucket",),
        )
        self._m_deadline = REGISTRY.counter(
            "serve_deadline_missed_total",
            "requests answered past their deadline (served, never dropped)",
        )
        self._m_batches = REGISTRY.counter(
            "serve_batches_total", "dispatched micro-batches"
        )
        self._m_retries = REGISTRY.counter(
            "serve_batch_retries_total",
            "batch dispatch retries after a (possibly injected) failure",
        )
        self._m_failed = REGISTRY.counter(
            "serve_failed_requests_total",
            "requests failed loudly after every batch attempt raised",
        )
        self._m_qdepth = REGISTRY.gauge(
            "serve_queue_depth_total",
            "requests waiting across all bucket queues",
        )
        self._last_batch_end: float | None = None
        self._last_version: int | None = None
        # Versions whose first served batch already linked to its swap span
        # (round 16) — linking once per version keeps span files lean.
        self._linked_versions: set[int] = set()
        self.swap_gaps_ms: list[float] = []
        self._running = True
        # drain() halt: unlike close() (which lets workers empty their
        # queues), a draining replica must stop PROMPTLY so queued requests
        # can move to survivors — only the in-flight batch finishes.
        self._halt = False
        self._workers = [
            threading.Thread(target=self._worker, args=(size,), daemon=True)
            for size in engine.bucket_sizes
        ]
        for t in self._workers:
            t.start()

    # ---- submission ----

    def submit(self, image_u8: np.ndarray, deadline_ms: float | None = None) -> Future:
        """Enqueue one bucket-shaped [S, S, 3] uint8 image; resolves to a
        :class:`PredictResult`. Raises immediately on a non-bucket shape."""
        h, w, _ = image_u8.shape
        if h != w or h not in self._queues:
            raise ValueError(
                f"submit() takes exact bucket shapes {self.engine.bucket_sizes}; "
                f"got {image_u8.shape} (route through the front door for "
                f"padding/tiling)"
            )
        now = time.monotonic()
        cfg_deadline = self.engine.serve_config.deadline_ms
        if deadline_ms is None and cfg_deadline > 0:
            deadline_ms = cfg_deadline
        req = _Request(
            image=image_u8,
            t_submit=now,
            deadline_s=(now + deadline_ms / 1e3) if deadline_ms else None,
        )
        # The running check and the enqueue share one locked section, and
        # drain() flips the halt flags under the same lock — so a request
        # either lands in the queue BEFORE a drain begins (the sweep
        # reroutes it) or sees the closed batcher and raises; it can never
        # slip into a halted queue after the sweep and hang its Future.
        with self._lock:
            if not self._running:
                raise RuntimeError("batcher is closed")
            self._counts["submitted"] += 1
            req.trace = f"req-{self._counts['submitted']:06d}"
            self._queues[h].put(req)
        self._m_qdepth.set(sum(q.qsize() for q in self._queues.values()))
        return req.future

    # ---- the per-bucket worker ----

    def _collect(self, size: int) -> list[_Request] | None:
        """Block for the first request, then fill until max_batch or the
        delay window closes. None = shutdown."""
        q = self._queues[size]
        while True:
            if self._halt:
                return None
            try:
                first = q.get(timeout=0.05)
                break
            except queue.Empty:
                if not self._running:
                    return None
        batch = [first]
        t_close = time.monotonic() + self.max_delay_s
        while len(batch) < self.max_batch:
            if self._halt:
                break  # dispatch what we hold; the queue moves to survivors
            remaining = t_close - time.monotonic()
            if remaining <= 0:
                break
            try:
                batch.append(q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _worker(self, size: int) -> None:
        batch_index = 0
        while True:
            batch = self._collect(size)
            if batch is None:
                return
            self._execute(size, batch, batch_index)
            batch_index += 1

    def _execute(self, size: int, batch: list[_Request], batch_index: int) -> None:
        images = np.stack([r.image for r in batch])
        last_err: Exception | None = None
        for attempt in range(MAX_BATCH_ATTEMPTS):
            # Request-boundary barrier: one snapshot per ATTEMPT, taken
            # immediately before dispatch. Everything this batch returns
            # comes from this snapshot, whatever installs meanwhile.
            version, variables = self.weights.snapshot()
            if self._chaos is not None:
                try:
                    self._chaos.on_batch(size, batch_index, attempt)
                except Exception as e:  # injected device loss -> retry
                    last_err = e
                    with self._lock:
                        self._counts["batch_retries"] += 1
                    self._m_retries.inc()
                    continue
            # Round 16: the FIRST batch served on a freshly swapped version
            # joins the swap's version-lineage trace and links to its span
            # — closing the train→serve chain the stitcher reconstructs.
            # Later batches keep the per-bucket trace.
            span_route = {"trace": f"bucket-{size}"}
            swap_ctx_of = getattr(self.weights, "swap_context", None)
            if swap_ctx_of is not None:
                with self._lock:
                    first_on_version = version not in self._linked_versions
                    if first_on_version:
                        self._linked_versions.add(version)
                if first_on_version:
                    wire = swap_ctx_of(version)
                    parsed = tracing.TraceContext.from_wire(wire)
                    if parsed is not None:
                        span_route = {
                            "trace": parsed.trace,
                            "remote_parent": wire,
                        }
            if self.replica is not None:
                span_route["replica"] = self.replica
            try:
                # One span per dispatched batch, joined to its requests by
                # their req-NNNNNN correlation ids and to the swap plane by
                # model_version.
                with tracing.span(
                    "serve.batch",
                    bucket=size,
                    n=len(batch),
                    attempt=attempt,
                    model_version=version,
                    requests=[r.trace for r in batch],
                    **span_route,
                ):
                    t0 = time.monotonic()
                    probs = self.engine.predict_bucket(variables, images)
                    t1 = time.monotonic()
            except Exception as e:
                last_err = e
                with self._lock:
                    self._counts["batch_retries"] += 1
                self._m_retries.inc()
                continue
            self._resolve(batch, probs, version, t0, t1, size)
            return
        # Every attempt failed: requests error out loudly, never hang.
        from fedcrack_tpu.obs import flight

        flight.note(
            "serve.batch_failed", bucket=size, n=len(batch), error=repr(last_err)
        )
        with self._lock:
            self._counts["failed"] += len(batch)
        self._m_failed.inc(len(batch))
        for r in batch:
            r.future.set_exception(
                last_err if last_err is not None else RuntimeError("batch failed")
            )

    def _resolve(self, batch, probs, version, t0, t1, size) -> None:
        with self._lock:
            self._counts["completed"] += len(batch)
            self._counts["batches"] += 1
            self._per_bucket[size] += len(batch)
            self._versions_served[version] = (
                self._versions_served.get(version, 0) + len(batch)
            )
            if self._last_version is not None and version != self._last_version:
                # Swap pause as the served plane sees it: idle gap between
                # the previous batch's completion and this (first post-swap)
                # batch's dispatch. Clamped at 0 — concurrent bucket workers
                # can legitimately overlap across the version boundary.
                gap = (t0 - self._last_batch_end) * 1e3 if self._last_batch_end else 0.0
                self.swap_gaps_ms.append(max(0.0, gap))
            self._last_version = version
            self._last_batch_end = t1
        bucket_lbl = str(size)
        m_latency = self._m_latency.labels(bucket=bucket_lbl)
        m_queue = self._m_queue_wait.labels(bucket=bucket_lbl)
        self._m_requests.labels(bucket=bucket_lbl).inc(len(batch))
        self._m_batches.inc()
        self._m_qdepth.set(sum(q.qsize() for q in self._queues.values()))
        n_missed = 0
        for i, r in enumerate(batch):
            queue_ms = (t0 - r.t_submit) * 1e3
            latency_ms = (t1 - r.t_submit) * 1e3
            missed = r.deadline_s is not None and t1 > r.deadline_s
            n_missed += bool(missed)
            self.queue_latency.add(queue_ms)
            self.latency.add(latency_ms)
            m_queue.observe(queue_ms / 1e3)
            m_latency.observe(latency_ms / 1e3)
            r.future.set_result(
                PredictResult(
                    probs=probs[i],
                    model_version=version,
                    queue_ms=queue_ms,
                    latency_ms=latency_ms,
                    deadline_missed=missed,
                )
            )
        if n_missed:
            with self._lock:
                self._counts["deadline_missed"] += n_missed
            self._m_deadline.inc(n_missed)
        if self._metrics is not None:
            self._metrics.log(
                "serve_batch",
                bucket=size,
                batch=len(batch),
                model_version=version,
                exec_ms=round((t1 - t0) * 1e3, 3),
            )

    # ---- fleet plumbing (round 17) ----

    def outstanding(self) -> int:
        """Requests accepted but not yet answered (queued + in a batch) —
        the router's least-outstanding dispatch key. O(lock)."""
        with self._lock:
            c = self._counts
            return c["submitted"] - c["completed"] - c["failed"]

    def queued(self) -> int:
        """Requests waiting in bucket queues (not yet in a batch)."""
        return sum(q.qsize() for q in self._queues.values())

    def queued_by_bucket(self) -> dict[int, int]:
        """Per-bucket queue depth — the router aggregates these into the
        ``serve_router_queue_depth_total{bucket=...}`` gauges the
        autoscaler scrapes
        (round 22). qsize() is approximate by nature; the controller reads
        it as a pressure signal, not an accounting truth."""
        return {size: q.qsize() for size, q in self._queues.items()}

    def resubmit(self, req: _Request) -> None:
        """Re-enqueue a request object drained from ANOTHER batcher — the
        router's replica-failover path. The request keeps its submit time,
        deadline and Future, so the original caller's handle resolves and
        client-side latency accounting spans the failover."""
        size = req.image.shape[0]
        if size not in self._queues:
            raise ValueError(
                f"resubmit bucket {size} not served here ({self.engine.bucket_sizes})"
            )
        with self._lock:  # same check-and-enqueue atomicity as submit()
            if not self._running:
                raise RuntimeError("batcher is closed")
            self._counts["submitted"] += 1
            self._queues[size].put(req)
        self._m_qdepth.set(sum(q.qsize() for q in self._queues.values()))

    def drain(self) -> list[_Request]:
        """Stop this replica and hand back everything still queued, futures
        UNANSWERED (unlike :meth:`close`, which fails them) — the router
        resubmits them to surviving replicas, so an accepted request rides a
        replica crash instead of erroring. In-flight batches finish on this
        replica first (their snapshot was already taken)."""
        with self._lock:  # serialize vs submit(): see the enqueue comment
            self._halt = True
            self._running = False
        for t in self._workers:
            t.join(timeout=10)
        leftovers: list[_Request] = []
        for q in self._queues.values():
            while True:
                try:
                    leftovers.append(q.get_nowait())
                except queue.Empty:
                    break
        self._m_qdepth.set(0)
        return leftovers

    # ---- observability / shutdown ----

    def stats(self) -> dict:
        """One JSON-safe snapshot: counters, per-bucket traffic, versions
        served, latency percentiles, swap gaps."""
        with self._lock:
            counts = dict(self._counts)
            per_bucket = {str(k): v for k, v in self._per_bucket.items()}
            versions = {str(k): v for k, v in self._versions_served.items()}
            gaps = list(self.swap_gaps_ms)
        return {
            **counts,
            "per_bucket": per_bucket,
            "versions_served": versions,
            "swap_gaps_ms": [round(g, 3) for g in gaps],
            "latency_ms": self.latency.summary(),
            "queue_ms": self.queue_latency.summary(),
        }

    def close(self) -> None:
        """Stop accepting work, let workers drain, fail anything left."""
        self._running = False
        for t in self._workers:
            t.join(timeout=10)
        for q in self._queues.values():
            while True:
                try:
                    r = q.get_nowait()
                except queue.Empty:
                    break
                if not r.future.done():
                    r.future.set_exception(RuntimeError("batcher closed"))

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
