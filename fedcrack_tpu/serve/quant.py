"""Post-training int8 weight quantization for the serve plane (round 17).

Weight-only, per-channel symmetric (Jacob et al., CVPR 2018, §2 without the
activation half): every params leaf with a channel axis is stored as int8
codes plus one float32 scale per output channel, computed DETERMINISTICALLY
from the weight tensor alone — ``scale_c = max(|w[..., c]|) / 127`` — so no
calibration data is needed and the same weights always produce the same
quantized program (byte-determinism discipline). Biases and batch-norm
statistics stay float32 (they are O(channels) bytes and quantizing them buys
nothing). The predict program dequantizes in-graph (``q * scale``), so the
device-resident weights are int8: 4x smaller than float32, which is the
weight-load bandwidth lever forward inference cares about.

The optional activation fake-quant (``ServeConfig.quant_act_fakequant``)
applies dynamic per-tensor symmetric int8 quantize-dequantize to the
pre-sigmoid logits — a deterministic function of the inputs (no calibration),
measuring the activation-quant accuracy headroom at the program boundary.
Interior activations stay in the serving compute dtype; quantizing them is
kernel work queued behind the ROADMAP's hardware session.

The A/B gate (:func:`quant_gate`) is the install-time contract: the
quantized program must reproduce the reference program's masks on a seeded
probe batch at every bucket size (mask IoU >= ``ServeConfig.quant_iou_floor``)
or the install is REFUSED loudly and the replica keeps serving the reference
program — never a silent accuracy cliff. FLOPs honesty: a quantized forward
charges the SAME canonical FLOPs as the reference program
(``obs.flops.resunet_forward_flops``) — int8 does fewer effective bit-ops,
not fewer canonical MACs, so MFU comparisons across the bf16/int8 grid stay
apples-to-apples.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

# Quantized leaves are dicts with exactly these keys; everything else in the
# tree passes through untouched. A dict is a pytree, so the quantized tree
# jits/device_puts like any variables tree. Round 20 adds an fp8 leaf flavor
# (e4m3 codes, same per-channel scale sidecar) for the kernel plane; a tree
# holds ONE flavor, decided by the plane that quantized it.
QKEY, SKEY = "int8_code", "scale"
QKEY_FP8 = "fp8_code"

# fp8 e4m3 (4 exponent / 3 mantissa bits): max finite magnitude 448 — the
# symmetric-scale analog of int8's 127.
FP8_E4M3_MAX = 448.0


class QuantizedVariables:
    """Marker wrapper around a quantized variables pytree.

    The batcher's weights snapshot carries either a plain variables tree
    (reference program) or one of these (quantized program); the engine
    routes on the type, so ONE snapshot-per-batch barrier covers both paths
    and a swap can change program *and* weights atomically.
    """

    def __init__(self, tree: Any):
        self.tree = tree


def _is_qleaf(node: Any) -> bool:
    return isinstance(node, dict) and (
        set(node.keys()) == {QKEY, SKEY} or set(node.keys()) == {QKEY_FP8, SKEY}
    )


def quantize_leaf(w: np.ndarray) -> dict:
    """Per-channel symmetric int8 codes + scales for one weight tensor.

    The LAST axis is the output-channel axis (flax conv kernels are HWIO,
    dense kernels are IO). All-zero channels get scale 1.0 so dequantize is
    exact (0 * 1.0) and never divides by zero."""
    w = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {QKEY: q, SKEY: scale}


def quantize_variables(variables: Any) -> QuantizedVariables:
    """Quantize every params leaf with a channel structure (ndim >= 2);
    biases, BN scales and batch statistics stay float32. Pure function of
    the weights — same tree in, byte-identical quantized tree out."""

    def walk(node, in_params: bool):
        if isinstance(node, dict):
            return {k: walk(v, in_params or k == "params") for k, v in node.items()}
        arr = np.asarray(node)
        if in_params and arr.ndim >= 2:
            return quantize_leaf(arr)
        return arr

    return QuantizedVariables(walk(variables, False))


def quantize_leaf_fp8(w: np.ndarray) -> dict:
    """Per-channel symmetric fp8 e4m3 codes + scales for one weight tensor
    (Micikevicius et al.'s weight format: e4m3 for weights, e5m2 reserved for
    gradients). Same scale discipline as :func:`quantize_leaf` with 448 (the
    e4m3 finite max) in place of 127; all-zero channels get scale 1.0.
    Raises where this jax build has no fp8 dtypes — callers resolve the
    plane first (``jaxcompat.fp8_supported``)."""
    from fedcrack_tpu.jaxcompat import fp8_dtypes

    dts = fp8_dtypes()
    if dts is None:
        raise RuntimeError("this jax build has no fp8 dtypes")
    w = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    scale = np.where(absmax > 0, absmax / FP8_E4M3_MAX, 1.0).astype(np.float32)
    code = np.asarray((w / scale), np.float32).astype(dts[0])
    return {QKEY_FP8: code, SKEY: scale}


def quantize_variables_fp8(variables: Any) -> QuantizedVariables:
    """fp8 twin of :func:`quantize_variables`: same leaf selection (params,
    ndim >= 2), e4m3 codes instead of int8."""

    def walk(node, in_params: bool):
        if isinstance(node, dict):
            return {k: walk(v, in_params or k == "params") for k, v in node.items()}
        arr = np.asarray(node)
        if in_params and arr.ndim >= 2:
            return quantize_leaf_fp8(arr)
        return arr

    return QuantizedVariables(walk(variables, False))


def quantize_for_plane(variables: Any, kernel_plane: str) -> QuantizedVariables:
    """The quantized tree a kernel plane consumes: int8 codes for
    ``reference``/``fused_int8`` (the r17 format), e4m3 codes for ``fp8``.
    Callers pass the engine's EFFECTIVE plane — an fp8 request on a backend
    without fp8 support has already degraded to ``reference`` there, so the
    tree and the compiled program always agree."""
    if kernel_plane == "fp8":
        return quantize_variables_fp8(variables)
    if kernel_plane in ("reference", "fused_int8"):
        return quantize_variables(variables)
    raise ValueError(f"unknown kernel_plane {kernel_plane!r}")


def dequantize_variables(qtree: Any) -> Any:
    """Inverse projection: the float32 tree the quantized program computes
    with. Traceable — called inside the jitted predict program, so XLA sees
    int8 (or fp8) weight inputs and fuses the ``q * scale`` expansion."""

    def walk(node):
        if _is_qleaf(node):
            code = node[QKEY] if QKEY in node else node[QKEY_FP8]
            return code.astype("float32") * node[SKEY]
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qtree)


def quantized_bytes(qtree: Any) -> tuple[int, int]:
    """(quantized_bytes, reference_bytes) over the tree — the memory /
    weight-bandwidth claim, computed not asserted."""
    import jax

    q_bytes = ref_bytes = 0
    for leaf in jax.tree_util.tree_leaves(qtree):
        arr = np.asarray(leaf)
        q_bytes += arr.nbytes
        ref_bytes += arr.size * (4 if arr.dtype == np.int8 else arr.itemsize)
    return q_bytes, ref_bytes


def fake_quant_activations(x):
    """Dynamic per-tensor symmetric int8 quantize-dequantize (traceable).
    Scale is max|x|/127 computed in-graph — deterministic per input, no
    calibration state."""
    import jax.numpy as jnp

    absmax = jnp.max(jnp.abs(x))
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    return jnp.clip(jnp.round(x / scale), -127, 127) * scale


def mask_iou(probs_a: np.ndarray, probs_b: np.ndarray, threshold: float = 0.5) -> float:
    """Intersection-over-union of the thresholded masks; both-empty = 1.0
    (two programs agreeing there is no crack DO agree)."""
    a = np.asarray(probs_a) > threshold
    b = np.asarray(probs_b) > threshold
    union = np.logical_or(a, b).sum()
    if union == 0:
        return 1.0
    return float(np.logical_and(a, b).sum() / union)


@dataclasses.dataclass(frozen=True)
class QuantGateResult:
    """The install-time A/B verdict: per-bucket mask IoU of the quantized
    program vs the reference oracle on the seeded probe batch."""

    passed: bool
    iou: float                    # min over buckets — the gating number
    floor: float
    per_bucket: dict              # {bucket_size: iou}
    probe_batch: int
    probe_seed: int

    def to_json(self) -> dict:
        return {
            "passed": self.passed,
            "iou": round(self.iou, 6),
            "floor": self.floor,
            "per_bucket": {str(k): round(v, 6) for k, v in self.per_bucket.items()},
            "probe_batch": self.probe_batch,
            "probe_seed": self.probe_seed,
        }


def probe_images(size: int, n: int, seed: int) -> np.ndarray:
    """The seeded probe batch for one bucket: synthetic crack images in
    uint8 transport form — same generator the load/test planes use, so the
    gate exercises crack-shaped inputs, not noise."""
    from fedcrack_tpu.data.pipeline import to_uint8_transport
    from fedcrack_tpu.data.synthetic import synth_crack_batch

    imgs_f, msks_f = synth_crack_batch(n, img_size=size, seed=seed)
    imgs_u8, _ = to_uint8_transport(imgs_f, msks_f)
    return imgs_u8


def quant_gate(
    engine: Any,
    reference_variables: Any,
    quantized_variables: QuantizedVariables,
    *,
    floor: float | None = None,
    probe_batch: int | None = None,
    probe_seed: int | None = None,
) -> QuantGateResult:
    """Run the A/B gate: both programs over the seeded probe batch at every
    bucket size; the min per-bucket mask IoU must clear the floor.

    Both argument trees must already be device-placed (``engine.prepare`` /
    ``engine.prepare_quantized``) — the gate is called from the install
    path, off the serving path, where placement already happened."""
    cfg = engine.serve_config
    floor = cfg.quant_iou_floor if floor is None else floor
    n = cfg.quant_probe_batch if probe_batch is None else probe_batch
    seed = cfg.quant_probe_seed if probe_seed is None else probe_seed
    per_bucket: dict[int, float] = {}
    for size in engine.bucket_sizes:
        batch = probe_images(size, min(n, engine.max_batch), seed)
        ref = engine.predict_bucket(reference_variables, batch)
        quant = engine.predict_bucket(quantized_variables, batch)
        per_bucket[size] = mask_iou(ref, quant)
    worst = min(per_bucket.values())
    return QuantGateResult(
        passed=worst >= floor,
        iou=worst,
        floor=floor,
        per_bucket=per_bucket,
        probe_batch=n,
        probe_seed=seed,
    )
