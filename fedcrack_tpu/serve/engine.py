"""Device-resident inference engine: pre-compiled predict programs per
(image-size, dtype) bucket, plus tiled sliding-window inference for images
larger than any bucket.

The reference's inference path is a one-shot script that rebuilds the Keras
model per run (test/Segmentation2.py; SURVEY §2.1 C4b). Here the ResUNet
stays device-resident and every served shape is ONE compiled XLA program,
built at startup:

- ``fn(variables, images_u8[max_batch, S, S, 3]) -> probs_f32[..., 1]`` per
  bucket size S — uint8 transport bytes in (1/4 the host->device traffic,
  same trick as the training plane), on-device ``normalize_images``, sigmoid
  probabilities out. The model config's PR-1 layout flags
  (``stem_layout``/``res_layout``) apply unchanged: transformed kernels are
  derived in-forward, so the served weights are layout-blind.
- Requests smaller than a bucket are spatially zero-padded into the smallest
  bucket that holds them and the output is cropped back (SAME-padded convs
  make the crop a policy choice, not an equivalence; the bucket contract is
  exact for images AT a bucket size).
- Images larger than the largest bucket run **tiled sliding-window
  inference**: overlapping S x S tiles batched through the bucket program,
  blended with a deterministic separable ramp. The tile schedule and the
  float32 host accumulation are fixed functions of (H, W, S, overlap), so
  tiled output is byte-deterministic run to run (test-pinned).
- With a multi-device mesh (``parallel.mesh.make_mesh``), the batch lane of
  each bucket is sharded over the ``batch`` axis (variables replicated) —
  data-parallel serving on the same mesh machinery the training plane uses.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from fedcrack_tpu.configs import ModelConfig, ServeConfig
from fedcrack_tpu.data.pipeline import normalize_images
from fedcrack_tpu.models import ResUNet

BATCH_AX = "batch"


def tile_plan(extent: int, tile: int, overlap: int) -> list[int]:
    """Deterministic 1-D tile offsets covering ``[0, extent)`` with ``tile``-
    sized windows and at least ``overlap`` shared pixels between neighbors;
    the final window is clamped to the extent (its overlap grows). Requires
    ``extent >= tile``."""
    if extent < tile:
        raise ValueError(f"extent {extent} < tile {tile}")
    stride = tile - overlap
    if stride <= 0:
        raise ValueError(f"overlap {overlap} must be < tile {tile}")
    offsets = list(range(0, max(extent - tile, 0) + 1, stride))
    if offsets[-1] != extent - tile:
        offsets.append(extent - tile)
    return offsets


def _ramp_weights(tile: int, overlap: int, has_before: bool, has_after: bool) -> np.ndarray:
    """1-D blend weights for one tile: 1.0 in the interior, linearly ramping
    down to 1/(overlap+1) over the ``overlap`` pixels facing a neighboring
    tile; image-border edges stay at full weight so un-overlapped pixels are
    single-source."""
    w = np.ones(tile, np.float32)
    if overlap > 0:
        ramp = np.linspace(1.0, 1.0 / (overlap + 1), overlap, dtype=np.float32)
        if has_before:
            w[:overlap] = ramp[::-1]
        if has_after:
            w[-overlap:] = ramp
    return w


class InferenceEngine:
    """Owns the compiled bucket programs and the tiling/padding routing.

    Stateless w.r.t. weights: every predict call takes a ``variables``
    pytree (use :meth:`prepare` to place it on device once) — the hot-swap
    manager owns WHICH weights are current, the engine only computes. That
    split is what makes swap semantics easy to pin: a batch computes with
    exactly the snapshot it was handed.
    """

    def __init__(
        self,
        model_config: ModelConfig | None = None,
        serve_config: ServeConfig | None = None,
        mesh: Any | None = None,
    ):
        self.model_config = model_config or ModelConfig()
        self.serve_config = serve_config or ServeConfig()
        if self.model_config.in_channels != 3:
            raise ValueError("serving assumes 3-channel RGB inputs")
        self._mesh = mesh
        self._sharding = None
        self._rep_sharding = None
        if mesh is not None and self.serve_config.mesh_batch > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            if BATCH_AX not in mesh.shape:
                raise ValueError(f"mesh {mesh.axis_names} has no '{BATCH_AX}' axis")
            if mesh.shape[BATCH_AX] != self.serve_config.mesh_batch:
                raise ValueError(
                    f"mesh batch axis {mesh.shape[BATCH_AX]} != "
                    f"serve mesh_batch {self.serve_config.mesh_batch}"
                )
            self._sharding = NamedSharding(mesh, P(BATCH_AX))
            self._rep_sharding = NamedSharding(mesh, P())
        model = ResUNet(config=self._bucket_model_config())

        def _predict(variables, images_u8):
            x = normalize_images(images_u8)
            logits = model.apply(variables, x, train=False)
            return jax.nn.sigmoid(logits).astype(jnp.float32)

        # One jit wrapper serves every bucket: jax.jit specializes and
        # caches per input shape, so each bucket size still gets (and keeps)
        # its own compiled XLA program.
        kwargs = {}
        if self._sharding is not None:
            kwargs = {
                "in_shardings": (self._rep_sharding, self._sharding),
                "out_shardings": self._sharding,
            }
        self._fn = jax.jit(_predict, **kwargs)
        # Round 17: the int8-quantized predict program — weights arrive as
        # the quantized pytree (int8 codes + per-channel scales), are
        # dequantized IN-GRAPH (XLA sees int8 inputs and fuses q*scale into
        # the weight loads), and the optional activation fake-quant applies
        # at the logits boundary. Same canonical FLOPs as the reference
        # program (obs/flops) — int8 changes bytes moved, not MACs charged.
        #
        # Round 20: ServeConfig.kernel_plane selects the BODY of this
        # program. The quant_gate calls predict_bucket with
        # QuantizedVariables, which routes here — so whichever plane built
        # _fn_q is exactly the program the gate probes, and a fused plane
        # inherits the r17 install contract (IoU floor, loud bf16 refusal)
        # with zero gate changes. "fp8" on a backend without fp8 support
        # degrades to "reference" at build time: the SAME closure as r17,
        # bit-exact by construction (test-pinned).
        self.kernel_plane = self.serve_config.kernel_plane
        self.effective_kernel_plane = self.kernel_plane
        if self.kernel_plane == "fp8":
            from fedcrack_tpu import jaxcompat

            if not jaxcompat.fp8_supported():
                self.effective_kernel_plane = "reference"
        self._fn_q = None
        if self.serve_config.quant == "int8":
            from fedcrack_tpu.serve.quant import (
                dequantize_variables,
                fake_quant_activations,
            )

            act_fq = self.serve_config.quant_act_fakequant

            if self.effective_kernel_plane == "reference":

                def _predict_q(qtree, images_u8):
                    x = normalize_images(images_u8)
                    logits = model.apply(dequantize_variables(qtree), x, train=False)
                    if act_fq:
                        logits = fake_quant_activations(logits)
                    return jax.nn.sigmoid(logits).astype(jnp.float32)

            else:
                from fedcrack_tpu.kernels.dequant import default_impl
                from fedcrack_tpu.kernels.forward import fused_predict_logits

                fused_config = self._bucket_model_config()
                if (
                    fused_config.stem_layout != "reference"
                    or fused_config.res_layout != "reference"
                ):
                    raise ValueError(
                        f"kernel_plane={self.kernel_plane!r} supports only the "
                        "reference parameter layouts (kernels/forward.py); got "
                        f"stem_layout={fused_config.stem_layout!r} "
                        f"res_layout={fused_config.res_layout!r}"
                    )
                impl = default_impl()

                def _predict_q(qtree, images_u8):
                    x = normalize_images(images_u8)
                    logits = fused_predict_logits(qtree, x, fused_config, impl=impl)
                    if act_fq:
                        logits = fake_quant_activations(logits)
                    return jax.nn.sigmoid(logits).astype(jnp.float32)

            self._fn_q = jax.jit(_predict_q, **kwargs)
        self._max_batch = self.serve_config.max_batch

    def _bucket_model_config(self) -> ModelConfig:
        """The served model config: training-time layout flags kept, serving
        dtype applied. img_size is irrelevant to apply (fully convolutional)
        but kept coherent with the largest bucket."""
        import dataclasses

        return dataclasses.replace(
            self.model_config,
            img_size=max(self.serve_config.bucket_sizes),
            compute_dtype=self.serve_config.compute_dtype,
        )

    # ---- weights placement ----

    def prepare(self, variables: Any) -> Any:
        """Place a host variables pytree on device (replicated over the mesh
        when sharded serving is on). Called once per hot-swap, off the
        serving path."""
        from fedcrack_tpu.serve.quant import QuantizedVariables

        if isinstance(variables, QuantizedVariables):
            return self.prepare_quantized(variables)
        if self._rep_sharding is not None:
            out = jax.device_put(variables, self._rep_sharding)
        else:
            out = jax.device_put(variables)
        jax.block_until_ready(out)
        return out

    def prepare_quantized(self, quantized: Any) -> Any:
        """Device-place a quantized weights wrapper (int8 codes + scales
        land on device as-is; dequantize happens in-program)."""
        from fedcrack_tpu.serve.quant import QuantizedVariables

        if not isinstance(quantized, QuantizedVariables):
            raise TypeError(
                f"prepare_quantized wants QuantizedVariables, got "
                f"{type(quantized).__name__}"
            )
        if self._fn_q is None:
            raise ValueError(
                "engine was built with quant='none'; rebuild with "
                "ServeConfig.quant='int8' to serve quantized weights"
            )
        if self._rep_sharding is not None:
            tree = jax.device_put(quantized.tree, self._rep_sharding)
        else:
            tree = jax.device_put(quantized.tree)
        jax.block_until_ready(tree)
        return QuantizedVariables(tree)

    # ---- bucket routing ----

    @property
    def bucket_sizes(self) -> tuple[int, ...]:
        return tuple(self.serve_config.bucket_sizes)

    @property
    def max_batch(self) -> int:
        return self._max_batch

    def bucket_for(self, h: int, w: int) -> int | None:
        """Smallest bucket that holds (h, w); None -> tiled path."""
        for size in self.serve_config.bucket_sizes:
            if h <= size and w <= size:
                return size
        return None

    def warmup(self, variables: Any) -> None:
        """Compile every bucket program before traffic arrives (first-request
        latency must not pay XLA compile). A quantized weights wrapper warms
        the quantized programs; a plain tree warms the reference programs —
        a fleet serving both warms both."""
        from fedcrack_tpu.serve.quant import QuantizedVariables

        fn = self._fn_q if isinstance(variables, QuantizedVariables) else self._fn
        tree = variables.tree if isinstance(variables, QuantizedVariables) else variables
        for size in self.serve_config.bucket_sizes:
            dummy = np.zeros((self._max_batch, size, size, 3), np.uint8)
            jax.block_until_ready(fn(tree, self._stage(dummy)))

    def _stage(self, images_u8: np.ndarray):
        if self._sharding is not None:
            return jax.device_put(images_u8, self._sharding)
        return jax.device_put(images_u8)

    def predict_bucket(self, variables: Any, images_u8: np.ndarray) -> np.ndarray:
        """Run one micro-batch through its bucket program.

        ``images_u8``: [B, S, S, 3] uint8 with B <= max_batch and S a bucket
        size; the batch lane is zero-padded to the compiled max_batch (pad
        lanes are discarded — inference-mode BN normalizes with running
        stats, so lanes are independent). Returns [B, S, S, 1] float32
        probabilities on host."""
        b, h, w, c = images_u8.shape
        if h != w or h not in self.serve_config.bucket_sizes:
            raise ValueError(f"not a compiled bucket shape: {images_u8.shape}")
        if b > self._max_batch:
            raise ValueError(f"batch {b} exceeds compiled max_batch {self._max_batch}")
        if images_u8.dtype != np.uint8:
            raise ValueError(f"expected uint8 transport bytes, got {images_u8.dtype}")
        if b < self._max_batch:
            pad = np.zeros((self._max_batch - b, h, w, c), np.uint8)
            images_u8 = np.concatenate([images_u8, pad], axis=0)
        from fedcrack_tpu.serve.quant import QuantizedVariables

        if isinstance(variables, QuantizedVariables):
            if self._fn_q is None:
                raise ValueError(
                    "quantized weights handed to an engine built with "
                    "quant='none'"
                )
            probs = self._fn_q(variables.tree, self._stage(images_u8))
        else:
            probs = self._fn(variables, self._stage(images_u8))
        return np.asarray(jax.device_get(probs))[:b]

    def predict_image(self, variables: Any, image_u8: np.ndarray) -> np.ndarray:
        """Serve one [H, W, 3] uint8 image at any size: direct bucket, padded
        bucket, or tiled sliding window. Returns [H, W, 1] float32 probs."""
        h, w, _ = image_u8.shape
        bucket = self.bucket_for(h, w)
        if bucket is not None:
            canvas = np.zeros((1, bucket, bucket, 3), np.uint8)
            canvas[0, :h, :w] = image_u8
            probs = self.predict_bucket(variables, canvas)
            return probs[0, :h, :w]
        return self.predict_tiled(variables, image_u8)

    # ---- tiled sliding-window inference ----

    def predict_tiled(self, variables: Any, image_u8: np.ndarray) -> np.ndarray:
        """Overlap-blended sliding-window inference for images beyond the
        largest bucket. Deterministic by construction: tile offsets, batch
        grouping, blend weights, and the float32 accumulation order are all
        fixed functions of (H, W, tile, overlap) — two runs produce
        byte-identical output (test-pinned)."""
        tile = max(self.serve_config.bucket_sizes)
        overlap = self.serve_config.tile_overlap
        h, w, _ = image_u8.shape
        # Pad either undersized dim up to one tile (cropped at the end).
        ph, pw = max(h, tile), max(w, tile)
        if (ph, pw) != (h, w):
            padded = np.zeros((ph, pw, 3), np.uint8)
            padded[:h, :w] = image_u8
            image_u8 = padded
        ys = tile_plan(ph, tile, overlap)
        xs = tile_plan(pw, tile, overlap)
        acc = np.zeros((ph, pw, 1), np.float32)
        wacc = np.zeros((ph, pw, 1), np.float32)
        tiles, spans = [], []
        for yi, y in enumerate(ys):
            for xi, x in enumerate(xs):
                tiles.append(image_u8[y : y + tile, x : x + tile])
                wy = _ramp_weights(tile, overlap, yi > 0, yi + 1 < len(ys))
                wx = _ramp_weights(tile, overlap, xi > 0, xi + 1 < len(xs))
                spans.append((y, x, np.outer(wy, wx)[..., None]))
        # Fixed-order batches of max_batch tiles; accumulation stays host-
        # side float32 in schedule order — determinism over speed of the
        # final reduce (the device work is still the batched bucket fn).
        for start in range(0, len(tiles), self._max_batch):
            chunk = np.stack(tiles[start : start + self._max_batch])
            probs = self.predict_bucket(variables, chunk)
            for i, (y, x, wgt) in enumerate(spans[start : start + self._max_batch]):
                acc[y : y + tile, x : x + tile] += probs[i] * wgt
                wacc[y : y + tile, x : x + tile] += wgt
        out = acc / wacc
        return out[:h, :w]

    def n_tiles(self, h: int, w: int) -> int:
        """How many tiles a (h, w) image costs on the tiled path (capacity
        accounting for the batcher/load-gen)."""
        tile = max(self.serve_config.bucket_sizes)
        overlap = self.serve_config.tile_overlap
        ph, pw = max(h, tile), max(w, tile)
        return len(tile_plan(ph, tile, overlap)) * len(tile_plan(pw, tile, overlap))


def watch_recompiles(engine: "InferenceEngine", registry: Any = None):
    """Export the engine's jit-cache stability as the serve plane's
    ``serve_recompiles_total`` gauge (a collect-time callback over a
    :class:`~fedcrack_tpu.analysis.sanitizers.RecompileSentry`).

    Call AFTER ``engine.warmup(...)``: the sentry marks the post-warmup
    cache size as steady state, so every scrape reports recompiles SINCE
    warmup — the steady-state/hot-swap contract says that number is 0, and
    tests/test_serve.py pins it through a real ``/metrics`` scrape. On jax
    builds without ``_cache_size`` the gauge reports -1 (unknown), never a
    false 0. Returns the sentry for direct assertions."""
    from fedcrack_tpu.analysis.sanitizers import RecompileSentry
    from fedcrack_tpu.obs.registry import REGISTRY

    sentry = RecompileSentry()
    supported = RecompileSentry.supported(engine._fn)
    if supported:
        sentry.watch("serve.predict", engine._fn)
        if engine._fn_q is not None:
            sentry.watch("serve.predict_int8", engine._fn_q)
        sentry.mark()
    reg = registry if registry is not None else REGISTRY
    reg.gauge(
        "serve_recompiles_total",
        "XLA recompiles of the serve predict program since warmup "
        "(steady-state contract: 0 across any number of hot swaps; "
        "-1 = this jax build exposes no jit cache size)",
    ).set_function(
        (lambda: sum(sentry.deltas().values())) if supported else (lambda: -1)
    )
    return sentry
