"""Frame-coherent video serving (round 19): per-stream tile cache + temporal
crack tracking.

Production crack inspection is drone/vehicle VIDEO — consecutive frames are
mostly identical, which the per-request serve plane (r10 engine + r17 fleet)
cannot see. A :class:`StreamSession` turns the r10 tile plan into a
per-stream cache of per-tile sigmoid probabilities keyed on
**(model_version, tile content hash)**: a new frame re-runs ONLY the tiles
whose bytes actually changed (static camera ~ 0 tiles, moving camera ~ the
motion band), then re-blends the full frame with the exact separable-ramp /
fixed-f32-accumulation schedule of ``InferenceEngine.predict_tiled``.

The load-bearing claim — **cached output is byte-identical to stateless
inference** — is provable, not approximate, because of two r10 invariants
(both test-pinned in tests/test_serve.py):

- per-tile probabilities out of ``predict_bucket`` are independent of batch
  grouping (inference-mode BN uses running stats; pad lanes cannot perturb
  real lanes), so a tile computed alone, in a miss-batch, or in
  ``predict_tiled``'s chunking yields the same bytes;
- the blend is a fixed function of (H, W, tile, overlap): same offsets,
  same ramp weights, same host-float32 accumulation order.

The session therefore reproduces ``predict_tiled`` arithmetic exactly from
cached tiles; tests/test_serve_stream.py pins per-frame byte-identity over
random motion sequences including a frame straddling a live hot swap.

Hot-swap safety: the model version is IN the cache key, so a swap can never
serve a stale tile; each frame pins ONE weights snapshot (the r10 tiled-
request barrier), and entries from older versions are purged the first
frame after the swap. ``reset()`` (chaos: SERVE_STREAM_RESET) drops the
cache entirely — the next frame is a full re-run, the escape hatch.

On top of the mask stream, :class:`CrackTracker` gives contours STABLE ids
across frames by greedy centroid matching over ``tools.quantify`` stats —
per-crack area/perimeter growth over time, the output an inspector actually
wants — and an optional EMA smooths the probability field for the tracker
without ever touching the byte-identical raw mask.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import time
from collections import OrderedDict
from typing import Any

import numpy as np

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs.registry import REGISTRY
from fedcrack_tpu.serve.engine import _ramp_weights, tile_plan


def tile_digest(tile_u8: np.ndarray) -> bytes:
    """Content hash of one uint8 tile (the cache key's second half).

    sha256 over the raw bytes: collision-safe at any realistic cache size,
    and ~GB/s on host — a rounding error next to the conv stack it saves."""
    return hashlib.sha256(np.ascontiguousarray(tile_u8).tobytes()).digest()


@dataclasses.dataclass
class FrameResult:
    """One processed frame: the byte-identical probability field plus the
    cache accounting the metrics/bench/CI layers read."""

    probs: np.ndarray            # [H, W, 1] float32 — predict_tiled-identical
    model_version: int
    frame_index: int
    tiles_total: int
    tiles_computed: int          # cache misses actually run on device
    cache_hits: int
    evicted: int
    full_rerun: bool             # reset/disabled-cache escape hatch fired
    latency_ms: float
    tracks: list[dict] = dataclasses.field(default_factory=list)
    smoothed: np.ndarray | None = None  # EMA probs (never the raw contract)

    def mask_bytes(self, threshold: float = 0.5) -> bytes:
        return (
            (self.probs[..., 0] > threshold).astype(np.uint8) * 255
        ).tobytes()


class CrackTracker:
    """Stable per-crack ids + growth over a mask stream.

    Frame-to-frame matching is deliberately simple and deterministic:
    greedy nearest-centroid within ``match_dist`` pixels (closest pairs
    first), which is exact for the slow inter-frame motion video serving
    targets — cracks do not teleport. Unmatched contours open new tracks;
    a track unseen for ``miss_ttl`` frames retires. Contour measurement is
    ``tools.quantify.quantify_mask`` — the same stats the reference's
    Segmentation2.py contour pass produced, now with identity over time.
    """

    def __init__(self, match_dist: float, miss_ttl: int = 5):
        if match_dist <= 0:
            raise ValueError(f"match_dist must be > 0, got {match_dist}")
        if miss_ttl < 1:
            raise ValueError(f"miss_ttl must be >= 1, got {miss_ttl}")
        self.match_dist = float(match_dist)
        self.miss_ttl = int(miss_ttl)
        self._next_id = 1
        # id -> {centroid, first_frame, last_frame, first_area, last_area,
        #        max_area, last_perimeter, frames_seen, missed}
        self.tracks: dict[int, dict] = {}

    @staticmethod
    def _contours(mask: np.ndarray, threshold: int = 127) -> list[dict]:
        import cv2

        mask = np.asarray(mask)
        if mask.ndim == 3:
            mask = mask[..., 0]
        if mask.dtype != np.uint8:
            mask = (np.clip(mask, 0.0, 1.0) * 255).astype(np.uint8)
        _, binary = cv2.threshold(mask, threshold, 255, cv2.THRESH_BINARY)
        found, _ = cv2.findContours(
            binary, cv2.RETR_EXTERNAL, cv2.CHAIN_APPROX_SIMPLE
        )
        out = []
        for c in found:
            m = cv2.moments(c)
            if m["m00"] > 0:
                cx, cy = m["m10"] / m["m00"], m["m01"] / m["m00"]
            else:  # degenerate (line-thin) contour: mean of its points
                pts = c.reshape(-1, 2)
                cx, cy = float(pts[:, 0].mean()), float(pts[:, 1].mean())
            out.append(
                {
                    "centroid": (float(cx), float(cy)),
                    "area_px": float(cv2.contourArea(c)),
                    "perimeter_px": float(cv2.arcLength(c, True)),
                }
            )
        return out

    def update(self, mask: np.ndarray, frame_index: int) -> list[dict]:
        """Advance the tracker one frame; returns the live track records
        (JSON-safe) after matching this frame's contours."""
        contours = self._contours(mask)
        live = [tid for tid, t in self.tracks.items() if t["missed"] < self.miss_ttl]
        # Greedy closest-pair matching: all (track, contour) distances under
        # the gate, ascending; ties broken by (track id, contour index) so
        # the same frames always match the same way.
        pairs = []
        for tid in live:
            tc = self.tracks[tid]["centroid"]
            for ci, c in enumerate(contours):
                d = float(np.hypot(tc[0] - c["centroid"][0], tc[1] - c["centroid"][1]))
                if d <= self.match_dist:
                    pairs.append((d, tid, ci))
        pairs.sort()
        matched_t: set[int] = set()
        matched_c: set[int] = set()
        for d, tid, ci in pairs:
            if tid in matched_t or ci in matched_c:
                continue
            matched_t.add(tid)
            matched_c.add(ci)
            t = self.tracks[tid]
            c = contours[ci]
            t["centroid"] = c["centroid"]
            t["last_frame"] = frame_index
            t["last_area"] = c["area_px"]
            t["max_area"] = max(t["max_area"], c["area_px"])
            t["last_perimeter"] = c["perimeter_px"]
            t["frames_seen"] += 1
            t["missed"] = 0
        for tid in live:
            if tid not in matched_t:
                self.tracks[tid]["missed"] += 1
        for ci, c in enumerate(contours):
            if ci in matched_c:
                continue
            self.tracks[self._next_id] = {
                "centroid": c["centroid"],
                "first_frame": frame_index,
                "last_frame": frame_index,
                "first_area": c["area_px"],
                "last_area": c["area_px"],
                "max_area": c["area_px"],
                "last_perimeter": c["perimeter_px"],
                "frames_seen": 1,
                "missed": 0,
            }
            self._next_id += 1
        return self.snapshot()

    def snapshot(self) -> list[dict]:
        """JSON-safe live-track records, sorted by id (stable output)."""
        out = []
        for tid in sorted(self.tracks):
            t = self.tracks[tid]
            if t["missed"] >= self.miss_ttl:
                continue
            out.append(
                {
                    "id": tid,
                    "centroid": [round(t["centroid"][0], 2), round(t["centroid"][1], 2)],
                    "first_frame": t["first_frame"],
                    "last_frame": t["last_frame"],
                    "frames_seen": t["frames_seen"],
                    "area_px": t["last_area"],
                    "area_growth_px": round(t["last_area"] - t["first_area"], 2),
                    "max_area_px": t["max_area"],
                    "perimeter_px": t["last_perimeter"],
                }
            )
        return out


class StreamSession:
    """One video stream's serving state: the (model_version, tile-hash)
    cache, the frame counter, the optional tracker/EMA.

    NOT thread-safe per session by design — a gRPC stream processes frames
    in order on one handler; the manager serializes any cross-session
    accounting. ``weights`` is anything with ``snapshot() -> (version,
    variables)`` (ModelVersionManager, FleetVersionManager, or a test
    stub): each frame pins exactly one snapshot, the r10 barrier.
    """

    def __init__(
        self,
        engine: Any,
        weights: Any,
        *,
        height: int,
        width: int,
        cache_tiles: int | None = None,
        track: bool = False,
        smooth_alpha: float = 0.0,
        threshold: float = 0.5,
        track_match_dist: float | None = None,
        chaos: Any = None,
        stream_id: str = "",
    ):
        if height < 1 or width < 1:
            raise ValueError(f"bad frame dimensions {height}x{width}")
        if not 0.0 <= smooth_alpha < 1.0:
            raise ValueError(
                f"smooth_alpha must be in [0, 1), got {smooth_alpha}"
            )
        self.engine = engine
        self.weights = weights
        self.height = int(height)
        self.width = int(width)
        self.threshold = threshold if 0.0 < threshold < 1.0 else 0.5
        cfg = engine.serve_config
        self.cache_tiles = (
            cfg.stream_cache_tiles if cache_tiles is None else int(cache_tiles)
        )
        self.smooth_alpha = float(smooth_alpha)
        self.chaos = chaos
        self.stream_id = stream_id
        self.frame_index = 0
        # (version, sha256 digest) -> [tile, tile, 1] float32 probs.
        self._cache: OrderedDict[tuple[int, bytes], np.ndarray] = OrderedDict()
        self._ema: np.ndarray | None = None
        self.tracker: CrackTracker | None = None
        if track:
            dist = (
                track_match_dist
                if track_match_dist is not None
                else cfg.stream_track_match_frac * float(np.hypot(height, width))
            )
            self.tracker = CrackTracker(match_dist=dist)
        # Lifetime totals (the manager aggregates these into the registry).
        self.totals = {
            "frames": 0,
            "tiles_total": 0,
            "tiles_computed": 0,
            "cache_hits": 0,
            "evictions": 0,
            "full_reruns": 0,
            "resets": 0,
        }
        # The frame decomposition is a fixed function of (H, W, tile,
        # overlap) — precompute it once per session.
        tile = max(cfg.bucket_sizes)
        overlap = cfg.tile_overlap
        self._tile = tile
        self._overlap = overlap
        self._ph, self._pw = max(height, tile), max(width, tile)
        self._ys = tile_plan(self._ph, tile, overlap)
        self._xs = tile_plan(self._pw, tile, overlap)
        self._spans: list[tuple[int, int, np.ndarray]] = []
        for yi, y in enumerate(self._ys):
            for xi, x in enumerate(self._xs):
                wy = _ramp_weights(tile, overlap, yi > 0, yi + 1 < len(self._ys))
                wx = _ramp_weights(tile, overlap, xi > 0, xi + 1 < len(self._xs))
                self._spans.append((y, x, np.outer(wy, wx)[..., None]))

    # ---- cache plumbing ----

    def reset(self) -> None:
        """Drop every cached tile (chaos stream reset / client request).
        The next frame falls back to a full-tile re-run — and because the
        cache only ever holds byte-exact per-tile probs, a reset can change
        LATENCY, never bytes."""
        self._cache.clear()
        self.totals["resets"] += 1

    def cache_len(self) -> int:
        return len(self._cache)

    def _purge_versions(self, keep_version: int) -> int:
        """Evict entries from any model version other than the pinned one.
        The version lives in the KEY, so stale entries are unreachable the
        instant a swap lands — this purge only returns their memory."""
        dead = [k for k in self._cache if k[0] != keep_version]
        for k in dead:
            del self._cache[k]
        return len(dead)

    def _cache_put(self, key: tuple[int, bytes], probs: np.ndarray) -> int:
        """LRU insert; returns how many entries were evicted for bound."""
        evicted = 0
        if self.cache_tiles <= 0:
            return 0
        self._cache[key] = probs
        self._cache.move_to_end(key)
        while len(self._cache) > self.cache_tiles:
            self._cache.popitem(last=False)
            evicted += 1
        return evicted

    # ---- the frame path ----

    def process_frame(self, image_u8: np.ndarray) -> FrameResult:
        """Serve one [H, W, 3] uint8 frame.

        Byte-identity contract: ``result.probs`` equals
        ``engine.predict_tiled(variables, image_u8)`` for the pinned
        snapshot's variables, bit for bit, whatever mix of cached and
        computed tiles produced it."""
        t0 = time.monotonic()
        h, w, c = image_u8.shape
        if (h, w) != (self.height, self.width):
            raise ValueError(
                f"frame shape {h}x{w} != session {self.height}x{self.width}"
            )
        if c != 3:
            raise ValueError(f"channels must be 3 (RGB), got {c}")
        if image_u8.dtype != np.uint8:
            raise ValueError(f"expected uint8 frame, got {image_u8.dtype}")
        frame_index = self.frame_index
        self.frame_index += 1

        # Chaos hook: a planned mid-stream reset drops the cache BEFORE the
        # frame is served — this frame must be a clean full re-run.
        if self.chaos is not None:
            self.chaos.on_frame(self.stream_id, frame_index, self)

        # ONE snapshot per frame (the r10 tiled-request barrier): a swap
        # landing while this frame computes cannot tear it across versions.
        version, variables = self.weights.snapshot()
        evicted = self._purge_versions(version)

        # Pad undersized dims exactly like predict_tiled.
        padded = image_u8
        if (self._ph, self._pw) != (h, w):
            padded = np.zeros((self._ph, self._pw, 3), np.uint8)
            padded[:h, :w] = image_u8

        tile = self._tile
        probs_of: list[np.ndarray | None] = [None] * len(self._spans)
        misses: list[int] = []
        keys: list[tuple[int, bytes]] = []
        for i, (y, x, _) in enumerate(self._spans):
            key = (version, tile_digest(padded[y : y + tile, x : x + tile]))
            keys.append(key)
            if self.cache_tiles > 0:
                hit = self._cache.get(key)
                if hit is not None:
                    self._cache.move_to_end(key)
                    probs_of[i] = hit
                    continue
            misses.append(i)
        cache_hits = len(self._spans) - len(misses)

        # Batch ONLY the misses through the bucket program, max_batch at a
        # time (per-tile output is grouping-independent — pad-lane
        # independence — so this regrouping cannot change bytes).
        max_batch = self.engine.max_batch
        for start in range(0, len(misses), max_batch):
            idxs = misses[start : start + max_batch]
            chunk = np.stack(
                [
                    padded[
                        self._spans[i][0] : self._spans[i][0] + tile,
                        self._spans[i][1] : self._spans[i][1] + tile,
                    ]
                    for i in idxs
                ]
            )
            out = self.engine.predict_bucket(variables, chunk)
            for j, i in enumerate(idxs):
                # Own copy: out[j] is a view into the batch array.
                p = np.ascontiguousarray(out[j])
                probs_of[i] = p
                evicted += self._cache_put(keys[i], p)

        # Blend in schedule order — the identical float32 ops, in the
        # identical order, as predict_tiled's accumulation loop.
        acc = np.zeros((self._ph, self._pw, 1), np.float32)
        wacc = np.zeros((self._ph, self._pw, 1), np.float32)
        for i, (y, x, wgt) in enumerate(self._spans):
            acc[y : y + tile, x : x + tile] += probs_of[i] * wgt
            wacc[y : y + tile, x : x + tile] += wgt
        probs = (acc / wacc)[:h, :w]

        full_rerun = cache_hits == 0
        self.totals["frames"] += 1
        self.totals["tiles_total"] += len(self._spans)
        self.totals["tiles_computed"] += len(misses)
        self.totals["cache_hits"] += cache_hits
        self.totals["evictions"] += evicted
        if full_rerun:
            self.totals["full_reruns"] += 1

        smoothed = None
        if self.smooth_alpha > 0.0:
            # EMA over the probability field — a SEPARATE, clearly-labeled
            # output; the raw probs/mask stay byte-identical to stateless.
            if self._ema is None:
                self._ema = probs.copy()
            else:
                a = np.float32(self.smooth_alpha)
                self._ema = a * self._ema + (np.float32(1.0) - a) * probs
            smoothed = self._ema

        tracks: list[dict] = []
        if self.tracker is not None:
            basis = smoothed if smoothed is not None else probs
            mask = ((basis[..., 0] > self.threshold).astype(np.uint8)) * 255
            tracks = self.tracker.update(mask, frame_index)

        return FrameResult(
            probs=probs,
            model_version=version,
            frame_index=frame_index,
            tiles_total=len(self._spans),
            tiles_computed=len(misses),
            cache_hits=cache_hits,
            evicted=evicted,
            full_rerun=full_rerun,
            latency_ms=(time.monotonic() - t0) * 1e3,
            tracks=tracks,
            smoothed=smoothed,
        )


class StreamSessionManager:
    """Owns every open :class:`StreamSession` and the ``serve_stream_*``
    registry families; the gRPC front door opens/feeds/closes sessions
    through it. Thread-safe: sessions map + aggregate counters under one
    lock (each session's frame path itself runs on its stream's handler)."""

    def __init__(
        self,
        engine: Any,
        weights: Any,
        *,
        max_sessions: int | None = None,
        chaos: Any = None,
        registry: Any = None,
    ):
        self.engine = engine
        self.weights = weights
        cfg = engine.serve_config
        self.max_sessions = (
            cfg.stream_max_sessions if max_sessions is None else int(max_sessions)
        )
        self.chaos = chaos
        self._lock = make_lock("serve.stream.manager")
        self._sessions: dict[str, StreamSession] = {}
        reg = registry if registry is not None else REGISTRY
        self._m_sessions = reg.counter(
            "serve_stream_sessions_total",
            "video sessions opened on the serve plane",
        )
        self._m_frames = reg.counter(
            "serve_stream_frames_total", "video frames served across all sessions"
        )
        self._m_hits = reg.counter(
            "serve_stream_cache_hits_total",
            "per-tile cache hits (tile bytes unchanged under the pinned "
            "model version; the device never ran them)",
        )
        self._m_misses = reg.counter(
            "serve_stream_cache_misses_total",
            "per-tile cache misses actually computed on device",
        )
        self._m_evict = reg.counter(
            "serve_stream_cache_evictions_total",
            "tile cache entries evicted (LRU bound or version purge)",
        )
        self._m_rerun = reg.counter(
            "serve_stream_full_rerun_total",
            "frames served with zero cache hits (first frame, reset, or "
            "full-motion escape hatch)",
        )
        self._m_resets = reg.counter(
            "serve_stream_resets_total",
            "mid-stream session resets (chaos SERVE_STREAM_RESET or client)",
        )
        self._m_frame_s = reg.histogram(
            "serve_stream_frame_seconds", "per-frame serve latency"
        )
        self._m_hit_ratio = reg.gauge(
            "serve_stream_cache_hit_ratio",
            "lifetime tile-cache hit ratio across sessions (hits / tiles)",
        )
        self._m_speedup = reg.gauge(
            "serve_stream_effective_speedup_ratio",
            "effective throughput multiplier vs stateless tiling "
            "(tiles_total / tiles_computed; the ~1/changed-tile-fraction "
            "model, measured)",
        )
        self._agg = {"tiles_total": 0, "tiles_computed": 0, "cache_hits": 0}
        self._m_hit_ratio.set_function(self._hit_ratio)
        self._m_speedup.set_function(self._speedup)

    def _hit_ratio(self) -> float:
        with self._lock:
            t = self._agg["tiles_total"]
            return (self._agg["cache_hits"] / t) if t else 0.0

    def _speedup(self) -> float:
        with self._lock:
            c = self._agg["tiles_computed"]
            t = self._agg["tiles_total"]
            # No frames yet -> 1.0 (no claim); all-hit lifetime -> bounded
            # by construction since every first frame computes its tiles.
            return (t / c) if c else 1.0

    def open(
        self,
        stream_id: str,
        *,
        height: int,
        width: int,
        track: bool = False,
        smooth_alpha: float = 0.0,
        threshold: float = 0.5,
    ) -> StreamSession:
        session = StreamSession(
            self.engine,
            self.weights,
            height=height,
            width=width,
            track=track,
            smooth_alpha=smooth_alpha,
            threshold=threshold,
            chaos=self.chaos,
            stream_id=stream_id,
        )
        with self._lock:
            if stream_id in self._sessions:
                raise ValueError(f"stream {stream_id!r} is already open")
            if len(self._sessions) >= self.max_sessions:
                raise ValueError(
                    f"open sessions exceed the bound ({self.max_sessions})"
                )
            self._sessions[stream_id] = session
        self._m_sessions.inc()
        return session

    def get(self, stream_id: str) -> StreamSession | None:
        with self._lock:
            return self._sessions.get(stream_id)

    def close(self, stream_id: str) -> StreamSession | None:
        with self._lock:
            session = self._sessions.pop(stream_id, None)
        return session

    def open_sessions(self) -> int:
        with self._lock:
            return len(self._sessions)

    def record(self, result: FrameResult) -> None:
        """Fold one frame's accounting into the registry (called by the
        front door after each served frame)."""
        self._m_frames.inc()
        self._m_hits.inc(result.cache_hits)
        self._m_misses.inc(result.tiles_computed)
        self._m_evict.inc(result.evicted)
        if result.full_rerun:
            self._m_rerun.inc()
        self._m_frame_s.observe(result.latency_ms / 1e3)
        with self._lock:
            self._agg["tiles_total"] += result.tiles_total
            self._agg["tiles_computed"] += result.tiles_computed
            self._agg["cache_hits"] += result.cache_hits

    def record_reset(self) -> None:
        self._m_resets.inc()

    def stats(self) -> dict:
        with self._lock:
            agg = dict(self._agg)
            n_open = len(self._sessions)
        t, c = agg["tiles_total"], agg["tiles_computed"]
        return {
            "open_sessions": n_open,
            **agg,
            "hit_ratio": (agg["cache_hits"] / t) if t else 0.0,
            "effective_speedup": (t / c) if c else 1.0,
        }


def tracks_to_json(tracks: list[dict]) -> str:
    """Wire form of a track snapshot (StreamResponse.tracks_json)."""
    return json.dumps(tracks, sort_keys=True)
