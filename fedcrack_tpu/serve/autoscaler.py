"""SLO-driven fleet autoscaler — the capacity half of round 22's loop.

The r17 fleet is statically sized: off-peak it burns replicas, at peak it
sheds. This controller closes the loop the ROADMAP's "Elastic fleet" item
promised, built entirely from parts that already exist:

- **Signals**: the controller consumes the registry's OWN Prometheus
  exposition (the r15 parser over ``registry.exposition()`` — the same
  text a dashboard scrapes, the r16 watchdog idiom), after asking the
  router to :meth:`~fedcrack_tpu.serve.router.FleetRouter.refresh_gauges`.
  It reads exactly the signals admission control acts on:
  ``serve_rolling_p95_seconds``, per-bucket
  ``serve_router_queue_depth_total``, and ``serve_fleet_replicas``.
- **Scale-up** (:meth:`ServeFleet.add_replica`): the new replica is
  prepared and warmed OFF the serving path — shared-engine fleets reuse
  the already-compiled programs, process-per-replica fleets ride the r17
  persistent compile cache — and the router only sees it once its weights
  slot is committed and its batcher live.
- **Scale-down** (:meth:`ServeFleet.remove_replica` → the r17
  ``kill_replica`` reroute): queued requests move to survivors with their
  original futures, so zero ACCEPTED requests drop (test-pinned).
- **Hysteresis**: one action per evaluation, a ``scale_cooldown_s`` dead
  time after every action, and scale-down only after
  ``scale_down_idle_evals`` consecutive calm evaluations — a storm gust
  cannot flap the fleet. Shedding stays the loud backstop at the router:
  the controller's job is to make it the exception, never the steady
  state.

The controller also integrates **replica-seconds** (live replicas × wall
time) — the headline cost meter: the bench's diurnal A/B shows the
autoscaled fleet serving the same profile as static-max at materially
lower replica-seconds while p95 holds.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs import flight
from fedcrack_tpu.obs.promexp import parse_prometheus_text, sample_value
from fedcrack_tpu.obs.registry import REGISTRY, MetricsRegistry

log = logging.getLogger("fedcrack.serve.autoscaler")

SCALE_UP = "up"
SCALE_DOWN = "down"
# Calm is deliberately stricter than the scale-up trigger (half of it):
# the gap between "grow above X" and "shrink below X/2" is the hysteresis
# band that keeps a load level sitting near the trigger from flapping.
CALM_P95_FACTOR = 0.5


class FleetAutoscaler:
    """Scale a :class:`~fedcrack_tpu.serve.fleet.ServeFleet` between
    ``ServeConfig.min_replicas`` and ``max_replicas`` from its scraped
    pressure signals. Construction requires an ARMED config
    (``min_replicas >= 1`` — ``configs.py`` validates the band)."""

    def __init__(
        self,
        fleet: Any,
        *,
        registry: MetricsRegistry | None = None,
        clock=time.monotonic,
    ):
        cfg = fleet.router.serve_config
        if cfg.min_replicas < 1:
            raise ValueError(
                "autoscaler needs an armed band: set ServeConfig.min_replicas"
                " >= 1 (and max_replicas >= min_replicas)"
            )
        self.fleet = fleet
        self.cfg = cfg
        self.registry = registry if registry is not None else REGISTRY
        self._clock = clock
        self._lock = make_lock("serve.autoscaler.control")
        self._cooldown_until = 0.0
        self._calm_evals = 0
        self._evaluations = 0
        self._replica_seconds = 0.0
        self._last_t: float | None = None
        self.actions: list[dict] = []
        self._m_events = REGISTRY.counter(
            "serve_scale_events_total",
            "autoscaler fleet resizes by direction",
            labels=("direction",),
        )
        self._m_replica_seconds = REGISTRY.gauge(
            "serve_replica_seconds_total",
            "integrated live-replicas x wall-time — the elastic fleet's "
            "cost meter (what static-max burns and autoscaling saves)",
        )
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ---- signal read ----

    def read_signals(self, parsed: dict | None = None) -> dict:
        """The controller's inputs, from a parsed exposition. ``parsed`` is
        a :func:`parse_prometheus_text` result; None refreshes the router
        gauges and parses the registry's own exposition — the production
        path (tests inject synthetic expositions)."""
        if parsed is None:
            self.fleet.router.refresh_gauges()
            parsed = parse_prometheus_text(self.registry.exposition())
        live = sample_value(parsed, "serve_fleet_replicas")
        p95_s = sample_value(parsed, "serve_rolling_p95_seconds")
        fam = parsed.get("serve_router_queue_depth_total")
        queued = 0.0
        if fam is not None:
            queued = sum(
                v
                for k, v in fam["samples"].items()
                if not any(name == "__sample__" for name, _ in k)
            )
        return {
            "live": int(live) if live is not None else 0,
            "p95_ms": (p95_s or 0.0) * 1e3,
            "queued": int(queued),
        }

    # ---- the control law ----

    def _wants_up(self, sig: dict) -> str | None:
        """Reason to grow, or None. Queue pressure is per-live-replica
        (N queued on 4 replicas is calmer than N on 1); the p95 trigger
        fires BEFORE the SLO breaches (``scale_up_p95_frac`` of it) so
        capacity arrives before the shed probe would."""
        live = max(1, sig["live"])
        if sig["queued"] >= self.cfg.scale_up_queue_depth * live:
            return (
                f"queued {sig['queued']} >= "
                f"{self.cfg.scale_up_queue_depth}/replica x {live}"
            )
        slo = self.cfg.slo_p95_ms
        if slo > 0 and sig["p95_ms"] >= self.cfg.scale_up_p95_frac * slo:
            return (
                f"p95 {sig['p95_ms']:.1f} ms >= "
                f"{self.cfg.scale_up_p95_frac:.2f} x SLO {slo:.1f} ms"
            )
        return None

    def _is_calm(self, sig: dict) -> bool:
        """Calm = empty queues AND p95 well inside the hysteresis band —
        the precondition a scale-down must hold for
        ``scale_down_idle_evals`` consecutive evaluations."""
        if sig["queued"] > 0:
            return False
        slo = self.cfg.slo_p95_ms
        if slo > 0:
            band = CALM_P95_FACTOR * self.cfg.scale_up_p95_frac * slo
            if sig["p95_ms"] >= band:
                return False
        return True

    def evaluate(self, parsed: dict | None = None) -> dict:
        """One control-loop tick: read signals, integrate replica-seconds,
        take at most ONE scaling action. Returns the decision record (also
        appended to :attr:`actions` when an action fired)."""
        with self._lock:
            sig = self.read_signals(parsed)
            now = self._clock()
            self._evaluations += 1
            if self._last_t is not None:
                self._replica_seconds += sig["live"] * (now - self._last_t)
            self._last_t = now
            self._m_replica_seconds.set(self._replica_seconds)
            decision = {
                "evaluation": self._evaluations,
                "action": None,
                "reason": "",
                **sig,
            }
            if now < self._cooldown_until:
                decision["reason"] = "cooldown"
                return decision
            up_reason = self._wants_up(sig)
            if up_reason is not None:
                self._calm_evals = 0
                if sig["live"] >= self.cfg.max_replicas:
                    decision["reason"] = f"at max_replicas: {up_reason}"
                    return decision
                return self._scale_up(decision, up_reason, now)
            if not self._is_calm(sig):
                self._calm_evals = 0
                decision["reason"] = "steady"
                return decision
            self._calm_evals += 1
            if (
                sig["live"] > self.cfg.min_replicas
                and self._calm_evals >= self.cfg.scale_down_idle_evals
            ):
                return self._scale_down(decision, now)
            decision["reason"] = (
                f"calm {self._calm_evals}/{self.cfg.scale_down_idle_evals}"
            )
            return decision

    def _scale_up(self, decision: dict, reason: str, now: float) -> dict:
        replica = self.fleet.add_replica(warm=True)
        self._cooldown_until = now + self.cfg.scale_cooldown_s
        self._m_events.labels(direction=SCALE_UP).inc()
        decision.update(action=SCALE_UP, reason=reason, replica=replica.index)
        self.actions.append(decision)
        flight.note("serve.scale_up", replica=replica.index, reason=reason)
        log.info("scale-up -> replica %d (%s)", replica.index, reason)
        return decision

    def _scale_down(self, decision: dict, now: float) -> dict:
        # Highest-index live replica drains: indices only grow, so the
        # newest capacity leaves first and replica 0 (the tiled-path and
        # shared-engine anchor) never drains.
        victim = max(
            (r for r in self.fleet.router.live_replicas()), key=lambda r: r.index
        )
        reroute = self.fleet.remove_replica(victim.index)
        self._cooldown_until = now + self.cfg.scale_cooldown_s
        self._calm_evals = 0
        self._m_events.labels(direction=SCALE_DOWN).inc()
        decision.update(
            action=SCALE_DOWN,
            reason=f"calm for {self.cfg.scale_down_idle_evals} evals",
            replica=victim.index,
            rerouted=reroute["rerouted"],
        )
        self.actions.append(decision)
        flight.note(
            "serve.scale_down", replica=victim.index,
            rerouted=reroute["rerouted"],
        )
        log.info(
            "scale-down: drained replica %d (%d rerouted)",
            victim.index, reroute["rerouted"],
        )
        return decision

    # ---- lifecycle (the r16 watchdog loop shape) ----

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.cfg.scale_interval_s):
                try:
                    self.evaluate()
                except Exception:
                    log.exception("autoscaler tick failed; retrying next period")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10)
            self._thread = None

    # ---- audit ----

    def replica_seconds(self) -> float:
        """The integral so far, including the un-metered tail since the
        last evaluation (so a final read after stop() is complete)."""
        with self._lock:
            total = self._replica_seconds
            if self._last_t is not None:
                live = sum(1 for r in self.fleet.router.replicas if r.alive)
                total += live * max(0.0, self._clock() - self._last_t)
            return total

    def audit(self) -> dict:
        """JSON-safe controller verdict for bench/soak artifacts: how many
        ticks, every action taken, the cost integral, the band."""
        with self._lock:
            actions = list(self.actions)
            evaluations = self._evaluations
        ups = sum(1 for a in actions if a["action"] == SCALE_UP)
        downs = sum(1 for a in actions if a["action"] == SCALE_DOWN)
        return {
            "evaluations": evaluations,
            "scale_ups": ups,
            "scale_downs": downs,
            "actions": actions,
            "replica_seconds": round(self.replica_seconds(), 3),
            "band": [self.cfg.min_replicas, self.cfg.max_replicas],
            "live": sum(1 for r in self.fleet.router.replicas if r.alive),
        }
