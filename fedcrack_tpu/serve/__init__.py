"""Serving plane: TPU-native batched inference over the federated model.

The subsystem that turns the training stack's outputs into the ROADMAP's
"serves heavy traffic" story (round 10):

- :mod:`fedcrack_tpu.serve.engine` — pre-compiled per-bucket predict
  programs, spatial pad/crop routing, overlap-blended tiled sliding-window
  inference for oversized images;
- :mod:`fedcrack_tpu.serve.batcher` — dynamic micro-batching with
  per-request deadline accounting and streaming latency percentiles;
- :mod:`fedcrack_tpu.serve.hot_swap` — live model-version manager watching
  the federation's checkpoint/statefile outputs, swapping served weights at
  a request-boundary barrier (serve-while-training);
- :mod:`fedcrack_tpu.serve.service` — the gRPC ``ServePlane/Predict``
  front door (``python -m fedcrack_tpu.serve``);
- :mod:`fedcrack_tpu.serve.quant` — int8 weight-only post-training
  quantized predict programs, A/B-gated on probe mask IoU vs the
  reference oracle (round 17);
- :mod:`fedcrack_tpu.serve.fleet` / :mod:`fedcrack_tpu.serve.router` —
  the multi-replica fleet: least-outstanding routing, SLO load shedding,
  fleet-wide two-phase coordinated hot swap (round 17);
- :mod:`fedcrack_tpu.serve.autoscaler` /
  :mod:`fedcrack_tpu.serve.shadow` — the elastic fleet: SLO-driven
  scale-up/down between ``min_replicas``/``max_replicas``, and
  shadow-replica progressive delivery with metric-gated auto-promote /
  auto-rollback (round 22).
"""

from fedcrack_tpu.serve.autoscaler import FleetAutoscaler  # noqa: F401

from fedcrack_tpu.serve.batcher import (  # noqa: F401
    MicroBatcher,
    PredictResult,
    StaticWeights,
)
from fedcrack_tpu.serve.engine import InferenceEngine, tile_plan  # noqa: F401
from fedcrack_tpu.serve.hot_swap import (  # noqa: F401
    ModelVersionManager,
    publish_statefile,
    read_statefile_weights,
)
from fedcrack_tpu.serve.fleet import (  # noqa: F401
    FleetVersionManager,
    Replica,
    ServeFleet,
)
from fedcrack_tpu.serve.quant import (  # noqa: F401
    QuantizedVariables,
    quant_gate,
    quantize_variables,
)
from fedcrack_tpu.serve.router import FleetRouter, LoadShedError  # noqa: F401
from fedcrack_tpu.serve.shadow import (  # noqa: F401
    ShadowController,
    ShadowMirror,
)
from fedcrack_tpu.serve.service import (  # noqa: F401
    ServeServer,
    ServeServerThread,
    ServeService,
)
