"""gRPC front door for the serving plane.

One bidi-streaming ``Predict`` RPC (``fedcrack.ServePlane``), hand-bound like
the control plane's ``FedControl`` (transport/service.py — no codegen
plugin). Requests stream in as LogChunk-style framed image chunks
(offset/last + optional CRC32C per chunk); on the final chunk the image is
assembled and routed:

- exact bucket shape -> the micro-batcher (dynamic batching, the hot path);
- smaller than a bucket -> zero-padded into the smallest holding bucket via
  the batcher, output cropped;
- larger than every bucket -> tiled sliding-window inference, pinned to one
  weights snapshot for the whole request (a multi-batch tiled request must
  not straddle a swap either).

Responses carry the thresholded uint8 mask plus the model version and
queue/total latency for client-side SLO accounting.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import threading
import time
from typing import Any, AsyncIterator

import grpc
import numpy as np

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.transport import transport_pb2 as pb
from fedcrack_tpu.transport.service import channel_options

log = logging.getLogger("fedcrack.serve")

SERVE_SERVICE_NAME = "fedcrack.ServePlane"
PREDICT_METHOD = "Predict"
PREDICT_PATH = f"/{SERVE_SERVICE_NAME}/{PREDICT_METHOD}"
STREAM_METHOD = "StreamPredict"
STREAM_PATH = f"/{SERVE_SERVICE_NAME}/{STREAM_METHOD}"

OK = "OK"
REJECTED = "REJECTED"
# Admission-control shed (round 17, serve/router.py): the fleet refused the
# request BEFORE queueing it — the gRPC-status-code-shaped loud reject a
# client backs off on, distinct from REJECTED (malformed request).
SHED = "RESOURCE_EXHAUSTED"

# Per-stream assembly caps: chunks accumulate server-side until `last`, so an
# unbounded stream of never-finishing requests must hit a ceiling — on total
# buffered bytes AND on the number of open request entries (empty-payload
# chunks would never trip the byte cap).
MAX_PENDING_BYTES = 256 * 1024 * 1024
MAX_PENDING_REQUESTS = 1024


@dataclasses.dataclass
class _Pending:
    height: int
    width: int
    channels: int
    threshold: float
    deadline_ms: float
    chunks: bytearray = dataclasses.field(default_factory=bytearray)


def _reject(request_id: int, reason: str) -> pb.PredictResponse:
    return pb.PredictResponse(request_id=request_id, status=REJECTED, title=reason)


class ServeService:
    """The Predict handler over one engine + batcher + weights source."""

    def __init__(
        self, engine: Any, batcher: Any, weights: Any, stream_manager: Any = None
    ):
        self.engine = engine
        self.batcher = batcher
        self.weights = weights
        # Frame-coherent video serving (round 19): a StreamSessionManager
        # turns StreamPredict RPCs into per-stream tile-cached sessions.
        # None leaves the RPC registered but loudly rejecting.
        self.stream_manager = stream_manager
        self._lock = make_lock("serve.service.stats")
        self.tiled_served = 0
        self.rejected = 0
        self.shed = 0

    # ---- request assembly ----

    def _validate_chunk(self, msg: pb.PredictRequest, pending: dict) -> str | None:
        if msg.height <= 0 or msg.width <= 0:
            return f"bad dimensions {msg.height}x{msg.width}"
        if msg.channels != 3:
            return f"channels must be 3 (RGB), got {msg.channels}"
        if msg.HasField("crc32c"):
            from fedcrack_tpu.native import crc32c

            got = crc32c(msg.image)
            if got != msg.crc32c:
                return (
                    f"image chunk checksum mismatch at offset {msg.offset}: "
                    f"computed {got:#010x}, declared {msg.crc32c:#010x}"
                )
        total = sum(len(p.chunks) for p in pending.values())
        if total + len(msg.image) > MAX_PENDING_BYTES:
            return "per-stream pending image bytes exceed the assembly cap"
        if msg.request_id not in pending and len(pending) >= MAX_PENDING_REQUESTS:
            return "per-stream open request entries exceed the assembly cap"
        return None

    def _assemble(self, p: _Pending) -> np.ndarray | str:
        want = p.height * p.width * p.channels
        if len(p.chunks) != want:
            return f"image bytes {len(p.chunks)} != {p.height}x{p.width}x{p.channels}"
        return np.frombuffer(bytes(p.chunks), np.uint8).reshape(
            p.height, p.width, p.channels
        )

    # ---- routing ----

    async def _serve_one(
        self, request_id: int, image: np.ndarray, p: _Pending
    ) -> pb.PredictResponse:
        h, w, _ = image.shape
        threshold = p.threshold if 0.0 < p.threshold < 1.0 else 0.5
        deadline = p.deadline_ms if p.deadline_ms > 0 else None
        bucket = self.engine.bucket_for(h, w)
        t0 = time.monotonic()
        if bucket is not None:
            canvas = image
            if (h, w) != (bucket, bucket):
                canvas = np.zeros((bucket, bucket, 3), np.uint8)
                canvas[:h, :w] = image
            fut = self.batcher.submit(canvas, deadline_ms=deadline)
            res = await asyncio.wrap_future(fut)
            probs = res.probs[:h, :w]
            version = res.model_version
            queue_ms, latency_ms = res.queue_ms, res.latency_ms
        else:
            # Tiled path: pin ONE snapshot for the whole request.
            version, variables = self.weights.snapshot()
            probs = await asyncio.to_thread(
                self.engine.predict_tiled, variables, image
            )
            queue_ms = 0.0
            latency_ms = (time.monotonic() - t0) * 1e3
            with self._lock:
                self.tiled_served += 1
        mask = ((probs[..., 0] > threshold).astype(np.uint8) * 255).tobytes()
        return pb.PredictResponse(
            request_id=request_id,
            status=OK,
            mask=mask,
            model_version=version,
            latency_ms=latency_ms,
            queue_ms=queue_ms,
            height=h,
            width=w,
        )

    # ---- the stream handler ----

    async def predict_session(
        self, request_iterator: AsyncIterator[pb.PredictRequest], context
    ) -> AsyncIterator[pb.PredictResponse]:
        pending: dict[int, _Pending] = {}
        # request_ids already REJECTED mid-assembly: exactly ONE response per
        # request goes out (clients count responses 1:1 with requests), so
        # later chunks of a dead request are swallowed until its `last`
        # chunk retires the id.
        dead: set[int] = set()
        async for msg in request_iterator:
            if msg.request_id in dead:
                if msg.last:
                    dead.discard(msg.request_id)
                continue
            bad = self._validate_chunk(msg, pending)
            if bad is not None:
                pending.pop(msg.request_id, None)
                if not msg.last:
                    dead.add(msg.request_id)
                with self._lock:
                    self.rejected += 1
                yield _reject(msg.request_id, bad)
                continue
            p = pending.get(msg.request_id)
            if p is None:
                p = _Pending(
                    height=msg.height,
                    width=msg.width,
                    channels=msg.channels,
                    threshold=msg.threshold,
                    deadline_ms=msg.deadline_ms,
                )
                pending[msg.request_id] = p
            if msg.offset != len(p.chunks):
                pending.pop(msg.request_id, None)
                if not msg.last:
                    dead.add(msg.request_id)
                with self._lock:
                    self.rejected += 1
                yield _reject(
                    msg.request_id,
                    f"chunk offset {msg.offset} != received {len(p.chunks)}",
                )
                continue
            p.chunks.extend(msg.image)
            if not msg.last:
                continue
            del pending[msg.request_id]
            image = self._assemble(p)
            if isinstance(image, str):
                with self._lock:
                    self.rejected += 1
                yield _reject(msg.request_id, image)
                continue
            try:
                yield await self._serve_one(msg.request_id, image, p)
            except Exception as e:  # a failed batch errors THIS request only
                from fedcrack_tpu.serve.router import LoadShedError

                if isinstance(e, LoadShedError):
                    # Admission control fired: loud RESOURCE_EXHAUSTED with
                    # the shed reason — never a silent drop, never a stall.
                    with self._lock:
                        self.shed += 1
                    yield pb.PredictResponse(
                        request_id=msg.request_id, status=SHED, title=str(e)
                    )
                    continue
                log.exception("predict failed for request %d", msg.request_id)
                with self._lock:
                    self.rejected += 1
                yield _reject(msg.request_id, repr(e))

    # ---- the video-stream handler (round 19) ----

    async def stream_session(
        self, request_iterator: AsyncIterator[pb.StreamRequest], context
    ) -> AsyncIterator[pb.StreamResponse]:
        """One open/frames/close video session protocol over a bidi stream.

        Every Open, every completed frame, and every Close gets exactly one
        response (clients count 1:1); frame chunks reuse the LogChunk
        offset/last + optional CRC32C idiom. Frames within a stream are
        served in arrival order — the ordering the tile cache and the crack
        tracker are defined over. Sessions opened on this RPC are closed
        when the RPC ends, so a dropped connection cannot leak session
        slots toward the ``stream_max_sessions`` bound."""
        from fedcrack_tpu.serve.stream import tracks_to_json

        opened: dict[str, Any] = {}      # stream_id -> StreamSession
        frames: dict[str, dict] = {}     # stream_id -> in-flight chunk state
        try:
            async for msg in request_iterator:
                sid = msg.stream_id
                kind = msg.WhichOneof("msg")
                if self.stream_manager is None:
                    with self._lock:
                        self.rejected += 1
                    yield pb.StreamResponse(
                        status=REJECTED, title="video serving not enabled"
                    )
                    continue
                if kind == "open":
                    o = msg.open
                    if o.channels not in (0, 3):
                        bad = f"channels must be 3 (RGB), got {o.channels}"
                    elif sid in opened:
                        bad = f"stream {sid!r} is already open on this call"
                    else:
                        bad = None
                    if bad is None:
                        try:
                            opened[sid] = self.stream_manager.open(
                                sid,
                                height=o.height,
                                width=o.width,
                                track=o.track,
                                smooth_alpha=o.smooth_alpha,
                                threshold=o.threshold,
                            )
                        except ValueError as e:
                            bad = str(e)
                    if bad is not None:
                        with self._lock:
                            self.rejected += 1
                        yield pb.StreamResponse(status=REJECTED, title=bad)
                        continue
                    yield pb.StreamResponse(
                        status=OK,
                        title="OPENED",
                        height=o.height,
                        width=o.width,
                    )
                elif kind == "frame":
                    session = opened.get(sid)
                    if session is None:
                        with self._lock:
                            self.rejected += 1
                        yield pb.StreamResponse(
                            frame_id=msg.frame.frame_id,
                            status=REJECTED,
                            title=f"stream {sid!r} is not open",
                        )
                        continue
                    f = msg.frame
                    if f.HasField("crc32c"):
                        from fedcrack_tpu.native import crc32c

                        got = crc32c(f.image)
                        if got != f.crc32c:
                            frames.pop(sid, None)
                            with self._lock:
                                self.rejected += 1
                            yield pb.StreamResponse(
                                frame_id=f.frame_id,
                                status=REJECTED,
                                title=(
                                    f"frame chunk checksum mismatch at offset "
                                    f"{f.offset}: computed {got:#010x}, "
                                    f"declared {f.crc32c:#010x}"
                                ),
                            )
                            continue
                    st = frames.get(sid)
                    if st is None or st["frame_id"] != f.frame_id:
                        st = {"frame_id": f.frame_id, "chunks": bytearray()}
                        frames[sid] = st
                    if f.offset != len(st["chunks"]):
                        frames.pop(sid, None)
                        with self._lock:
                            self.rejected += 1
                        yield pb.StreamResponse(
                            frame_id=f.frame_id,
                            status=REJECTED,
                            title=(
                                f"chunk offset {f.offset} != received "
                                f"{len(st['chunks'])}"
                            ),
                        )
                        continue
                    st["chunks"].extend(f.image)
                    if not f.last:
                        continue
                    frames.pop(sid, None)
                    want = session.height * session.width * 3
                    if len(st["chunks"]) != want:
                        with self._lock:
                            self.rejected += 1
                        yield pb.StreamResponse(
                            frame_id=f.frame_id,
                            status=REJECTED,
                            title=(
                                f"frame bytes {len(st['chunks'])} != "
                                f"{session.height}x{session.width}x3"
                            ),
                        )
                        continue
                    image = np.frombuffer(bytes(st["chunks"]), np.uint8).reshape(
                        session.height, session.width, 3
                    )
                    try:
                        result = await asyncio.to_thread(
                            session.process_frame, image
                        )
                    except Exception as e:  # errors THIS frame only
                        log.exception(
                            "stream frame failed (%s, frame %d)", sid, f.frame_id
                        )
                        with self._lock:
                            self.rejected += 1
                        yield pb.StreamResponse(
                            frame_id=f.frame_id, status=REJECTED, title=repr(e)
                        )
                        continue
                    self.stream_manager.record(result)
                    yield pb.StreamResponse(
                        frame_id=f.frame_id,
                        status=OK,
                        mask=result.mask_bytes(session.threshold),
                        model_version=result.model_version,
                        latency_ms=result.latency_ms,
                        height=session.height,
                        width=session.width,
                        tiles_total=result.tiles_total,
                        tiles_computed=result.tiles_computed,
                        cache_hits=result.cache_hits,
                        full_rerun=result.full_rerun,
                        tracks_json=(
                            tracks_to_json(result.tracks)
                            if session.tracker is not None
                            else ""
                        ),
                    )
                elif kind == "close":
                    if opened.pop(sid, None) is None:
                        with self._lock:
                            self.rejected += 1
                        yield pb.StreamResponse(
                            status=REJECTED, title=f"stream {sid!r} is not open"
                        )
                        continue
                    self.stream_manager.close(sid)
                    frames.pop(sid, None)
                    yield pb.StreamResponse(status=OK, title="CLOSED")
                else:
                    with self._lock:
                        self.rejected += 1
                    yield pb.StreamResponse(
                        status=REJECTED, title="empty StreamRequest"
                    )
        finally:
            if self.stream_manager is not None:
                for sid in opened:
                    self.stream_manager.close(sid)


class ServeServer:
    """Binds a :class:`ServeService` on an asyncio gRPC server."""

    def __init__(
        self,
        service: ServeService,
        host: str = "127.0.0.1",
        port: int = 8890,
        max_message_mb: int = 64,
    ):
        self.service = service
        self._host = host
        self._port = port
        self._max_message_mb = max_message_mb
        self._server: grpc.aio.Server | None = None
        self.bound_port: int | None = None

    async def start(self) -> int:
        server = grpc.aio.server(options=channel_options(self._max_message_mb))
        handler = grpc.stream_stream_rpc_method_handler(
            self.service.predict_session,
            request_deserializer=pb.PredictRequest.FromString,
            response_serializer=pb.PredictResponse.SerializeToString,
        )
        stream_handler = grpc.stream_stream_rpc_method_handler(
            self.service.stream_session,
            request_deserializer=pb.StreamRequest.FromString,
            response_serializer=pb.StreamResponse.SerializeToString,
        )
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    SERVE_SERVICE_NAME,
                    {PREDICT_METHOD: handler, STREAM_METHOD: stream_handler},
                ),
            )
        )
        self.bound_port = server.add_insecure_port(f"{self._host}:{self._port}")
        await server.start()
        self._server = server
        log.info("serving plane on %s:%s", self._host, self.bound_port)
        return self.bound_port

    async def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)


class ServeServerThread:
    """Runs a :class:`ServeServer` on its own loop in a daemon thread — the
    in-process harness for tests, bench.py and load_gen smoke runs."""

    def __init__(self, server: ServeServer):
        self.server = server
        self.loop = asyncio.new_event_loop()
        self.port: int | None = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.port = self.loop.run_until_complete(self.server.start())
        self._started.set()
        self.loop.run_forever()

    def __enter__(self) -> "ServeServerThread":
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("serve server failed to start")
        return self

    def __exit__(self, *exc) -> None:
        fut = asyncio.run_coroutine_threadsafe(self.server.stop(grace=0.5), self.loop)
        try:
            fut.result(timeout=10)
        finally:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._thread.join(timeout=10)
