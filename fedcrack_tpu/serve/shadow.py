"""Shadow-replica progressive delivery — the delivery half of round 22.

The r17 fleet installs every gate-passing version fleet-wide: every release
is a fleet-wide bet. This module turns a release into an EVALUATION first:

- **Mirroring** (:class:`ShadowMirror`): the router calls ``observe`` for
  every admitted request (serve/router.py's post-dispatch hook); a sampled
  fraction (``ServeConfig.shadow_fraction``, deterministic count-based
  stride) is re-submitted to a shadow lane — a
  :class:`~fedcrack_tpu.serve.batcher.MicroBatcher` over the CANDIDATE
  weights pinned by :class:`~fedcrack_tpu.serve.batcher.StaticWeights`.
  The shadow lane lives outside the router's replica set, so there is no
  wire path from it to any client: its answers are observed for latency
  and dropped. A crashing shadow raises inside the hook, which both the
  mirror and the router swallow — production answers and latency never
  depend on the shadow (test-pinned, chaos-drilled).
- **Verdict** (:class:`ShadowController.stage`): candidate vs production
  on three axes — canary mask IoU (the r18
  :class:`~fedcrack_tpu.health.canary.CanaryEvaluator`, production payload
  as the pinned reference), prediction-drift PSI deltas (the r18
  :class:`~fedcrack_tpu.health.drift.DriftMonitor` probe profiles), and
  the shadow/production latency ratio from mirrored traffic. All floors/
  ceilings come from ``ServeConfig``; every gate's value AND verdict land
  in the record, and a ``serve.shadow_verdict`` span joins the candidate's
  flush lineage (r16) — the verdict is traceable to the flush that
  produced the weights.
- **Promote / rollback**: promote = the r17 two-phase fleet commit
  (``fleet.install``); rollback = the version is remembered and never
  staged again (the statefile keeps advertising it; the controller's floor
  skips past). Either way the shadow lane is torn down first.

The controller can also run the fleet's POLL loop (:meth:`start`): instead
of the manager auto-installing every publish, each new statefile version
stages through the shadow first — progressive delivery as the default
serve posture when ``shadow_fraction > 0``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

import numpy as np

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs import flight
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.registry import REGISTRY, MetricsRegistry
from fedcrack_tpu.serve.batcher import MicroBatcher, StaticWeights

log = logging.getLogger("fedcrack.serve.shadow")

PROMOTE = "promote"
ROLLBACK = "rollback"


class ShadowMirror:
    """The router-facing sampling hook: every ``stride``-th observed
    request is copied to the shadow batcher; answers feed a latency list
    and are dropped. ``observe`` NEVER raises out (the router guards too —
    two layers, because a shadow failure reaching a client is the one
    unacceptable outcome)."""

    def __init__(self, batcher: MicroBatcher, fraction: float):
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"shadow fraction must be in (0, 1], got {fraction}")
        self._batcher = batcher
        # Deterministic count-based sampling: fraction 0.25 -> every 4th
        # admitted request mirrors. No RNG on the serving path.
        self.stride = max(1, round(1.0 / fraction))
        self._lock = make_lock("serve.shadow.mirror")
        self._seen = 0
        self.mirrored = 0
        self.failures = 0
        self.latencies_ms: list[float] = []
        self._m_mirrored = REGISTRY.counter(
            "serve_shadow_mirrored_total",
            "admitted requests mirrored to the shadow candidate lane",
        )
        self._m_failures = REGISTRY.counter(
            "serve_shadow_failures_total",
            "shadow-lane submissions or answers that failed (production "
            "unaffected by contract)",
        )

    def observe(self, image_u8: np.ndarray) -> None:
        with self._lock:
            self._seen += 1
            if self._seen % self.stride:
                return
            self.mirrored += 1
        self._m_mirrored.inc()
        try:
            fut = self._batcher.submit(image_u8)
        except Exception:
            with self._lock:
                self.failures += 1
            self._m_failures.inc()
            return
        fut.add_done_callback(self._on_done)

    def _on_done(self, fut) -> None:
        if fut.cancelled() or fut.exception() is not None:
            with self._lock:
                self.failures += 1
            self._m_failures.inc()
            return
        with self._lock:
            self.latencies_ms.append(fut.result().latency_ms)

    def completed(self) -> int:
        with self._lock:
            return len(self.latencies_ms)

    def snapshot(self) -> dict:
        with self._lock:
            lat = list(self.latencies_ms)
            return {
                "seen": self._seen,
                "mirrored": self.mirrored,
                "completed": len(lat),
                "failures": self.failures,
                "latencies_ms": lat,
            }


class ShadowController:
    """Stage candidate versions on a shadow lane; promote or roll back on
    the measured verdict. One candidate at a time (the ``stage`` lock);
    construction requires ``ServeConfig.shadow_fraction > 0``."""

    def __init__(
        self,
        fleet: Any,
        *,
        registry: MetricsRegistry | None = None,
        metrics: Any | None = None,
    ):
        cfg = fleet.router.serve_config
        if cfg.shadow_fraction <= 0:
            raise ValueError(
                "shadow delivery needs ServeConfig.shadow_fraction > 0"
            )
        self.fleet = fleet
        self.cfg = cfg
        self.registry = registry if registry is not None else REGISTRY
        self._metrics = metrics
        self._lock = make_lock("serve.shadow.stage")
        self._rejected: set[int] = set()
        self.verdicts: list[dict] = []
        self.last: dict | None = None
        self._m_verdicts = REGISTRY.counter(
            "serve_shadow_verdicts_total",
            "shadow staging outcomes by verdict",
            labels=("verdict",),
        )
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ---- the evaluation ----

    def _probe_psi(self, engine: Any, ref_payload: Any, cand_payload: Any) -> dict:
        """Prediction-drift PSI between production and candidate on the
        pinned probe set: the SAME seeded inputs through both programs, so
        the ``input`` signal is identically 0 and confidence/entropy (and
        crack_fraction, with cv2) isolate what the MODEL changed."""
        from fedcrack_tpu.health.drift import DriftMonitor
        from fedcrack_tpu.serve.quant import probe_images

        ref = DriftMonitor.capture_reference(engine, ref_payload)
        mon = DriftMonitor(ref)
        n = min(self.cfg.quant_probe_batch, engine.max_batch)
        for size in engine.bucket_sizes:
            batch = probe_images(size, n, self.cfg.quant_probe_seed)
            mon.observe(batch, engine.predict_bucket(cand_payload, batch))
        return mon.compare()

    def stage(
        self, version: int, host_variables: Any, *, wait_s: float = 5.0
    ) -> dict:
        """Evaluate candidate ``version`` against live production and
        decide. Blocks up to ``wait_s`` for ``shadow_min_samples`` mirrored
        answers (traffic permitting); canary IoU and PSI probes run on the
        engine directly, so a verdict ALWAYS lands — a shadow lane that
        answered nothing simply cannot be promoted. Returns the verdict
        record (also appended to :attr:`verdicts`)."""
        from fedcrack_tpu.health.canary import CanaryEvaluator

        version = int(version)
        with self._lock:
            engine = self.fleet.engine
            prod_version, prod_payload = self.fleet.manager.snapshot_for(0)
            fctx = tracing.flush_context(version)
            with tracing.span(
                "serve.shadow_verdict",
                trace=fctx.trace,
                remote_parent=fctx.to_wire(),
                version=version,
                baseline_version=prod_version,
            ) as span_handle:
                cand_payload = engine.prepare(host_variables)
                shadow = MicroBatcher(
                    engine, StaticWeights(cand_payload, version)
                )
                mirror = ShadowMirror(shadow, self.cfg.shadow_fraction)
                self.fleet.router.attach_shadow(mirror)
                try:
                    deadline = time.monotonic() + wait_s
                    while (
                        mirror.completed() < self.cfg.shadow_min_samples
                        and time.monotonic() < deadline
                    ):
                        time.sleep(0.02)
                finally:
                    self.fleet.router.detach_shadow(mirror)
                    shadow.close()
                mirrored = mirror.snapshot()
                # Off-path quality probes — production payload is the
                # canary reference (IoU 1.0 by construction), candidate is
                # the measured eval.
                canary = CanaryEvaluator(engine, registry=self.registry)
                canary.evaluate(prod_version, prod_payload)
                iou = canary.evaluate(version, cand_payload)["iou"]
                psis = self._probe_psi(engine, prod_payload, cand_payload)
                psi_max = max(psis.values()) if psis else 0.0
                prod_p95 = self.fleet.router.rolling.percentile(95.0)
                lat = mirrored["latencies_ms"]
                shadow_p95 = (
                    float(np.percentile(np.asarray(lat), 95.0)) if lat else None
                )
                if shadow_p95 is None:
                    latency_factor = None
                elif prod_p95 is None or prod_p95 <= 0:
                    latency_factor = 1.0
                else:
                    latency_factor = shadow_p95 / prod_p95
                reasons = []
                if mirrored["completed"] < self.cfg.shadow_min_samples:
                    reasons.append(
                        f"shadow answered {mirrored['completed']} < "
                        f"min_samples {self.cfg.shadow_min_samples}"
                    )
                if iou < self.cfg.shadow_iou_floor:
                    reasons.append(
                        f"canary iou {iou:.4f} < floor "
                        f"{self.cfg.shadow_iou_floor:.4f}"
                    )
                if psi_max > self.cfg.shadow_psi_ceiling:
                    reasons.append(
                        f"psi max {psi_max:.4f} > ceiling "
                        f"{self.cfg.shadow_psi_ceiling:.4f}"
                    )
                if (
                    latency_factor is not None
                    and latency_factor > self.cfg.shadow_latency_factor
                ):
                    reasons.append(
                        f"shadow p95 {latency_factor:.2f}x production > "
                        f"{self.cfg.shadow_latency_factor:.2f}x"
                    )
                verdict = PROMOTE if not reasons else ROLLBACK
                record = {
                    "version": version,
                    "baseline_version": prod_version,
                    "verdict": verdict,
                    "reasons": reasons,
                    "iou": iou,
                    "iou_floor": self.cfg.shadow_iou_floor,
                    "psi": psis,
                    "psi_max": round(psi_max, 6),
                    "psi_ceiling": self.cfg.shadow_psi_ceiling,
                    "latency_factor": (
                        round(latency_factor, 4)
                        if latency_factor is not None else None
                    ),
                    "latency_ceiling": self.cfg.shadow_latency_factor,
                    "shadow_p95_ms": (
                        round(shadow_p95, 3) if shadow_p95 is not None else None
                    ),
                    "production_p95_ms": (
                        round(prod_p95, 3) if prod_p95 is not None else None
                    ),
                    "mirrored": mirrored["mirrored"],
                    "completed": mirrored["completed"],
                    "shadow_failures": mirrored["failures"],
                    "trace": fctx.trace,
                }
                if span_handle is not None:
                    span_handle.set(
                        verdict=verdict, iou=round(iou, 6),
                        psi_max=round(psi_max, 6),
                    )
                if verdict == PROMOTE:
                    record["installed"] = self.fleet.install(
                        version, host_variables
                    )
                else:
                    # Remembered forever: the statefile keeps advertising
                    # this version; re-staging a known-bad candidate every
                    # poll would burn the probe budget for nothing.
                    self._rejected.add(version)
                    record["installed"] = False
        self.verdicts.append(record)
        self.last = record
        self._m_verdicts.labels(verdict=verdict).inc()
        flight.note(
            "serve.shadow_verdict", version=version, verdict=verdict,
            iou=record["iou"], psi_max=record["psi_max"],
            latency_factor=record["latency_factor"], reasons=reasons or None,
        )
        if self._metrics is not None:
            self._metrics.log("shadow_verdict", **{
                k: v for k, v in record.items() if k != "psi"
            })
        log.info(
            "shadow verdict v%d: %s (iou=%.4f psi_max=%.4f latency=%sx)%s",
            version, verdict, iou, psi_max,
            f"{latency_factor:.2f}" if latency_factor is not None else "?",
            f" — {'; '.join(reasons)}" if reasons else "",
        )
        return record

    # ---- progressive-delivery poll loop ----

    def poll_once(self) -> dict | None:
        """One delivery tick: the newest statefile/checkpoint version that
        is neither installed nor rejected stages through the shadow."""
        floor = self.fleet.manager.version
        if self._rejected:
            floor = max(floor, max(self._rejected))
        got = self.fleet.manager.watcher.best_available(floor)
        if got is None:
            return None
        return self.stage(*got)

    def start(self, poll_s: float | None = None) -> None:
        """Run the delivery loop in place of the manager's auto-install
        poll — every publish stages through the shadow first."""
        if self._thread is not None:
            return
        interval = poll_s if poll_s is not None else self.cfg.swap_poll_s
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.poll_once()
                except Exception:
                    log.exception("shadow staging failed; retrying next poll")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10)
            self._thread = None

    def audit(self) -> dict:
        """JSON-safe delivery verdict for bench/soak artifacts."""
        verdicts = list(self.verdicts)
        return {
            "staged": len(verdicts),
            "promoted": sum(1 for v in verdicts if v["verdict"] == PROMOTE),
            "rolled_back": sum(
                1 for v in verdicts if v["verdict"] == ROLLBACK
            ),
            "verdicts": verdicts,
        }
