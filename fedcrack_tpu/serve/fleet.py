"""Multi-replica serve fleet with fleet-wide coordinated hot swap (round 17).

Scales the r10 serve plane out: N :class:`Replica` workers (each an engine +
micro-batcher) behind a :class:`~fedcrack_tpu.serve.router.FleetRouter`, with
ONE :class:`FleetVersionManager` owning every replica's weights snapshot.

**Two-phase swap — "zero torn versions fleet-wide".** A publish (statefile /
checkpoint / direct install) runs:

1. *Prepare* (off the serving path, no locks): host weights are device-placed
   for every live replica's engine; with ``ServeConfig.quant="int8"`` the
   int8 weight-only quantized payload is built and **A/B-gated** against the
   reference program on a seeded probe batch (``serve/quant.py``) — a gate
   failure REFUSES the quantized payload loudly and prepares the reference
   payload instead (the replica keeps serving unquantized weights; never a
   silent accuracy cliff).
2. *Commit* (one fleet-lock acquisition): every replica's
   ``(version, payload)`` slot flips together. The batcher's request-boundary
   snapshot reads take the same lock, so a request accepted after commit
   returns — on ANY replica — answers from the new version, and a batch that
   snapshotted before the commit answers entirely from its snapshot (the
   straddle contract, test-pinned exactly like the r10 single-process swap).
   The lock-hold time is the fleet-wide pause, exported as
   ``serve_fleet_swap_pause_seconds``.

The manager wraps the r10 machinery rather than reimplementing it: source
watching is the shared :class:`~fedcrack_tpu.serve.hot_swap.WeightSourceWatcher`,
swap spans join the same version-lineage traces
(``fedtr-v(N-1)#flush:vN``), and ``swap_context`` feeds the batcher's
first-batch-on-version trace link per replica.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.registry import REGISTRY
from fedcrack_tpu.serve.batcher import MicroBatcher
from fedcrack_tpu.serve.engine import InferenceEngine
from fedcrack_tpu.serve.hot_swap import WeightSourceWatcher
from fedcrack_tpu.serve.router import FleetRouter

log = logging.getLogger("fedcrack.serve.fleet")


class _ReplicaWeights:
    """The batcher-facing weights source of one replica: snapshot() reads
    the FLEET manager's slot for this replica (the commit barrier's lock),
    swap_context() forwards the fleet swap's trace context."""

    def __init__(self, manager: "FleetVersionManager", index: int):
        self._manager = manager
        self._index = index

    def snapshot(self) -> tuple[int, Any]:
        return self._manager.snapshot_for(self._index)

    def swap_context(self, version: int) -> str | None:
        return self._manager.swap_context(version)


class Replica:
    """One serve worker: an engine + a micro-batcher over the fleet slot.

    ``engine`` may be shared across replicas (in-process fleets: one XLA
    program, N serving lanes) or per-replica (the process-per-replica
    deployment shape; the persistent compilation cache makes the 2nd..Nth
    boot warm)."""

    def __init__(
        self,
        index: int,
        engine: InferenceEngine,
        manager: "FleetVersionManager",
        *,
        metrics: Any | None = None,
        chaos: Any | None = None,
    ):
        self.index = index
        self.engine = engine
        self.alive = True
        self.batcher = MicroBatcher(
            engine,
            _ReplicaWeights(manager, index),
            metrics=metrics,
            chaos=chaos,
            replica=index,
        )


class FleetVersionManager:
    """Fleet-wide weights ownership: one slot per replica, flipped together.

    The fleet analog of the r10 ``ModelVersionManager`` — same polling
    sources (via the shared :class:`WeightSourceWatcher`), same off-path
    heavy lifting, but ``install`` runs the two-phase prepare/commit over
    every live replica. Replicas are registered AFTER construction
    (:meth:`attach_replicas`) because batchers need the manager first.
    """

    def __init__(
        self,
        serve_config: Any,
        *,
        ckpt_dir: str | None = None,
        state_path: str | None = None,
        poll_s: float | None = None,
        template: Any | None = None,
        metrics: Any | None = None,
        canary: Any | None = None,
    ):
        self.serve_config = serve_config
        self._watcher = WeightSourceWatcher(
            ckpt_dir=ckpt_dir, state_path=state_path, template=template
        )
        self._poll_s = poll_s if poll_s is not None else serve_config.swap_poll_s
        self._metrics = metrics
        # Canary evaluator (round 18): probed at the install tail after the
        # commit barrier, off the serving path; failures never fail a swap.
        self.canary = canary
        self._lock = make_lock("serve.fleet.snapshot")
        self._replicas: list[Replica] = []
        self._slots: list[tuple[int, Any]] = []
        self._version = -1
        # Round 22: the last installed HOST weights, retained so a scale-up
        # (grow_slot) can prepare a brand-new replica's payload without
        # waiting for the next publish.
        self._last_host_variables: Any | None = None
        self._swap_ctx: dict[int, str] = {}
        self.swaps: list[dict] = []
        self.last_swap: dict | None = None
        self.quant_gates: list[dict] = []
        self.last_quant_gate: dict | None = None
        self._m_pause = REGISTRY.histogram(
            "serve_fleet_swap_pause_seconds",
            "commit-barrier hold of a fleet-wide swap (all replica pointers "
            "flip under one lock; prepare/gate work happens off-path before)",
        )
        self._m_quant_iou = REGISTRY.gauge(
            "serve_quant_iou_ratio",
            "probe-batch mask IoU of the int8 predict program vs the "
            "reference oracle at the last install gate (min over buckets; "
            "installs below ServeConfig.quant_iou_floor are refused)",
        )
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ---- wiring ----

    def attach_replicas(
        self, replicas: list, initial_variables: Any, initial_version: int = 0
    ) -> None:
        """Register the fleet and install the initial weights on every
        replica (prepare + commit, including the quant gate) — the boot-time
        install, before any traffic."""
        if self._replicas:
            raise RuntimeError("replicas already attached")
        self._replicas = list(replicas)
        self._slots = [(-1, None)] * len(replicas)
        self._last_host_variables = initial_variables
        payloads, _ = self._prepare_payloads(initial_variables)
        with self._lock:
            self._version = int(initial_version)
            self._slots = [(int(initial_version), p) for p in payloads]

    def grow_slot(self, replica: "Replica") -> None:
        """Round 22 scale-up: register ONE new replica after boot. The
        prepare (device placement, honoring the last quant-gate verdict)
        runs OFF the fleet lock from the retained host weights — serving
        never pauses for a grow; the slot append is one lock acquisition.
        ``replica.index`` must be the current fleet size (indices only ever
        grow; scale-down leaves dead slots behind, exactly like a crash)."""
        if replica.index != len(self._replicas):
            raise ValueError(
                f"grow_slot expects index {len(self._replicas)}, "
                f"got {replica.index}"
            )
        if self._last_host_variables is None:
            raise RuntimeError("no installed weights to grow a replica from")
        # A shared engine reuses a live twin's device payload (same buffers,
        # same compiled programs — the in-process fleet shape); a fresh
        # engine device-places the retained host weights the same way the
        # fleet-wide install would have.
        payload = None
        for r in self._replicas:
            if r.engine is replica.engine and r.alive:
                _, payload = self.snapshot_for(r.index)
                break
        if payload is None:
            payload = self._prepare_one(replica.engine)
        with self._lock:
            self._replicas.append(replica)
            self._slots.append((self._version, payload))
        from fedcrack_tpu.obs import flight

        flight.note("serve.fleet_grow", replica=replica.index,
                    version=self.version)

    def _prepare_one(self, engine: InferenceEngine) -> Any:
        """Device payload for one NEW engine from the retained host weights,
        replaying the last install's quant decision (a refused gate keeps
        refusing — growing the fleet must not resurrect a bad program)."""
        from fedcrack_tpu.serve import quant as quant_mod

        hv = self._last_host_variables
        if (
            self.serve_config.quant == "int8"
            and self.last_quant_gate is not None
            and self.last_quant_gate.get("passed")
        ):
            plane = getattr(engine, "effective_kernel_plane", "reference")
            return engine.prepare_quantized(
                quant_mod.quantize_for_plane(hv, plane)
            )
        return engine.prepare(hv)

    @property
    def watcher(self) -> WeightSourceWatcher:
        """The configured weight source — the shadow controller (round 22)
        polls it directly when progressive delivery replaces auto-install."""
        return self._watcher

    # ---- serving-path reads ----

    def snapshot_for(self, index: int) -> tuple[int, Any]:
        with self._lock:
            return self._slots[index]

    def snapshot(self) -> tuple[int, Any]:
        """The front door's tiled-path read: replica 0's slot (tiled
        requests run on replica 0's engine)."""
        return self.snapshot_for(0)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def swap_context(self, version: int) -> str | None:
        with self._lock:
            return self._swap_ctx.get(int(version))

    # ---- the two-phase install ----

    def _prepare_payloads(self, host_variables: Any):
        """Phase 1: per-replica device payloads, quant-gated when enabled.
        Runs WITHOUT the fleet lock — serving continues on current slots.
        Returns (payloads, gate_record_or_None); a refused gate means every
        payload is the reference program's weights."""
        from fedcrack_tpu.serve import quant as quant_mod

        engines: dict[int, Any] = {}
        for r in self._replicas:
            engines.setdefault(id(r.engine), r.engine)
        ref_by_engine = {
            eid: eng.prepare(host_variables) for eid, eng in engines.items()
        }
        gate_record = None
        quant_by_engine: dict[int, Any] = {}
        if self.serve_config.quant == "int8":
            # Round 20: quantize in the code format the engines' kernel
            # plane consumes (int8 for reference/fused_int8, e4m3 for fp8 —
            # an fp8 request already degraded to reference at engine build
            # where the backend lacks fp8). The gate below probes whichever
            # program the plane compiled, unchanged.
            plane = getattr(
                next(iter(engines.values())), "effective_kernel_plane", "reference"
            )
            qhost = quant_mod.quantize_for_plane(host_variables, plane)
            quant_by_engine = {
                eid: eng.prepare_quantized(qhost) for eid, eng in engines.items()
            }
            # Gate once per install on the first engine: quantization and
            # the probe are deterministic, so every engine would return the
            # same verdict; the per-engine PAYLOADS above are still placed
            # separately (each engine owns its device buffers).
            eid0, eng0 = next(iter(engines.items()))
            # Gate knobs come from the FLEET's serve_config, not the
            # engine's — a shared engine may have been built under a
            # different floor than this fleet runs with.
            gate = quant_mod.quant_gate(
                eng0,
                ref_by_engine[eid0],
                quant_by_engine[eid0],
                floor=self.serve_config.quant_iou_floor,
                probe_batch=self.serve_config.quant_probe_batch,
                probe_seed=self.serve_config.quant_probe_seed,
            )
            gate_record = gate.to_json()
            self._m_quant_iou.set(gate.iou)
            self.quant_gates.append(gate_record)
            self.last_quant_gate = gate_record
            if not gate.passed:
                log.error(
                    "quantized build (kernel_plane=%s) REFUSED: probe mask "
                    "IoU %.4f < floor %.4f — fleet keeps serving the "
                    "reference program",
                    plane,
                    gate.iou,
                    gate.floor,
                )
                quant_by_engine = {}
            from fedcrack_tpu.obs import flight

            flight.note(
                "serve.quant_gate", passed=gate.passed, iou=gate.iou,
                floor=gate.floor,
            )
        payloads = []
        for r in self._replicas:
            if not r.alive:
                payloads.append(None)
            elif quant_by_engine:
                payloads.append(quant_by_engine[id(r.engine)])
            else:
                payloads.append(ref_by_engine[id(r.engine)])
        return payloads, gate_record

    def install(self, version: int, host_variables: Any) -> bool:
        """Two-phase fleet swap to ``version`` (no-op unless strictly
        newer). Prepare runs off-path; commit is one lock acquisition
        flipping every live replica's slot — the barrier after which no
        snapshot anywhere in the fleet returns the old version."""
        current = self.version
        if version <= current:
            return False
        self._last_host_variables = host_variables
        fctx = tracing.flush_context(version)
        sctx = tracing.TraceContext(fctx.trace, f"fleet-swap:v{version}")
        with tracing.span(
            "serve.fleet_swap",
            trace=fctx.trace,
            ctx=sctx.to_wire(),
            remote_parent=fctx.to_wire(),
            from_version=current,
            to_version=version,
            replicas=len(self._replicas),
        ) as span_handle:
            t0 = time.monotonic()
            payloads, gate_record = self._prepare_payloads(host_variables)
            load_ms = (time.monotonic() - t0) * 1e3
            t_commit = time.monotonic()
            with self._lock:
                if version <= self._version:
                    if span_handle is not None:
                        span_handle.set(installed=False)
                    return False
                for i, payload in enumerate(payloads):
                    if payload is not None:
                        self._slots[i] = (version, payload)
                self._version = version
                self._swap_ctx[version] = sctx.to_wire()
                while len(self._swap_ctx) > 8:
                    self._swap_ctx.pop(min(self._swap_ctx))
            pause_s = time.monotonic() - t_commit
            if span_handle is not None:
                span_handle.set(installed=True, pause_ms=round(pause_s * 1e3, 3))
        self._m_pause.observe(pause_s)
        REGISTRY.counter(
            "serve_swaps_total", "hot swaps installed by the version manager"
        ).inc()
        from fedcrack_tpu.obs import flight

        flight.note(
            "serve.fleet_swap", from_version=current, to_version=version,
            load_ms=round(load_ms, 3), pause_ms=round(pause_s * 1e3, 3),
        )
        record = {
            "from_version": current,
            "to_version": version,
            "load_ms": round(load_ms, 3),
            "pause_ms": round(pause_s * 1e3, 3),
            "replicas": sum(1 for p in payloads if p is not None),
            "quant_gate": gate_record,
            # fedlint: disable=DET001 -- human-readable record timestamp
            "ts": time.time(),
        }
        self.swaps.append(record)
        self.last_swap = record
        log.info(
            "fleet hot-swap: v%d -> v%d on %d replicas (%.1f ms prepare, "
            "%.3f ms commit pause)",
            current, version, record["replicas"], load_ms, pause_s * 1e3,
        )
        if self._metrics is not None:
            self._metrics.log("serve_fleet_swap", **record)
        if self.canary is not None:
            # First committed payload: every replica serves the same
            # version, so one probe pass is the fleet's canary verdict.
            payload = next((p for p in payloads if p is not None), None)
            if payload is not None:
                try:
                    self.canary.evaluate(version, payload)
                except Exception:
                    log.exception(
                        "canary eval failed for v%d (swap unaffected)", version
                    )
        return True

    # ---- polling lifecycle (same shape as the r10 manager) ----

    def poll_once(self) -> bool:
        got = self._watcher.best_available(self.version)
        if got is None:
            return False
        return self.install(*got)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self._poll_s):
                try:
                    self.poll_once()
                except Exception:
                    log.exception("fleet swap poll failed; retrying next period")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10)
            self._thread = None
        self._watcher.close()


class ServeFleet:
    """The assembled fleet: engines, replicas, manager, router — what the
    gRPC front door and the harnesses hold. ``submit``/``snapshot`` mirror
    the single-replica batcher/manager surface, so ``ServeService`` works
    unchanged."""

    def __init__(
        self,
        model_config: Any,
        serve_config: Any,
        initial_variables: Any,
        *,
        initial_version: int = 0,
        ckpt_dir: str | None = None,
        state_path: str | None = None,
        template: Any | None = None,
        metrics: Any | None = None,
        chaos: Any | None = None,
        shared_engine: InferenceEngine | None = None,
        share_engine: bool = True,
        router_window_s: float = 10.0,
        warmup: bool = True,
    ):
        n = serve_config.replicas
        if shared_engine is not None:
            engines = [shared_engine] * n
        elif share_engine:
            engines = [InferenceEngine(model_config, serve_config)] * n
        else:
            engines = [InferenceEngine(model_config, serve_config) for _ in range(n)]
        self.manager = FleetVersionManager(
            serve_config,
            ckpt_dir=ckpt_dir,
            state_path=state_path,
            template=template,
            metrics=metrics,
        )
        self._metrics = metrics
        self._chaos = chaos
        self.replicas = [
            Replica(i, engines[i], self.manager, metrics=metrics, chaos=chaos)
            for i in range(n)
        ]
        self.manager.attach_replicas(
            self.replicas, initial_variables, initial_version
        )
        if warmup:
            from fedcrack_tpu.serve.quant import QuantizedVariables, quantize_for_plane

            seen: set[int] = set()
            for r in self.replicas:
                if id(r.engine) in seen:
                    continue
                seen.add(id(r.engine))
                _, payload = self.manager.snapshot_for(r.index)
                r.engine.warmup(payload)
                if serve_config.quant == "int8":
                    # Warm BOTH programs: a refused gate serves the
                    # reference program, a later passing install swaps to
                    # the quantized one — neither may pay compile mid-traffic.
                    if isinstance(payload, QuantizedVariables):
                        r.engine.warmup(r.engine.prepare(initial_variables))
                    else:
                        r.engine.warmup(
                            r.engine.prepare_quantized(
                                quantize_for_plane(
                                    initial_variables,
                                    getattr(
                                        r.engine,
                                        "effective_kernel_plane",
                                        "reference",
                                    ),
                                )
                            )
                        )
        # Which kernel plane answers quantized traffic — labeled info gauge
        # (obs/flops.py) so a scrape can tell fused from reference serving.
        from fedcrack_tpu.obs.flops import export_kernel_plane

        export_kernel_plane(
            getattr(self.engine, "effective_kernel_plane", "reference"),
            requested=serve_config.kernel_plane,
        )
        self.router = FleetRouter(
            self.replicas, serve_config, window_s=router_window_s
        )

    # batcher-shaped surface for the front door
    def submit(self, image_u8, deadline_ms=None):
        return self.router.submit(image_u8, deadline_ms=deadline_ms)

    def snapshot(self):
        return self.manager.snapshot()

    @property
    def engine(self) -> InferenceEngine:
        return self.replicas[0].engine

    def install(self, version: int, host_variables: Any) -> bool:
        return self.manager.install(version, host_variables)

    # ---- elastic lifecycle (round 22) ----

    def add_replica(self, *, warm: bool = True) -> Replica:
        """Scale-up: build, register and (by default) warm ONE new replica
        entirely OFF the serving path, then publish it to the router — the
        only sanctioned grow path (fedlint FLEET001). The new replica
        shares replica 0's engine (one XLA program, another serving lane;
        the r17 persistent compile cache makes a per-process engine's boot
        warm the same way), so the router first sees it with its batcher
        live and its weights slot already committed."""
        engine = self.replicas[0].engine
        index = len(self.replicas)
        replica = Replica(
            index, engine, self.manager,
            metrics=self._metrics, chaos=self._chaos,
        )
        self.manager.grow_slot(replica)
        if warm:
            _, payload = self.manager.snapshot_for(index)
            engine.warmup(payload)
        self.replicas.append(replica)
        # The router-list append lives HERE, not in router.py: FLEET001
        # pins every replica-set mutation inside serve/fleet.py or
        # serve/autoscaler.py, and the router's list IS the fleet's.
        with self.router._lock:
            self.router.replicas.append(replica)
            self.router._m_replicas.set(
                sum(1 for r in self.router.replicas if r.alive)
            )
        from fedcrack_tpu.obs import flight

        flight.note("serve.replica_added", replica=index)
        return replica

    def remove_replica(self, index: int) -> dict:
        """Scale-down: drain replica ``index`` out of rotation via the
        router's kill/reroute machinery — queued requests move to survivors
        with their original futures, so zero ACCEPTED requests drop (the
        r17 pin the autoscaler leans on). The slot stays behind, dead."""
        return self.router.kill_replica(index)

    def stats(self) -> dict:
        return {
            "router": self.router.stats(),
            "swaps": list(self.manager.swaps),
            "quant_gate": self.manager.last_quant_gate,
        }

    def close(self) -> None:
        self.manager.stop()
        for r in self.replicas:
            r.batcher.close()
