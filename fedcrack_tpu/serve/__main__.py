"""``python -m fedcrack_tpu.serve`` — boot the crack-segmentation endpoint.

Builds the engine (one compiled program per bucket), resolves initial
weights (``--weights`` msgpack > statefile > checkpoint dir > seed init, in
that order), starts the hot-swap poller against the federation's
checkpoint/statefile outputs, and serves ``fedcrack.ServePlane/Predict``
until SIGTERM/SIGINT.

Prints exactly one ``SERVING <host>:<port> ...`` line to stdout once ready —
harnesses (tools/load_gen.py --spawn, the e2e smoke) key on it.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import logging
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m fedcrack_tpu.serve", description=__doc__
    )
    p.add_argument("--config", help="FedConfig JSON preset (serve + model sections)")
    p.add_argument("--weights", help="msgpack pytree to serve initially")
    p.add_argument("--ckpt-dir", help="orbax checkpoint dir to hot-swap from")
    p.add_argument("--state-path", help="federation statefile to hot-swap from")
    p.add_argument("--host")
    p.add_argument("--port", type=int)
    p.add_argument("--buckets", help="comma-separated bucket sizes, e.g. 128,256")
    p.add_argument("--max-batch", type=int)
    p.add_argument("--max-delay-ms", type=float)
    p.add_argument("--tile-overlap", type=int,
                   help="sliding-window overlap px (must be < smallest bucket)")
    p.add_argument("--swap-poll-s", type=float)
    p.add_argument("--compute-dtype", choices=["float32", "bfloat16"])
    p.add_argument(
        "--replicas",
        type=int,
        help="replica workers behind the fleet router (>1 enables the "
        "round-17 fleet: least-outstanding dispatch, coordinated two-phase "
        "hot swap, admission control)",
    )
    p.add_argument(
        "--quant",
        choices=["none", "int8"],
        help="post-training weight quantization of the predict program; "
        "int8 installs are A/B-gated on probe mask IoU vs the reference "
        "oracle and refused below --quant-iou-floor",
    )
    p.add_argument("--quant-iou-floor", type=float)
    p.add_argument(
        "--min-replicas",
        type=int,
        help="arm the round-22 SLO autoscaler: fleet floor (>= 1; pairs "
        "with --max-replicas; --replicas is the boot size inside the band)",
    )
    p.add_argument(
        "--max-replicas",
        type=int,
        help="autoscaler fleet ceiling (>= --min-replicas)",
    )
    p.add_argument("--scale-interval-s", type=float,
                   help="autoscaler control-loop period")
    p.add_argument("--scale-cooldown-s", type=float,
                   help="dead time after any scaling action (anti-flap)")
    p.add_argument(
        "--shadow-fraction",
        type=float,
        help="arm round-22 progressive delivery: fraction of admitted "
        "traffic mirrored to a shadow candidate lane (> 0; publishes then "
        "stage through shadow and auto-promote/auto-rollback instead of "
        "installing directly)",
    )
    p.add_argument(
        "--slo-p95-ms",
        type=float,
        help="shed (RESOURCE_EXHAUSTED) when rolling p95 breaches this; 0 off",
    )
    p.add_argument(
        "--queue-bound",
        type=int,
        help="shed when queued requests across replicas reach this; 0 off",
    )
    p.add_argument(
        "--stream-cache-tiles",
        type=int,
        help="per-stream tile cache bound for video sessions (entries = "
        "tiles, keyed on (model_version, tile hash)); 0 disables caching — "
        "every frame is a full re-run",
    )
    p.add_argument(
        "--stream-max-sessions",
        type=int,
        help="open video sessions the serve process will hold at once",
    )
    p.add_argument(
        "--compile-cache-dir",
        help="persistent XLA compilation cache directory (warm replica "
        "boots; jax_compilation_cache_dir)",
    )
    p.add_argument("--metrics-path", help="JSONL metrics sink (serve_batch/serve_swap)")
    p.add_argument(
        "--metrics-port",
        type=int,
        default=0,
        help="Prometheus /metrics endpoint for the live registry (request "
        "latency per bucket, queue depth, deadline misses, swaps, "
        "recompiles); 0 disables, -1 binds an ephemeral port",
    )
    p.add_argument(
        "--spans-path",
        help="JSONL trace-span sink (serve.batch/serve.swap correlation "
        "spans); empty disables",
    )
    p.add_argument("--seed", type=int, default=0, help="init seed when no weights found")
    return p


def resolve_config(args):
    from fedcrack_tpu.configs import FedConfig

    if args.config:
        with open(args.config) as f:
            fed = FedConfig.from_json(f.read())
    else:
        fed = FedConfig()
    serve = fed.serve
    overrides = {}
    if args.buckets:
        overrides["bucket_sizes"] = tuple(
            int(s) for s in args.buckets.split(",") if s.strip()
        )
    if args.max_batch is not None:
        overrides["max_batch"] = args.max_batch
    if args.max_delay_ms is not None:
        overrides["max_delay_ms"] = args.max_delay_ms
    if args.tile_overlap is not None:
        overrides["tile_overlap"] = args.tile_overlap
    if args.swap_poll_s is not None:
        overrides["swap_poll_s"] = args.swap_poll_s
    if args.compute_dtype:
        overrides["compute_dtype"] = args.compute_dtype
    if args.host:
        overrides["host"] = args.host
    if args.port is not None:
        overrides["port"] = args.port
    if args.replicas is not None:
        overrides["replicas"] = args.replicas
    if args.quant is not None:
        overrides["quant"] = args.quant
    if args.quant_iou_floor is not None:
        overrides["quant_iou_floor"] = args.quant_iou_floor
    if args.slo_p95_ms is not None:
        overrides["slo_p95_ms"] = args.slo_p95_ms
    if args.queue_bound is not None:
        overrides["queue_bound"] = args.queue_bound
    if args.stream_cache_tiles is not None:
        overrides["stream_cache_tiles"] = args.stream_cache_tiles
    if args.stream_max_sessions is not None:
        overrides["stream_max_sessions"] = args.stream_max_sessions
    if args.min_replicas is not None:
        overrides["min_replicas"] = args.min_replicas
    if args.max_replicas is not None:
        overrides["max_replicas"] = args.max_replicas
    if args.scale_interval_s is not None:
        overrides["scale_interval_s"] = args.scale_interval_s
    if args.scale_cooldown_s is not None:
        overrides["scale_cooldown_s"] = args.scale_cooldown_s
    if args.shadow_fraction is not None:
        overrides["shadow_fraction"] = args.shadow_fraction
    if overrides:
        serve = dataclasses.replace(serve, **overrides)
    return fed.model, serve


def resolve_initial_weights(args, template, seed: int):
    """(version, variables): explicit file > statefile > ckpt dir > seed."""
    from fedcrack_tpu.serve.hot_swap import read_statefile_weights

    if args.weights:
        from fedcrack_tpu.fed.serialization import tree_from_bytes

        with open(args.weights, "rb") as f:
            return 0, tree_from_bytes(f.read(), template=template)
    if args.state_path:
        got = read_statefile_weights(args.state_path, template=template)
        if got is not None:
            return got
    if args.ckpt_dir:
        import os

        from fedcrack_tpu.ckpt.manager import FedCheckpointer

        if os.path.isdir(args.ckpt_dir):
            with FedCheckpointer(args.ckpt_dir) as ckptr:
                ckpt = ckptr.restore(template)
            if ckpt is not None:
                return ckpt.model_version, ckpt.variables
    print(
        "no weights source found; serving seed-initialized model "
        f"(seed {seed}) until the first hot-swap",
        file=sys.stderr,
    )
    return 0, template


async def _serve(args) -> int:
    import jax

    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve.batcher import MicroBatcher
    from fedcrack_tpu.serve.engine import InferenceEngine
    from fedcrack_tpu.serve.hot_swap import ModelVersionManager
    from fedcrack_tpu.serve.service import ServeServer, ServeService

    if args.compile_cache_dir:
        # Warm boot (round 17): point the persistent XLA cache at the shared
        # directory BEFORE any program compiles — the 2nd..Nth replica/
        # session reuses the 1st one's executables.
        from fedcrack_tpu.jaxcompat import enable_compilation_cache

        enable_compilation_cache(args.compile_cache_dir)

    model_config, serve_config = resolve_config(args)
    template = init_variables(jax.random.key(args.seed), model_config)
    version, variables = resolve_initial_weights(args, template, args.seed)

    metrics = None
    if args.metrics_path:
        from fedcrack_tpu.obs.metrics import MetricsLogger

        metrics = MetricsLogger(args.metrics_path)

    fleet = None
    if (
        serve_config.replicas > 1
        or serve_config.quant != "none"
        or serve_config.min_replicas > 0
        or serve_config.shadow_fraction > 0
    ):
        # Round-17 fleet topology (also the single-replica quantized shape:
        # the fleet manager owns the A/B gate; round 22's autoscaler and
        # shadow delivery only exist on the fleet shape).
        from fedcrack_tpu.serve.fleet import ServeFleet

        fleet = ServeFleet(
            model_config,
            serve_config,
            variables,
            initial_version=version,
            ckpt_dir=args.ckpt_dir,
            state_path=args.state_path,
            template=template,
            metrics=metrics,
        )
        engine, batcher_like, manager = fleet.engine, fleet.router, fleet.manager
    else:
        engine = InferenceEngine(model_config, serve_config)
        manager = ModelVersionManager(
            engine,
            variables,
            initial_version=version,
            ckpt_dir=args.ckpt_dir,
            state_path=args.state_path,
            poll_s=serve_config.swap_poll_s,
            template=template,
            metrics=metrics,
        )
        engine.warmup(manager.snapshot()[1])
        batcher_like = MicroBatcher(engine, manager, metrics=metrics)
    # Live telemetry (round 15): /metrics exporter + post-warmup recompile
    # sentry (serve_recompiles_total must stay 0 across hot swaps) + spans.
    from fedcrack_tpu.obs.promexp import start_exporter
    from fedcrack_tpu.serve.engine import watch_recompiles

    watch_recompiles(engine)
    exporter = start_exporter(args.metrics_port)
    if args.spans_path:
        from fedcrack_tpu.obs import spans as tracing

        tracing.install(args.spans_path)
    # Frame-coherent video serving (round 19): per-stream tile-cached
    # sessions behind the same front door; the weights source is the same
    # manager the still path pins snapshots from, so a hot swap invalidates
    # stream caches through the version in the key.
    from fedcrack_tpu.serve.stream import StreamSessionManager

    stream_manager = StreamSessionManager(engine, manager)
    server = ServeServer(
        ServeService(engine, batcher_like, manager, stream_manager=stream_manager),
        host=serve_config.host,
        port=serve_config.port,
        max_message_mb=serve_config.max_message_mb,
    )
    # Round 22: elastic capacity + progressive delivery on the fleet shape.
    autoscaler = None
    shadow_ctrl = None
    if fleet is not None and serve_config.min_replicas > 0:
        from fedcrack_tpu.serve.autoscaler import FleetAutoscaler

        autoscaler = FleetAutoscaler(fleet)
        autoscaler.start()
    if fleet is not None and serve_config.shadow_fraction > 0:
        from fedcrack_tpu.serve.shadow import ShadowController

        shadow_ctrl = ShadowController(fleet, metrics=metrics)
        # The shadow controller RUNS the delivery poll: publishes stage
        # through the shadow lane and auto-promote/rollback instead of the
        # manager's install-everything-at-once loop.
        shadow_ctrl.start()
    else:
        manager.start()
    port = await server.start()
    metrics_note = (
        f" metrics_port={exporter.bound_port}" if exporter is not None else ""
    )
    print(
        f"SERVING {serve_config.host}:{port} "
        f"buckets={','.join(str(s) for s in serve_config.bucket_sizes)} "
        f"max_batch={serve_config.max_batch} version={manager.version}"
        f" replicas={serve_config.replicas} quant={serve_config.quant}"
        f"{metrics_note}",
        flush=True,
    )

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
    await stop.wait()
    await server.stop()
    if autoscaler is not None:
        autoscaler.stop()
    if shadow_ctrl is not None:
        shadow_ctrl.stop()
    if fleet is not None:
        fleet.close()
    else:
        manager.stop()
        batcher_like.close()
    if exporter is not None:
        exporter.stop()
    if metrics is not None:
        import json

        stats = fleet.stats() if fleet is not None else batcher_like.stats()
        if autoscaler is not None:
            stats["autoscaler"] = autoscaler.audit()
        if shadow_ctrl is not None:
            stats["shadow"] = shadow_ctrl.audit()
        print(json.dumps({"serve_stats": stats}), flush=True)
        metrics.close()
    return 0


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    return asyncio.run(_serve(args))


if __name__ == "__main__":
    sys.exit(main())
