"""Fleet router: least-outstanding-requests dispatch + SLO load shedding.

The round-17 front of the multi-replica serve fleet (serve/fleet.py). One
router owns N replicas (each an engine + micro-batcher); ``submit`` is the
single admission point:

1. **Admission control** — before a request is accepted it may be SHED with
   a loud :class:`LoadShedError` (the gRPC front door answers
   ``RESOURCE_EXHAUSTED``): when queued requests across live replicas exceed
   ``ServeConfig.queue_bound``, or when the fleet's rolling p95 latency
   breaches ``ServeConfig.slo_p95_ms``. Shedding happens ONLY here — a
   request that was admitted is never dropped, whatever fails afterwards
   (the zero-drop discipline the r10 plane pins, now fleet-wide).
2. **Dispatch** — the live replica with the fewest outstanding requests
   wins (ties break to the lowest replica index — deterministic routing for
   a deterministic test plane). A ``serve.route`` span records the choice
   so stitched traces show which replica served a request.

Rolling p95: per-completion latencies feed a pair of bounded reservoirs
(:class:`fedcrack_tpu.obs.metrics.StreamingPercentiles`) rotated every
``window_s`` — reads pool the current and previous window, so the probe
tracks the last ~1-2 windows instead of the whole run (a breach recovers
once latencies do; an all-run reservoir would hold the SLO breached
forever). The probe arms only past ``MIN_SHED_SAMPLES`` completions per
window pair, so one slow cold request cannot shed.

Replica failure: :meth:`kill_replica` (the chaos drill's crash hook, and
the operational remove path) drains the dead replica's queued requests and
resubmits them — with their original futures and submit times — to
survivors, bypassing admission control: they were already accepted.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.metrics import StreamingPercentiles
from fedcrack_tpu.obs.registry import REGISTRY

# The p95 shed probe stays disarmed until this many samples sit in the
# rolling window pair — shedding on a cold-start sample would page on noise.
MIN_SHED_SAMPLES = 16

SHED_QUEUE_BOUND = "queue_bound"
SHED_P95_SLO = "p95_slo"


class LoadShedError(RuntimeError):
    """Admission refused — the caller gets this BEFORE the request enters
    any queue (RESOURCE_EXHAUSTED at the front door, never a silent drop)."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"load shed ({reason}): {detail}")
        self.reason = reason
        self.detail = detail


class RollingPercentiles:
    """Two-reservoir rolling latency window: samples land in the current
    reservoir; every ``window_s`` it becomes the previous one and a fresh
    reservoir starts. Reads pool both — a bounded, recency-faithful
    estimate built from the SAME StreamingPercentiles machinery the r10
    plane uses (merge() is the r15 satellite)."""

    def __init__(self, window_s: float = 10.0, capacity: int = 2048, seed: int = 0):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self._window_s = window_s
        self._capacity = capacity
        self._seed = seed
        self._lock = threading.Lock()
        self._cur = StreamingPercentiles(capacity, seed=seed)
        self._prev = StreamingPercentiles(capacity, seed=seed + 1)
        self._t_rotate = time.monotonic() + window_s

    def _maybe_rotate_locked(self) -> None:
        now = time.monotonic()
        if now >= self._t_rotate:
            self._prev = self._cur
            self._cur = StreamingPercentiles(self._capacity, seed=self._seed)
            self._t_rotate = now + self._window_s

    def add(self, value_ms: float) -> None:
        with self._lock:
            self._maybe_rotate_locked()
            self._cur.add(value_ms)

    def percentile(self, q: float) -> float | None:
        """Pooled percentile over (previous + current) window; None until
        any sample exists."""
        with self._lock:
            self._maybe_rotate_locked()
            cur, prev = self._cur, self._prev
        pooled = StreamingPercentiles(self._capacity, seed=self._seed)
        pooled.merge(cur)
        pooled.merge(prev)
        return pooled.percentile(q)

    def count(self) -> int:
        with self._lock:
            self._maybe_rotate_locked()
            return self._cur.count + self._prev.count


class FleetRouter:
    """Admission + dispatch over the fleet's replicas.

    ``replicas`` is a list of objects with ``.index``, ``.batcher`` (a
    :class:`~fedcrack_tpu.serve.batcher.MicroBatcher`) and ``.alive`` —
    ``serve.fleet.Replica``. The router exposes the batcher's ``submit``
    surface so the gRPC front door works unchanged against one replica or a
    fleet."""

    def __init__(self, replicas: list, serve_config: Any, *, window_s: float = 10.0):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.serve_config = serve_config
        self._lock = make_lock("serve.router.dispatch")
        self.rolling = RollingPercentiles(window_s=window_s)
        self._shed_counts: dict[str, int] = {}
        self._m_shed = REGISTRY.counter(
            "serve_shed_total",
            "requests refused at admission (RESOURCE_EXHAUSTED) by reason",
            labels=("reason",),
        )
        self._m_replicas = REGISTRY.gauge(
            "serve_fleet_replicas",
            "live replica workers behind the fleet router",
        )
        self._m_replicas.set(sum(1 for r in self.replicas if r.alive))
        # Round 22: the very signals admission control acts on, published so
        # the autoscaler (serve/autoscaler.py) — and any operator — can
        # scrape them instead of reaching into router internals.
        self._m_rolling_p95 = REGISTRY.gauge(
            "serve_rolling_p95_seconds",
            "rolling windowed p95 served latency the admission probe reads",
        )
        # (the batcher's per-replica total already owns the unlabeled
        # serve_queue_depth_total name; this is the fleet-wide per-bucket
        # view, suffixed per OBS001's unit vocabulary)
        self._m_queue_depth = REGISTRY.gauge(
            "serve_router_queue_depth_total",
            "queued requests across live replicas per bucket",
            labels=("bucket",),
        )
        # Shadow mirror hook (serve/shadow.py): observe-only; production
        # answers never depend on it. None = no candidate under evaluation.
        self._shadow: Any | None = None

    # ---- admission control ----

    def live_replicas(self) -> list:
        return [r for r in self.replicas if r.alive]

    def total_queued(self) -> int:
        return sum(r.batcher.queued() for r in self.live_replicas())

    def shed_reason(self) -> tuple[str, str] | None:
        """(reason, detail) when the next request must be shed; None =
        admit. Checked OUTSIDE the dispatch lock — both probes are
        O(replicas) counter reads."""
        bound = self.serve_config.queue_bound
        if bound > 0:
            queued = self.total_queued()
            if queued >= bound:
                return (
                    SHED_QUEUE_BOUND,
                    f"{queued} queued >= queue_bound {bound}",
                )
        slo = self.serve_config.slo_p95_ms
        if slo > 0 and self.rolling.count() >= MIN_SHED_SAMPLES:
            p95 = self.rolling.percentile(95.0)
            if p95 is not None and p95 > slo:
                return (
                    SHED_P95_SLO,
                    f"rolling p95 {p95:.1f} ms > SLO {slo:.1f} ms",
                )
        return None

    def shed_counts(self) -> dict:
        with self._lock:
            return dict(self._shed_counts)

    def refresh_gauges(self) -> dict:
        """Publish the admission signals (rolling p95, per-bucket queue
        depth) as registry gauges and return them — called by the
        autoscaler's control loop before it scrapes the exposition, and by
        anything that wants a coherent read of router pressure. Buckets
        with empty queues still publish 0 so the series never goes stale."""
        p95_ms = self.rolling.percentile(95.0)
        p95_s = (p95_ms if p95_ms is not None else 0.0) / 1e3
        self._m_rolling_p95.set(p95_s)
        depths: dict[int, int] = {}
        for r in self.live_replicas():
            for size, n in r.batcher.queued_by_bucket().items():
                depths[size] = depths.get(size, 0) + n
        for size, n in sorted(depths.items()):
            self._m_queue_depth.labels(bucket=str(size)).set(n)
        return {"p95_s": p95_s, "queue_depth": depths}

    # ---- shadow mirroring (round 22) ----

    def attach_shadow(self, mirror: Any) -> None:
        """Install the shadow mirror hook — an object with
        ``observe(image_u8)``. The router calls it AFTER a request is
        admitted and dispatched; the hook's answer (if any) never reaches
        the client. One mirror at a time; attach replaces."""
        with self._lock:
            self._shadow = mirror

    def detach_shadow(self, mirror: Any | None = None) -> None:
        """Remove the shadow hook. With ``mirror`` given, detach only if it
        is STILL the attached one — a finished evaluation must not tear
        down its successor's mirror."""
        with self._lock:
            if mirror is None or self._shadow is mirror:
                self._shadow = None

    # ---- dispatch ----

    def _pick(self, size: int):
        """Least-outstanding live replica SERVING this bucket (ties ->
        lowest index) — the same capability filter the kill-failover path
        applies, so dispatch and reroute agree on heterogeneous fleets."""
        live = [
            r
            for r in self.live_replicas()
            if size in r.batcher.engine.bucket_sizes
        ]
        if not live:
            raise RuntimeError(f"no live replica serves bucket {size}")
        return min(live, key=lambda r: (r.batcher.outstanding(), r.index))

    def submit(self, image_u8: np.ndarray, deadline_ms: float | None = None) -> Future:
        """Admission-checked, least-outstanding-dispatched submit. Raises
        :class:`LoadShedError` when admission control refuses (the caller
        answers RESOURCE_EXHAUSTED); returns the replica batcher's Future
        otherwise."""
        shed = self.shed_reason()
        if shed is not None:
            reason, detail = shed
            with self._lock:
                self._shed_counts[reason] = self._shed_counts.get(reason, 0) + 1
            self._m_shed.labels(reason=reason).inc()
            from fedcrack_tpu.obs import flight

            flight.note("serve.shed", reason=reason, detail=detail)
            raise LoadShedError(reason, detail)
        size = image_u8.shape[0]
        # A replica may die between pick and submit (kill_replica closes its
        # batcher after flipping alive); re-pick instead of failing an
        # ADMITTED request — each retry sees one fewer live replica.
        for _ in range(len(self.replicas) + 1):
            with self._lock:
                replica = self._pick(size)
            try:
                with tracing.span(
                    "serve.route",
                    trace=f"bucket-{size}",
                    replica=replica.index,
                    bucket=size,
                    outstanding=replica.batcher.outstanding(),
                ):
                    fut = replica.batcher.submit(image_u8, deadline_ms=deadline_ms)
            except RuntimeError:
                if replica.alive:
                    raise
                continue
            fut.add_done_callback(self._on_done)
            # Mirror AFTER the production dispatch succeeded: the shadow
            # sees only admitted traffic, and nothing it does — sampling,
            # submitting to the candidate, crashing — can touch ``fut``.
            shadow = self._shadow
            if shadow is not None:
                try:
                    shadow.observe(image_u8)
                except Exception:
                    # Shadow failures are the shadow plane's problem
                    # (counted in serve/shadow.py); never the client's.
                    pass
            return fut
        raise RuntimeError("no live replicas")

    def _on_done(self, fut: Future) -> None:
        # Feed the rolling SLO probe from every answered request, whichever
        # replica served it. Failed futures carry no latency — the p95
        # probe measures served latency, the failure path is loud already.
        if fut.cancelled() or fut.exception() is not None:
            return
        self.rolling.add(fut.result().latency_ms)

    # ---- replica lifecycle ----

    def kill_replica(self, index: int) -> dict:
        """Take replica ``index`` out of rotation (the chaos drill's crash)
        and reroute its queued requests to survivors with their original
        futures — zero accepted requests dropped. Returns the reroute
        accounting. In-flight batches on the dying replica complete first
        (their snapshot was taken); with no survivors the drained requests
        fail loudly instead of hanging."""
        with self._lock:
            replica = self.replicas[index]
            if not replica.alive:
                return {"rerouted": 0, "failed": 0, "already_dead": True}
            replica.alive = False
        leftovers = replica.batcher.drain()
        rerouted = failed = 0
        for req in leftovers:
            survivors = self.live_replicas()
            target = None
            for r in sorted(survivors, key=lambda r: (r.batcher.outstanding(), r.index)):
                if req.image.shape[0] in r.batcher.engine.bucket_sizes:
                    target = r
                    break
            if target is None:
                failed += 1
                if not req.future.done():
                    req.future.set_exception(
                        RuntimeError("replica crashed with no survivor for its bucket")
                    )
                continue
            target.batcher.resubmit(req)
            rerouted += 1
        self._m_replicas.set(sum(1 for r in self.replicas if r.alive))
        from fedcrack_tpu.obs import flight

        flight.note(
            "serve.replica_killed", replica=index, rerouted=rerouted, failed=failed
        )
        return {"rerouted": rerouted, "failed": failed, "already_dead": False}

    def stats(self) -> dict:
        """Fleet-level snapshot: per-replica batcher stats + shed counts +
        the rolling p95 the admission probe reads."""
        return {
            "replicas": len(self.replicas),
            "live": len(self.live_replicas()),
            "shed": self.shed_counts(),
            "rolling_p95_ms": self.rolling.percentile(95.0),
            "per_replica": {
                str(r.index): {"alive": r.alive, **r.batcher.stats()}
                for r in self.replicas
            },
        }
