"""Live hot-swap of the served global model — serve-while-training.

The federation publishes its global model two ways: orbax round-boundary
checkpoints (``ckpt/manager.py``, one step per ``model_version``) and the
mid-round durable statefile (``ckpt/statefile.py``, msgpack with
``model_version`` + ``global_blob``). The :class:`ModelVersionManager`
watches either (or both — highest version wins), loads newer weights OFF the
serving path, places them on device via ``engine.prepare``, and installs the
new ``(version, variables)`` snapshot with one pointer flip under a lock.

The batcher reads snapshots at its request-boundary barrier, so a swap:

- never drops or stalls in-flight batches (they finish on the snapshot they
  took);
- never tears a batch across versions (one snapshot per batch);
- costs the serving path only the pointer flip — the checkpoint read,
  msgpack decode and host->device transfer all happen in the poll thread
  (``last_swap['load_ms']`` records them).

Post-swap outputs are BIT-identical to a cold start of the same round's
weights (same compiled program, same device values — test-pinned in
tests/test_serve.py).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any

import msgpack

from fedcrack_tpu.analysis.sanitizers import make_lock
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.registry import REGISTRY

log = logging.getLogger("fedcrack.serve.hot_swap")


def read_statefile_weights(path: str, template: Any | None = None):
    """(model_version, variables) from a federation statefile, or None.

    Reads the raw msgpack payload (``ckpt.statefile.STATE_FORMAT``) without
    reconstructing a ServerState — serving needs only the version counter
    and the global weights, not cohort/phase/receipts."""
    from fedcrack_tpu.ckpt.statefile import STATE_FORMAT
    from fedcrack_tpu.fed.serialization import tree_from_bytes

    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    try:
        payload = msgpack.unpackb(blob, raw=False)
        if payload.get("format") != STATE_FORMAT:
            raise ValueError(f"unknown statefile format {payload.get('format')!r}")
        version = int(payload["model_version"])
        variables = tree_from_bytes(bytes(payload["global_blob"]), template=template)
    except Exception:
        log.exception("statefile %s unreadable for serving; keeping current model", path)
        return None
    return version, variables


def publish_statefile(
    path: str,
    variables: Any = None,
    model_version: int = 0,
    *,
    blob: bytes | None = None,
) -> None:
    """Write a minimal, format-compatible statefile carrying ``variables``
    (or a pre-encoded msgpack ``blob`` of them) at ``model_version`` (atomic
    write+fsync+rename). The test/bench harnesses use this to stand in for a
    live federation publishing a new round. Pass ``blob`` when the publish
    must be cheap at trigger time (serializing a full model mid-load-test
    costs seconds under GIL contention — encode before the run instead)."""
    from fedcrack_tpu.ckpt.statefile import STATE_FORMAT
    from fedcrack_tpu.ioutils import atomic_write_bytes

    if blob is None:
        from fedcrack_tpu.fed.serialization import tree_to_bytes

        blob = tree_to_bytes(variables)
    payload = {
        "format": STATE_FORMAT,
        "phase": "FINISHED",
        "cohort": [],
        "departed": [],
        "current_round": int(model_version),
        "model_version": int(model_version),
        "failed_rounds": 0,
        "global_blob": blob,
        "received": {},
        "logs": {},
        "history": [],
        "rejected": {},
        "opt_state": None,
    }
    atomic_write_bytes(path, msgpack.packb(payload, use_bin_type=True))


class WeightSourceWatcher:
    """The federation-output watcher shared by the single-process
    :class:`ModelVersionManager` and the fleet-wide
    ``serve.fleet.FleetVersionManager`` (round 17 refactor): knows where new
    global models come from (statefile and/or orbax checkpoint dir), which
    one currently wins (highest version), and how to read them — nothing
    about serving. Corrupt/unreadable sources are logged and skipped; the
    caller keeps its current model."""

    def __init__(
        self,
        *,
        ckpt_dir: str | None = None,
        state_path: str | None = None,
        template: Any | None = None,
    ):
        self._ckpt_dir = ckpt_dir or None
        self._state_path = state_path or None
        self._template = template
        self._ckptr = None

    def _checkpointer(self):
        from fedcrack_tpu.ckpt.manager import FedCheckpointer

        if self._ckptr is None:
            self._ckptr = FedCheckpointer(self._ckpt_dir)
        else:
            # orbax caches the step listing; newer managers expose reload().
            reload = getattr(self._ckptr._mngr, "reload", None)
            if callable(reload):
                try:
                    reload()
                except Exception:
                    pass
        return self._ckptr

    def best_available(self, newer_than: int):
        """Highest-version (version, host_variables) across sources that
        beats ``newer_than``; None when nothing newer exists."""
        best = None
        if self._state_path and os.path.exists(self._state_path):
            got = read_statefile_weights(self._state_path, template=self._template)
            if got is not None and got[0] > newer_than:
                best = got
        if self._ckpt_dir and os.path.isdir(self._ckpt_dir):
            try:
                ckptr = self._checkpointer()
                latest = ckptr.latest_version()
            except Exception:
                log.exception("checkpoint dir %s unreadable; skipping", self._ckpt_dir)
                latest = None
            if latest is not None and latest > newer_than and (
                best is None or latest > best[0]
            ):
                try:
                    ckpt = ckptr.restore(self._template)
                    if ckpt is not None:
                        best = (int(ckpt.model_version), ckpt.variables)
                except Exception:
                    log.exception("checkpoint restore failed; keeping current model")
        return best

    def close(self) -> None:
        if self._ckptr is not None:
            try:
                self._ckptr.close()
            except Exception:
                pass
            self._ckptr = None


class ModelVersionManager:
    """Watches federation outputs and owns the served weights snapshot.

    ``snapshot()`` is the batcher's request-boundary read: O(lock) — never
    touches disk or device. ``poll_once()`` does all heavy lifting and is
    driven by a daemon thread every ``poll_s`` (or called directly by tests
    and chaos hooks to force a deterministic swap point).
    """

    def __init__(
        self,
        engine: Any,
        initial_variables: Any,
        *,
        initial_version: int = 0,
        ckpt_dir: str | None = None,
        state_path: str | None = None,
        poll_s: float = 2.0,
        template: Any | None = None,
        metrics: Any | None = None,
        canary: Any | None = None,
    ):
        self.engine = engine
        self._watcher = WeightSourceWatcher(
            ckpt_dir=ckpt_dir, state_path=state_path, template=template
        )
        self._poll_s = poll_s
        self._metrics = metrics
        # Canary evaluator (round 18, health/canary.py): probed at the TAIL
        # of install(), in the poll thread, after the pointer flip — a
        # raising canary can never fail or block a swap (test-pinned).
        self.canary = canary
        self._lock = make_lock("serve.hot_swap.snapshot")
        self._current = (int(initial_version), engine.prepare(initial_variables))
        # Swap wire contexts by installed version (round 16): the batcher
        # links the FIRST batch served on a version to its swap span via
        # swap_context(). Bounded — only recent versions matter.
        self._swap_ctx: dict[int, str] = {}
        self.swaps: list[dict] = []
        self.last_swap: dict | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ---- the serving-path read ----

    def snapshot(self) -> tuple[int, Any]:
        with self._lock:
            return self._current

    def swap_context(self, version: int) -> str | None:
        """The wire context of the swap that installed ``version`` (None
        for the initial weights or long-evicted versions) — what the first
        batch served on a version links its span to."""
        with self._lock:
            return self._swap_ctx.get(int(version))

    @property
    def version(self) -> int:
        return self.snapshot()[0]

    # ---- polling ----

    def poll_once(self) -> bool:
        """Check sources; install a newer model if one exists. Returns
        whether a swap happened. Heavy work (decode + device transfer) runs
        here, outside the snapshot lock."""
        current_version, _ = self.snapshot()
        got = self._watcher.best_available(current_version)
        if got is None:
            return False
        return self.install(*got)

    def install(self, version: int, host_variables: Any) -> bool:
        """Place ``host_variables`` on device and flip the served snapshot to
        ``version`` (no-op unless strictly newer). The tail of every poll —
        also the public entry for harnesses that already hold the new round's
        weights (an in-process smoke must not pay a multi-second msgpack
        decode under the serving load's GIL just to reach the flip)."""
        current_version = self.snapshot()[0]
        if version <= current_version:
            return False
        # Round 16: the swap joins the version-lineage trace and links to
        # the flush that PUBLISHED this version — whose context is
        # deterministic (spans.flush_context), so the link needs nothing
        # beyond the version counter the statefile/checkpoint already
        # carries. A version published by something other than a flush
        # (harness publish, checkpoint import) leaves the link dangling —
        # the stitcher reports it unresolved, nothing breaks.
        fctx = tracing.flush_context(version)
        sctx = tracing.TraceContext(fctx.trace, f"swap:v{version}")
        with tracing.span(
            "serve.swap",
            trace=fctx.trace,
            ctx=sctx.to_wire(),
            remote_parent=fctx.to_wire(),
            from_version=current_version,
            to_version=version,
        ) as span_handle:
            t0 = time.monotonic()
            device_variables = self.engine.prepare(host_variables)
            load_ms = (time.monotonic() - t0) * 1e3
            with self._lock:
                if version <= self._current[0]:
                    # Raced with a concurrent poll: the span records the
                    # wasted load attempt, flagged so span consumers can
                    # count installed=true against serve_swaps_total.
                    if span_handle is not None:
                        span_handle.set(installed=False)
                    return False
                # Context registered in the SAME locked section as the
                # pointer flip: a batch snapshotting the new version right
                # after the flip must find its swap_context (the batcher's
                # first-batch link is one-shot — a miss is permanent).
                self._swap_ctx[version] = sctx.to_wire()
                while len(self._swap_ctx) > 8:
                    self._swap_ctx.pop(min(self._swap_ctx))
                self._current = (version, device_variables)
            if span_handle is not None:
                span_handle.set(installed=True)
        from fedcrack_tpu.obs import flight

        flight.note(
            "serve.swap", from_version=current_version, to_version=version,
            load_ms=round(load_ms, 3),
        )
        REGISTRY.counter(
            "serve_swaps_total", "hot swaps installed by the version manager"
        ).inc()
        REGISTRY.histogram(
            "serve_swap_pause_seconds",
            "off-path load cost of a swap (decode + device placement; the "
            "serving path pays only the pointer flip)",
        ).observe(load_ms / 1e3)
        record = {
            "from_version": current_version,
            "to_version": version,
            "load_ms": round(load_ms, 3),
            # Deadline/interval math above is monotonic (t0/load_ms); the
            # wall clock appears ONLY as this display field, named "ts" per
            # the obs JSONL convention ("t" = monotonic there).
            # fedlint: disable=DET001 -- human-readable record timestamp
            "ts": time.time(),
        }
        self.swaps.append(record)
        self.last_swap = record
        log.info("hot-swapped served model: v%d -> v%d (%.1f ms load)",
                 current_version, version, load_ms)
        if self._metrics is not None:
            self._metrics.log("serve_swap", **record)
        if self.canary is not None:
            # After the flip, still in the poll thread: the serving path
            # already moved on — the probe set reuses the engine's compiled
            # bucket programs, so no recompile and no swap-path stall.
            try:
                self.canary.evaluate(version, device_variables)
            except Exception:
                log.exception("canary eval failed for v%d (swap unaffected)",
                              version)
        return True

    # ---- lifecycle ----

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self._poll_s):
                try:
                    self.poll_once()
                except Exception:
                    log.exception("hot-swap poll failed; retrying next period")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10)
        self._thread = None
        self._watcher.close()

    def __enter__(self) -> "ModelVersionManager":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
