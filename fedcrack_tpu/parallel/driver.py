"""Multi-round mesh federation driver with double-buffered staging.

The one-program round (``parallel.fedavg_mesh``) consumes per-client data
already resident on the chips; what turns it into a *federation* is this
loop: stage round r's data, dispatch the round program (asynchronously),
and — while the device computes — synthesize/shuffle and stage round r+1's
buffers, so host→device transfer rides under device time instead of adding
to it. The reference's input pipeline is the opposite architecture: a
synchronous per-batch cv2 decode in the middle of the hot loop
(reference: client_fit_model.py:30-43 inside fit, SURVEY.md §3.3) — the
first-order bottleneck SURVEY.md §7 told us to replace.

Two round execution modes (round 7):

- **Monolithic** (``round_fn`` from ``build_federated_round``): the whole
  round is one program and staging double-buffers at ROUND grain — one
  ``device_put`` of the full epoch slab per round.
- **Segmented** (a ``SegmentedRound`` from
  ``build_federated_round_segments``): the round runs as K segment
  programs with a device-resident donated carry, and the next round's
  slab streams CHUNK BY CHUNK between segment dispatches
  (``segment_overlap=True``), so a single monolithic transfer never sits
  on the bus and the previous round's chunks are released as soon as the
  round barrier passes — peak staged-data HBM is bounded by ~2 epoch
  slabs (test-pinned via ``RoundRecord.max_live_staged_bytes``). Both
  modes produce bit-identical weights (staging is data-independent and
  the segmented program is byte-exact vs the monolithic scan).

Round 3 proved the overlap inside ``bench.py`` only; this module is the
reusable component (round-3 verdict "what's weak" #2): ``bench.py``'s
reference-scale section, ``tools/measure_baseline``'s mesh rows, and
``tools/refscale_federation`` all drive rounds through it, and the overlap's
correctness (same weights as sequential staging) is test-pinned.

Mid-federation checkpoint/resume (round 7, VERDICT r5 #7): pass a
``ckpt.manager.FedCheckpointer`` as ``checkpointer`` and the driver saves
the global variables at every round boundary; a restarted session restores
the checkpoint, passes the restored variables plus ``start_round`` and
continues the same trajectory (bit-identical on the deterministic path —
the data_fn is called with absolute round indices either way).

Preemption tolerance (round 8): ``max_round_retries > 0`` arms a bounded
per-round retry loop — an attempt that raises (device/host loss) or emits
non-finite weights/metrics is rolled back to the round boundary (durable
checkpoint when available, else an in-memory snapshot) and replayed,
bit-identically. The chaos suite drives it through
``fault_injector`` (``chaos.inject.MeshChaos``); both knobs are zero-cost
when off.

Resident data plane (round 9): both staging modes above re-ship the SAME
samples every round in a new shuffle order — the wire carries a
permutation of bytes already in HBM, and the staging term of the
max(compute, staging) roofline is pure waste. ``data_placement="resident"``
stages a deduplicated ``data.pipeline.SamplePool`` ONCE (sharded
``P('clients')``) and per round uploads only the ``[C, epochs, steps, B]``
int32 gather plan (kilobytes); the round program assembles each batch on
device by ``jnp.take`` — byte-identical to the streamed round over the
host-assembled slab (test-pinned). Accounting stays honest: the pool is
charged to the first round's record, every later round's ``staged_bytes``
is indices only, and ``max_live_staged_bytes`` includes the resident pool.
An HBM guard (:func:`resident_pool_fits`) falls the federation back to the
streamed/segment-chunked path — slabs host-assembled from the same pool +
plan, same trajectory — when the pool doesn't fit; a chaos/preemption
replay re-stages pool and plan bit-identically from the retained host twin.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedcrack_tpu.data.pipeline import SamplePool, split_epoch_slab
from fedcrack_tpu.obs import spans as tracing
from fedcrack_tpu.obs.registry import REGISTRY
from fedcrack_tpu.parallel.fedavg_mesh import (
    CohortRound,
    SegmentedRound,
    pad_cohort_axis,
)

CLIENTS, BATCH = "clients", "batch"


def _observe_round_record(record: "RoundRecord", sentry: Any = None) -> None:
    """Project one RoundRecord into the metric registry (the mesh/driver
    plane of the r15 catalog) and emit its correlation span. Purely
    additive: the record stays the artifact of truth, the registry is the
    live view a scrape sees mid-session."""
    REGISTRY.counter(
        "driver_rounds_total", "mesh federated rounds driven to their barrier"
    ).inc()
    REGISTRY.histogram(
        "driver_round_seconds",
        "host wall clock of one driven round (dispatch to barrier)",
        buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
    ).observe(record.wall_clock_s)
    REGISTRY.counter(
        "driver_staged_bytes_total",
        "host->device bytes newly staged for driven rounds",
    ).inc(max(0, record.staged_bytes))
    REGISTRY.gauge(
        "driver_live_staged_bytes",
        "peak concurrently-staged driver bytes in the latest round",
    ).set(record.max_live_staged_bytes)
    if record.bytes_per_round:
        REGISTRY.counter(
            "driver_wire_bytes_total",
            "modeled update wire bytes for driven rounds (codec-priced)",
        ).inc(record.bytes_per_round)
    if sentry is not None:
        REGISTRY.gauge(
            "driver_recompiles_total",
            "RecompileSentry deltas since its mark over the driver's "
            "watched round programs (steady-state contract: 0)",
        ).set(sum(sentry.deltas().values()))
    with tracing.span(
        "driver.round",
        trace=f"round-{record.round_idx}",
        wall_s=round(record.wall_clock_s, 6),
        staging_s=round(record.staging_s, 6),
        staged_bytes=int(record.staged_bytes),
        retries=int(record.retries),
        data_placement=record.data_placement,
    ):
        pass


@dataclasses.dataclass
class RoundRecord:
    """One round's timing + metrics, host-side.

    BOUNDARY-TERM NOTE (round 7): ``staging_s`` is the host-BLOCKING
    staging time paid for THIS round's data, in both modes. Round
    ``start_round``'s record carries the initial (never-overlapped)
    staging; a sequential-mode round carries the post-barrier staging of
    its own data (measured during the previous round's slot); an
    overlapped round carries 0.0 because its staging rode under the
    previous round's compute. Before round 7 the initial staging was
    charged to NO record and sequential records carried the NEXT round's
    staging — session totals (``sum(wall_clock_s + data_fn_s +
    staging_s)``) silently understated by one staging period.

    COMPARABILITY NOTE (round 5+): in sequential mode
    (``overlap_staging=False``) the ``data_fn(r+1)`` host shuffle is ALSO
    deferred past the round barrier (previously only staging was serialized
    while the shuffle rode under the in-flight round). Sequential session
    totals therefore now include the unoverlapped shuffle and are NOT
    comparable to pre-round-5 sequential runs; per-round ``wall_clock_s``
    is the intended pure round time either way. Overlap-mode records are
    unaffected.
    """

    round_idx: int
    metrics: dict[str, np.ndarray]  # per-client leaves from the round program
    # dispatch -> metrics readback. In overlap mode the NEXT round's data_fn
    # and staging ride under the in-flight round, so their host time is
    # EMBEDDED in this wall — summing wall_clock_s + data_fn_s across records
    # double-counts data_fn. Sum wall_clock_s alone for session time (plus
    # the first record's staging_s — the initial transfer precedes the first
    # dispatch in both modes). In sequential mode (overlap_staging=False)
    # data_fn/staging run after the round barrier, so wall_clock_s is a pure
    # round time and the session total picks up shuffle + staging from the
    # records (see the class docstring).
    wall_clock_s: float
    data_fn_s: float  # host time data_fn spent producing THIS round's data
    staging_s: float  # host-blocking staging paid for THIS round's data
    staged_bytes: int  # bytes newly staged for THIS round (0 = buffers reused)
    overlapped: bool  # next round's staging rode under this round's compute
    # Segmented path only: per-segment host timeline — dispatch time of each
    # segment program plus the next-round chunk transfer that rode under it
    # ({"segment", "dispatch_s", "staging_s", "staged_bytes"} per entry).
    segments: tuple = ()
    # Peak bytes of driver-staged round data live on the mesh at any point
    # during this round (current slab + however much of the next had landed).
    max_live_staged_bytes: int = 0
    # Preemption-tolerance path only (max_round_retries > 0): how many
    # failed attempts this round absorbed before the recorded (successful)
    # one, and what each failure was ("InjectedDeviceFailure: ...",
    # "non-finite round output", ...). 0/() on the default path.
    retries: int = 0
    faults: tuple = ()
    # Which data plane executed this round: "streamed" (per-round epoch-slab
    # staging) or "resident" (device-resident pool, index-only uploads —
    # staged_bytes is then the gather plan's bytes after the first round,
    # which also carries the one-time pool transfer). A federation asked to
    # run resident but bounced by the HBM guard records "streamed".
    data_placement: str = "streamed"
    # Compressed-transport counter (round 12): what this round's client
    # uploads would cost on the wire under the round program's update
    # codec — active clients x the round_fn's priced wire_bytes_per_client
    # (compress.codecs.encoded_bytes_model; the mesh plane moves no real
    # wire bytes, so this is the analytic twin of the gRPC plane's
    # history["bytes_received"]). None for round programs without the
    # counter (spatial rounds, externally built callables).
    bytes_per_round: int | None = None


class NonFiniteRound(RuntimeError):
    """A round produced NaN/Inf weights or metrics (detected only when
    ``max_round_retries > 0`` — the detection costs one device reduction +
    scalar readback per round, so the default path never pays it)."""


def _tree_finite(tree: Any) -> bool:
    """One fused device-side finiteness reduction over every float leaf,
    a single scalar readback on the host."""
    ok = jnp.asarray(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        a = jnp.asarray(leaf)
        if jnp.issubdtype(a.dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return bool(ok)


def _barrier_read(x: jax.Array) -> None:
    """Full transfer barrier: an on-device element readback is a real
    host round-trip even through remote-device tunnels, where
    ``block_until_ready`` has been observed returning early (bench.py)."""
    float(jnp.asarray(x[(0,) * x.ndim], jnp.float32))


def stage_round_data(
    images: np.ndarray,
    masks: np.ndarray,
    mesh: Mesh,
    image_spec: P | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Put one round's ``[C, steps, B, ...]`` arrays on the mesh and barrier
    until the bytes have landed.

    Staging shapes are layout-agnostic: under a transformed model layout
    (``ModelConfig.stem_layout``) ``images`` may be pre-packed to
    ``[C, steps, B, H/2, W/2, 4*ch]`` (``data.pipeline.space_to_depth_images``
    — identical byte count, so transfer estimates and ``staged_bytes``
    accounting are unchanged); the default ``P(clients, None, batch)`` spec
    shards the same leading axes either way. Masks always stage
    full-resolution. Segment-grain staging calls this once per step-range
    chunk (``data.pipeline.split_epoch_slab``) — the layout is closed under
    step-axis slicing."""
    sharding = NamedSharding(mesh, image_spec if image_spec is not None else P(CLIENTS, None, BATCH))
    si = jax.device_put(images, sharding)
    sm = jax.device_put(masks, sharding)
    _barrier_read(si)
    _barrier_read(sm)
    return si, sm


def stage_round_indices(
    idx: np.ndarray, mesh: Mesh, seg: SegmentedRound | None = None
):
    """Put one round's ``[C, epochs, steps, B]`` int32 gather plan on the
    mesh (clients sharded, per-step batch split over the ``batch`` axis —
    the same per-shard batch the streamed slab spec delivers) and barrier.

    For a segmented round the plan is staged as one ``[C, segment_epochs,
    steps, B]`` array per segment (each ``seg.segment`` call consumes its
    own slice); monolithic rounds get the single full array. Either way the
    payload is kilobytes — the entire point of the resident plane."""
    idx = np.ascontiguousarray(np.asarray(idx, np.int32))
    sharding = NamedSharding(mesh, P(CLIENTS, None, None, BATCH))
    if seg is None:
        out = jax.device_put(idx, sharding)
        _barrier_read(out)
        return out
    se = seg.segment_epochs
    parts = tuple(
        jax.device_put(np.ascontiguousarray(idx[:, k * se : (k + 1) * se]), sharding)
        for k in range(seg.n_segments)
    )
    for p in parts:
        _barrier_read(p)
    return parts


def resident_pool_fits(
    pool_nbytes: int,
    mesh: Mesh,
    *,
    limit_bytes: int | None = None,
    safety: float = 0.8,
) -> tuple[bool, dict]:
    """HBM guard for the resident data plane: does this pool's per-device
    share fit alongside the model/carry working set?

    The limit comes from, in order: the explicit ``limit_bytes`` argument,
    ``FEDCRACK_RESIDENT_HBM_LIMIT_BYTES`` (operator override), or the
    backend's reported per-device ``bytes_limit`` (TPU). When none is
    discoverable (CPU backends report nothing useful) the guard PASSES —
    the fallback exists for devices that can say no, not to veto hosts that
    can't say anything. ``safety`` reserves headroom for weights, optimizer
    carry and activations (the guard is deliberately coarse: a wrong "fits"
    surfaces as an allocator error on the first stage, a wrong "doesn't"
    only costs the streamed path's staging).

    Returns ``(fits, info)`` where ``info`` records the decision inputs for
    artifacts/logs."""
    limit = limit_bytes
    if limit is None:
        env = os.environ.get("FEDCRACK_RESIDENT_HBM_LIMIT_BYTES", "")
        if env:
            limit = int(env)
    if limit is None:
        try:
            stats = next(iter(mesh.devices.flat)).memory_stats() or {}
            limit = stats.get("bytes_limit")
        except Exception:
            limit = None
    n_clients = int(mesh.shape[CLIENTS]) if CLIENTS in mesh.shape else 1
    per_device = -(-int(pool_nbytes) // max(1, n_clients))  # ceil
    info = {
        "pool_bytes": int(pool_nbytes),
        "per_device_bytes": per_device,
        "limit_bytes": None if limit is None else int(limit),
        "safety": safety,
    }
    if limit is None:
        info["reason"] = "no per-device memory limit discoverable; assuming fit"
        return True, info
    fits = per_device <= safety * limit
    info["reason"] = (
        "fits"
        if fits
        else f"per-device pool share {per_device} B exceeds "
        f"{safety:.0%} of the {int(limit)} B device limit"
    )
    return fits, info


def _assembling_data_fn(pool: SamplePool, data_fn: Callable) -> Callable:
    """HBM-guard fallback bridge: wrap a resident-contract ``data_fn``
    (returning ``(idx, active, n_samples)``) into the streamed contract by
    host-assembling each round's epoch slab from the pool's host twin —
    ``pool[idx]`` on host is the same data movement the device gather
    performs, so the fallback trajectory is byte-identical."""

    def wrapped(r):
        out = data_fn(r)
        if out is None:
            return None
        idx, active, n_samples = out
        images, masks = pool.assemble_round_slab(np.asarray(idx))
        return images, masks, active, n_samples

    return wrapped


def _delete_staged(chunks: Sequence[jax.Array]) -> None:
    """Release driver-owned staged buffers NOW (not at GC): the segmented
    path's 2-epoch-slab HBM bound depends on the previous round's chunks
    dying at the round barrier, not whenever the collector runs."""
    for a in chunks:
        try:
            a.delete()
        except Exception:
            pass  # already deleted / backend without explicit delete


def _save_round_checkpoint(checkpointer, round_idx, variables, record, history):
    """Persist the round boundary through ``ckpt.manager.FedCheckpointer``.
    The device_get is a deliberate barrier — checkpoint cost is NOT
    overlapped with compute (it runs between rounds, like on_round)."""
    from fedcrack_tpu.ckpt.manager import FedCheckpoint

    history.append(
        {
            "round": round_idx + 1,
            "wall_clock_s": round(record.wall_clock_s, 3),
            "loss_mean": float(np.mean(record.metrics["loss"])),
        }
    )
    checkpointer.save(
        FedCheckpoint(
            current_round=round_idx + 1,
            model_version=round_idx + 1,
            variables=jax.device_get(variables),
            history=tuple(history),
        )
    )


def _run_segmented_round(
    seg: SegmentedRound,
    variables: Any,
    si: tuple,
    sm: tuple,
    active,
    n_samples,
    *,
    data_fn,
    round_idx: int,
    n_rounds: int,
    overlap_staging: bool,
    n_chunks: int,
    mesh: Mesh,
    spec: P,
    acct: dict,
    pipelined: dict | None = None,
):
    """One segmented round: K segment dispatches with the NEXT round's slab
    streaming chunk-by-chunk between them, then the finalize program.

    Mirrors ``SegmentedRound.__call__``'s host loop plus the driver-only
    concerns — next-round staging, the per-segment host timeline, and the
    live-staged-bytes accounting (``acct`` is the driver's mutable
    ``{"live": bytes, "round_max": bytes}``). Returns ``(variables,
    metrics, out)`` where ``out`` carries the timeline, the (possibly
    host-viewed) cohort arrays, and the staged next-round state.

    ``pipelined`` (round 14, ``round_overlap``): segment 0 was already
    dispatched by the PREVIOUS round's tail (its carry/raw and the
    validated cohort arrive here); the loop resumes at segment 1 and the
    next-round data trigger fires on the first EXECUTED segment instead of
    literal ``k == 0`` (with ``n_segments == 1`` it fires after the loop).
    """
    out: dict = {
        "next_buffers": None,
        "next_cohort": None,
        "next_bytes": 0,
        "next_data_s": 0.0,
    }
    timeline: list[dict] = []
    if pipelined is None:
        active, n_samples = seg.check_inputs(si, active, n_samples)
        carry = seg.init(variables)
        raw_last = None
        start_k = 0
    else:
        active, n_samples = pipelined["active"], pipelined["n_samples"]
        carry, raw_last = pipelined["carry"], pipelined["raw"]
        timeline.append(pipelined["entry"])
        start_k = 1
    pending: list = []
    nxt = None
    did_data = False

    def _pull_next_data():
        nonlocal nxt, pending, did_data
        did_data = True
        tdd = time.perf_counter()
        nxt = data_fn(round_idx + 1)
        out["next_data_s"] = time.perf_counter() - tdd
        if nxt is not None:
            ni, nm, na, nn = nxt
            out["next_cohort"] = (na, nn)
            out["next_bytes"] = int(ni.nbytes + nm.nbytes)
            nic, nmc = split_epoch_slab(ni, nm, n_chunks)
            pending = list(zip(nic, nmc))
            out["next_buffers"] = ([], [])

    for k in range(start_k, seg.n_segments):
        td = time.perf_counter()
        carry, raw_last = seg.segment(carry, variables, si, sm)
        entry = {
            "segment": k,
            "dispatch_s": round(time.perf_counter() - td, 4),
        }
        if overlap_staging and round_idx + 1 < n_rounds:
            if not did_data:
                _pull_next_data()
            if pending:
                # One chunk transfer rides under each in-flight segment
                # (all of them at k=0 in round-grain mode).
                take = len(pending) if n_chunks == 1 else 1
                tss = time.perf_counter()
                nb = 0
                for ci, cm in pending[:take]:
                    s_i, s_m = stage_round_data(ci, cm, mesh, spec)
                    out["next_buffers"][0].append(s_i)
                    out["next_buffers"][1].append(s_m)
                    nb += int(ci.nbytes + cm.nbytes)
                del pending[:take]
                acct["live"] += nb
                acct["round_max"] = max(acct["round_max"], acct["live"])
                entry["staging_s"] = round(time.perf_counter() - tss, 4)
                entry["staged_bytes"] = nb
        timeline.append(entry)
    # A fully pipelined single-segment round never entered the loop: the
    # next round's data still has to be produced + staged (under the
    # in-flight segment 0 + finalize).
    if overlap_staging and round_idx + 1 < n_rounds and not did_data:
        _pull_next_data()
    # Chunks the segment loop didn't reach (n_chunks was clamped below
    # n_segments, or data_fn ran long): still overlapped with the in-flight
    # tail segments + finalize.
    while pending:
        ci, cm = pending.pop(0)
        tss = time.perf_counter()
        s_i, s_m = stage_round_data(ci, cm, mesh, spec)
        out["next_buffers"][0].append(s_i)
        out["next_buffers"][1].append(s_m)
        acct["live"] += int(ci.nbytes + cm.nbytes)
        acct["round_max"] = max(acct["round_max"], acct["live"])
        timeline.append(
            {
                "segment": "drain",
                "staging_s": round(time.perf_counter() - tss, 4),
                "staged_bytes": int(ci.nbytes + cm.nbytes),
            }
        )
    variables, metrics = seg.finalize(carry, variables, active, n_samples, raw_last)
    out["timeline"] = timeline
    out["active"], out["n_samples"] = active, n_samples
    return variables, metrics, out


def _run_segmented_round_resident(
    seg: SegmentedRound,
    variables: Any,
    pool_dev: tuple,
    idx_parts: tuple,
    host_idx: np.ndarray,
    active,
    n_samples,
    *,
    data_fn,
    round_idx: int,
    n_rounds: int,
    overlap_staging: bool,
    mesh: Mesh,
    acct: dict,
    pipelined: dict | None = None,
):
    """One segmented round on the resident plane: K segment dispatches over
    the shared device pool, each gathering by its own plan slice. The next
    round's plan (kilobytes) stages after the first dispatch — there is no
    slab to stream chunk-by-chunk, which is the point. ``pipelined`` as in
    :func:`_run_segmented_round` (segment 0 pre-dispatched by the previous
    round's tail under ``round_overlap``)."""
    out: dict = {
        "next_buffers": None,
        "next_cohort": None,
        "next_bytes": 0,
        "next_data_s": 0.0,
        "next_host_idx": None,
    }
    timeline: list[dict] = []
    if pipelined is None:
        active, n_samples = seg.check_inputs(
            pool_dev, active, n_samples, idx=host_idx
        )
        carry = seg.init(variables)
        raw_last = None
        start_k = 0
    else:
        active, n_samples = pipelined["active"], pipelined["n_samples"]
        carry, raw_last = pipelined["carry"], pipelined["raw"]
        timeline.append(pipelined["entry"])
        start_k = 1
    did_data = False

    def _pull_next_plan(entry=None):
        nonlocal did_data
        did_data = True
        tdd = time.perf_counter()
        nxt = data_fn(round_idx + 1)
        out["next_data_s"] = time.perf_counter() - tdd
        if nxt is not None:
            nidx, na, nn = nxt
            nidx = np.ascontiguousarray(np.asarray(nidx, np.int32))
            out["next_cohort"] = (na, nn)
            out["next_host_idx"] = nidx
            out["next_bytes"] = int(nidx.nbytes)
            tss = time.perf_counter()
            out["next_buffers"] = stage_round_indices(nidx, mesh, seg)
            acct["live"] += out["next_bytes"]
            acct["round_max"] = max(acct["round_max"], acct["live"])
            if entry is not None:
                entry["staging_s"] = round(time.perf_counter() - tss, 4)
                entry["staged_bytes"] = out["next_bytes"]

    for k in range(start_k, seg.n_segments):
        td = time.perf_counter()
        carry, raw_last = seg.segment(carry, variables, pool_dev, idx_parts[k])
        entry = {
            "segment": k,
            "dispatch_s": round(time.perf_counter() - td, 4),
        }
        if overlap_staging and round_idx + 1 < n_rounds and not did_data:
            _pull_next_plan(entry)
        timeline.append(entry)
    if overlap_staging and round_idx + 1 < n_rounds and not did_data:
        _pull_next_plan()
    variables, metrics = seg.finalize(carry, variables, active, n_samples, raw_last)
    out["timeline"] = timeline
    out["active"], out["n_samples"] = active, n_samples
    return variables, metrics, out


def _dispatch_pipelined_segment(
    seg: SegmentedRound,
    out_vars: Any,
    resident: bool,
    *,
    si,
    sm,
    active,
    n_samples,
    host_idx_cur,
    segout,
    next_buffers,
    next_cohort,
):
    """Round-overlap (round 14): dispatch the NEXT round's init + segment-0
    programs against the in-flight current round's output, before the host
    blocks on the current round's metrics. Data dependencies (the new
    variables) order the device; the host merely enqueues earlier — same
    expression tree, bit-identical trajectory. When the next round reuses
    this round's buffers (``data_fn`` returned None) the dispatch runs over
    the current staged data and cohort."""
    td = time.perf_counter()
    if resident:
        if next_buffers is not None:
            idx_parts = next_buffers
            na, nn = next_cohort
            host_idx = segout["next_host_idx"]
        else:
            idx_parts = sm
            na, nn = active, n_samples
            host_idx = host_idx_cur
        pa, pn = seg.check_inputs(si, na, nn, idx=host_idx)
        carry = seg.init(out_vars)
        carry, raw = seg.segment(carry, out_vars, si, idx_parts[0])
    else:
        if next_buffers is not None:
            nsi, nsm = tuple(next_buffers[0]), tuple(next_buffers[1])
            na, nn = next_cohort
        else:
            nsi, nsm = si, sm
            na, nn = active, n_samples
        pa, pn = seg.check_inputs(nsi, na, nn)
        carry = seg.init(out_vars)
        carry, raw = seg.segment(carry, out_vars, nsi, nsm)
    return {
        "carry": carry,
        "raw": raw,
        "active": pa,
        "n_samples": pn,
        "entry": {
            "segment": 0,
            "dispatch_s": round(time.perf_counter() - td, 4),
            "pipelined": True,
        },
    }


def run_mesh_federation(
    round_fn: Callable,
    variables: Any,
    data_fn: Callable[[int], Any],
    n_rounds: int,
    mesh: Mesh,
    *,
    image_spec: P | None = None,
    overlap_staging: bool = True,
    segment_overlap: bool = True,
    round_overlap: bool = False,
    data_placement: str = "streamed",
    sample_pool: SamplePool | None = None,
    streamed_round_fn: Callable | None = None,
    resident_limit_bytes: int | None = None,
    on_round: Callable[[RoundRecord, Any], None] | None = None,
    checkpointer: Any | None = None,
    start_round: int = 0,
    history: Sequence[dict] = (),
    max_round_retries: int = 0,
    fault_injector: Callable[[int, int], Any] | None = None,
    recompile_sentry: Any | None = None,
) -> tuple[Any, list[RoundRecord]]:
    """Drive federated rounds ``start_round .. n_rounds-1`` through
    ``round_fn``.

    - ``round_fn``: a round program from ``build_federated_round`` /
      ``build_spatial_federated_round`` (signature
      ``(variables, images, masks, active, n_samples) -> (variables,
      metrics)``), or a :class:`~fedcrack_tpu.parallel.fedavg_mesh.
      SegmentedRound` from ``build_federated_round_segments`` — the driver
      then runs the segment loop itself so staging can stream between
      segment dispatches.
    - ``data_fn(r)``: host data for round ``r`` as ``(images, masks,
      active, n_samples)`` numpy arrays, or ``None`` to reuse round
      ``r-1``'s staged buffers and cohort (a client whose local dataset
      doesn't change between rounds should not re-ship it).
      ``data_fn(start_round)`` must return data. With ``overlap_staging``
      on, ``data_fn(r+1)`` is called while round ``r`` runs on device, so
      per-round synthesis/shuffle cost also hides under compute; with it
      off, it is called after round ``r``'s barrier, so sequential timing
      charges it separately.
    - ``overlap_staging``: stage round r+1 while round r's program runs
      (double buffering). ``False`` serializes staging after the round
      barrier — the two orders produce bit-identical weights (staging is
      data-independent), which the driver's tests pin.
    - ``segment_overlap`` (segmented rounds only): ``True`` streams the
      next round's slab as one step-range chunk per segment dispatch
      (epoch-grain double buffering — no monolithic transfer ever sits on
      the bus); ``False`` keeps round-grain staging (the full next slab
      transfers after the first segment dispatch). Ignored for monolithic
      ``round_fn``s.
    - ``round_overlap`` (round 14, segmented rounds only): overlap round
      N+1's FIRST SEGMENT dispatch with round N's aggregation tail — after
      round N's finalize program is dispatched (asynchronously), round
      N+1's init + segment-0 programs are dispatched against its output
      BEFORE the host blocks on round N's metrics readback, so the
      readback + record bookkeeping + ``on_round`` host work hide under
      device compute instead of serializing the rounds at the host. Pure
      host scheduling: the device-side expression tree is unchanged, so
      the trajectory is BIT-identical to ``round_overlap=False``
      (test-pinned). Requires a ``SegmentedRound`` (the r7 segment
      boundaries are the interleave points), ``overlap_staging=True`` (the
      next round's data must be staged before its segment can dispatch),
      and ``max_round_retries == 0`` (a pipelined segment dispatched
      against a round that later fails its finiteness check would need
      unwinding). The pipelined segment's dispatch time is recorded in the
      CONSUMING round's timeline (``"pipelined": True``) but rode under
      the previous round's wall.
    - ``data_placement``: ``"streamed"`` (default — the contracts above) or
      ``"resident"``: ``round_fn`` must be built with
      ``data_placement="resident"``, ``sample_pool`` must be the
      :class:`~fedcrack_tpu.data.pipeline.SamplePool` the plan indexes
      into, and ``data_fn(r)`` returns ``(idx, active, n_samples)`` where
      ``idx`` is the round's ``[C, epochs, steps, B]`` int32 gather plan
      (``SamplePool.round_indices``), or ``None`` to reuse round ``r-1``'s
      plan. The driver stages the pool ONCE (charged to the first executed
      round's record), uploads only the plan per round (same
      overlap/sequential semantics as slab staging), and keeps the pool
      resident across rounds — per-round ``staged_bytes`` collapses from
      the epoch slab to the plan's kilobytes. On a retry
      (``max_round_retries``) pool AND plan are re-staged bit-identically
      from the retained host twin before the replay.
    - ``streamed_round_fn`` + ``resident_limit_bytes``: the HBM-guard
      fallback. When :func:`resident_pool_fits` (against
      ``resident_limit_bytes``, the ``FEDCRACK_RESIDENT_HBM_LIMIT_BYTES``
      env override, or the backend's reported per-device limit) says the
      pool does NOT fit, the federation runs ``streamed_round_fn`` (a
      streamed-contract round over the same mesh/model) with epoch slabs
      host-assembled from the pool + plan — byte-identical trajectory,
      records tagged ``data_placement="streamed"``. With no fallback round
      provided, an unfittable pool raises instead of guessing.
    - ``on_round(record, variables)``: per-round hook (metrics sinks,
      held-out eval). ``variables`` is the round's output pytree, still on
      device; the hook runs between rounds, so its cost is NOT overlapped
      with device compute.
    - ``checkpointer``: optional ``ckpt.manager.FedCheckpointer``; the
      driver saves the global variables + history at EVERY round boundary
      (after ``on_round``). To resume a killed session, restore the
      checkpoint, pass the restored variables, ``start_round =
      ckpt.current_round`` and ``history = ckpt.history`` — with a
      deterministic ``data_fn`` the continued trajectory is identical to
      the uninterrupted run (test-pinned).
    - ``start_round``: absolute index of the first round to run (checkpoint
      resume); ``data_fn`` and ``RoundRecord.round_idx`` use absolute
      indices throughout.
    - ``max_round_retries``: preemption tolerance (0 disables, the default
      — no snapshotting, no finiteness checks, no overhead). With N > 0,
      each round absorbs up to N failed attempts: an attempt that raises
      (device/host loss) or produces non-finite weights/metrics is rolled
      back — weights restored from this round's boundary (the
      ``checkpointer``'s latest step when present, else an in-memory host
      snapshot taken at round start) — and replayed with the same
      ``data_fn(r)`` data, so the recovered trajectory is bit-identical to
      an unfaulted run (test-pinned). Attempt N+1's failure re-raises: a
      clean abort, never a hang. Per-round cost when enabled: one host
      ``device_get`` of the weights + one fused device-side finiteness
      reduction. NOTE: bit-identical replay requires ``data_fn`` to be a
      pure function of the round index — a data_fn advancing a shared RNG
      per CALL (rather than seeding from ``r``) yields a different shuffle
      on the replayed attempt (still a valid federation, not the pinned
      identical trajectory).
    - ``fault_injector``: chaos hook (``chaos.inject.MeshChaos``), called
      as ``injector(round_idx, attempt)`` before each attempt; it may raise
      (simulated preemption) or return an output-poisoning transform.
      Production runs leave it None.

    Returns the final global ``variables`` (on device) and one
    :class:`RoundRecord` per executed round. The first round's wall-clock
    includes XLA compilation; report post-compile medians from
    ``records[1:]``.

    Single-process staging only: ``stage_round_data`` device_puts host
    arrays this process can address in full. A multi-host job stages each
    process's client shards with ``jax.make_array_from_process_local_data``
    (see ``parallel.multihost`` and tests/test_multihost.py) and should
    drive its own round loop around ``round_fn``.
    """
    if n_rounds <= 0:
        raise ValueError(f"n_rounds must be positive, got {n_rounds}")
    if not 0 <= start_round < n_rounds:
        raise ValueError(
            f"start_round={start_round} outside [0, n_rounds={n_rounds})"
        )
    if max_round_retries < 0:
        raise ValueError(
            f"max_round_retries must be >= 0, got {max_round_retries}"
        )
    if data_placement not in ("streamed", "resident"):
        raise ValueError(
            f"data_placement must be 'streamed' or 'resident', got {data_placement!r}"
        )
    resident = data_placement == "resident"
    if resident:
        if sample_pool is None:
            raise ValueError("data_placement='resident' needs a sample_pool")
        if getattr(round_fn, "data_placement", "streamed") != "resident":
            raise ValueError(
                "data_placement='resident' needs a round_fn built with "
                "data_placement='resident' (the gather-assembly data contract)"
            )
        fits, guard = resident_pool_fits(
            sample_pool.nbytes, mesh, limit_bytes=resident_limit_bytes
        )
        if not fits:
            if streamed_round_fn is None:
                raise RuntimeError(
                    f"resident sample pool does not fit HBM ({guard['reason']}) "
                    "and no streamed_round_fn fallback was provided"
                )
            if getattr(streamed_round_fn, "data_placement", "streamed") != "streamed":
                raise ValueError("streamed_round_fn must be a streamed-contract round")
            # Same pool, same plan, same trajectory — just host-assembled
            # slabs shipped the old way.
            round_fn = streamed_round_fn
            data_fn = _assembling_data_fn(sample_pool, data_fn)
            resident = False
    elif getattr(round_fn, "data_placement", "streamed") != "streamed":
        raise ValueError(
            "round_fn was built with data_placement='resident' but the driver "
            "was asked to run streamed — pass data_placement='resident' plus "
            "the sample_pool (mismatched contracts would feed slabs to a "
            "gather program)"
        )
    spec = image_spec if image_spec is not None else P(CLIENTS, None, BATCH)
    seg = round_fn if isinstance(round_fn, SegmentedRound) else None
    if round_overlap:
        if seg is None:
            raise ValueError(
                "round_overlap=True requires a SegmentedRound — the r7 "
                "segment boundaries are the interleave points (an HBM-guard "
                "fallback to a monolithic streamed_round_fn cannot pipeline)"
            )
        if not overlap_staging:
            raise ValueError(
                "round_overlap=True requires overlap_staging=True: the next "
                "round's data must be staged before its first segment can "
                "dispatch early"
            )
        if max_round_retries > 0:
            raise ValueError(
                "round_overlap does not compose with max_round_retries: a "
                "pipelined segment dispatched against a round that later "
                "fails its finiteness check would need unwinding — run "
                "preemption tolerance without round-overlap"
            )
    hist = list(history)

    t0 = time.perf_counter()
    first = data_fn(start_round)
    data_s = time.perf_counter() - t0
    if first is None:
        raise ValueError(
            f"data_fn({start_round}) returned None: the first round has no data"
        )
    n_chunks = 1
    base_bytes = 0  # non-rotating driver-staged bytes (the resident pool)
    host_idx_cur = None
    ts = time.perf_counter()
    if resident:
        idx0, active, n_samples = first
        host_idx_cur = np.ascontiguousarray(np.asarray(idx0, np.int32))
        # The pool stages ONCE; it never rotates with the rounds.
        si = sample_pool.stage(mesh)
        sm = stage_round_indices(host_idx_cur, mesh, seg)
        base_bytes = sample_pool.nbytes
        staged_bytes = base_bytes + int(host_idx_cur.nbytes)
        cur_bytes = int(host_idx_cur.nbytes)
    else:
        images, masks, active, n_samples = first
        if seg is not None:
            n_chunks = seg.n_segments if segment_overlap else 1
            ic, mc = split_epoch_slab(images, masks, n_chunks)
            staged_pairs = [stage_round_data(i, m, mesh, spec) for i, m in zip(ic, mc)]
            si = tuple(p[0] for p in staged_pairs)
            sm = tuple(p[1] for p in staged_pairs)
        else:
            si, sm = stage_round_data(images, masks, mesh, spec)
        staged_bytes = int(images.nbytes + masks.nbytes)
        cur_bytes = staged_bytes
    # Charged to the first executed round's record (boundary-term fix,
    # round 7): the initial transfer is host-blocking in both modes.
    pending_staging_s = time.perf_counter() - ts
    acct = {"live": base_bytes + cur_bytes, "round_max": base_bytes + cur_bytes}

    records: list[RoundRecord] = []
    # round_overlap: the NEXT round's pre-dispatched segment-0 state
    # (carry/raw/validated cohort + its timeline entry), produced at the
    # previous round's tail and consumed by the next runner call.
    pipelined_state: dict | None = None
    for r in range(start_round, n_rounds):
        # Preemption tolerance: snapshot the round's input weights so a
        # failed attempt (device loss, non-finite output) can replay THIS
        # round from identical state. Host device_get round-trips float32
        # exactly, so the replayed trajectory is bit-identical (test-pinned).
        snapshot = jax.device_get(variables) if max_round_retries > 0 else None
        # Codec-twin cross-round state rides the same contract (r12 review
        # fix): the round program commits its error-feedback pytree / int8
        # seed counter when the async dispatch returns — before a
        # non-finite output surfaces at the host fetch — so a retry must
        # roll it back too, or the topk twin banks mass from the discarded
        # attempt. Pointer-level snapshot (immutable jax arrays + an int).
        codec_snapshot = (
            round_fn.codec_state()
            if max_round_retries > 0 and hasattr(round_fn, "codec_state")
            else None
        )
        attempt = 0
        round_faults: list[str] = []
        while True:
            acct["round_max"] = acct["live"]
            next_buffers = None
            next_cohort = None
            next_bytes = 0
            next_data_s = 0.0
            next_staging_s = 0.0
            next_host_idx = None
            timeline: list[dict] = []

            t0 = time.perf_counter()
            try:
                post = None
                if fault_injector is not None:
                    # Chaos hook (chaos.inject.MeshChaos): may raise (device
                    # failure) or return an output poison; one attribute
                    # check when absent.
                    post = fault_injector(r, attempt)
                if seg is None:
                    out_vars, metrics = round_fn(
                        variables, si, sm, active, n_samples
                    )
                    if post is not None:
                        out_vars, metrics = post(out_vars, metrics)

                    if overlap_staging and r + 1 < n_rounds:
                        # The round program is in flight; data_fn's host work
                        # and the staging transfers ride under it (the
                        # barrier inside stage_round_data only waits for the
                        # *transfer*, not the round), which is why this
                        # round's wall embeds them — see RoundRecord.
                        td = time.perf_counter()
                        nxt = data_fn(r + 1)
                        next_data_s = time.perf_counter() - td
                        if nxt is not None:
                            if resident:
                                nidx, na, nn = nxt
                                next_host_idx = np.ascontiguousarray(
                                    np.asarray(nidx, np.int32)
                                )
                                next_cohort = (na, nn)
                                next_bytes = int(next_host_idx.nbytes)
                                next_buffers = stage_round_indices(
                                    next_host_idx, mesh, None
                                )
                            else:
                                ni, nm, na, nn = nxt
                                next_cohort = (na, nn)
                                next_bytes = int(ni.nbytes + nm.nbytes)
                                next_buffers = stage_round_data(ni, nm, mesh, spec)
                            acct["live"] += next_bytes
                            acct["round_max"] = max(
                                acct["round_max"], acct["live"]
                            )
                elif resident:
                    out_vars, metrics, segout = _run_segmented_round_resident(
                        seg,
                        variables,
                        si,
                        sm,
                        host_idx_cur,
                        active,
                        n_samples,
                        data_fn=data_fn,
                        round_idx=r,
                        n_rounds=n_rounds,
                        overlap_staging=overlap_staging,
                        mesh=mesh,
                        acct=acct,
                        pipelined=pipelined_state,
                    )
                    if post is not None:
                        out_vars, metrics = post(out_vars, metrics)
                    timeline = segout["timeline"]
                    next_buffers = segout["next_buffers"]
                    next_cohort = segout["next_cohort"]
                    next_bytes = segout["next_bytes"]
                    next_data_s = segout["next_data_s"]
                    next_host_idx = segout["next_host_idx"]
                    active, n_samples = segout["active"], segout["n_samples"]
                else:
                    out_vars, metrics, segout = _run_segmented_round(
                        seg,
                        variables,
                        si,
                        sm,
                        active,
                        n_samples,
                        data_fn=data_fn,
                        round_idx=r,
                        n_rounds=n_rounds,
                        overlap_staging=overlap_staging,
                        n_chunks=n_chunks,
                        mesh=mesh,
                        spec=spec,
                        acct=acct,
                        pipelined=pipelined_state,
                    )
                    if post is not None:
                        out_vars, metrics = post(out_vars, metrics)
                    timeline = segout["timeline"]
                    next_buffers = segout["next_buffers"]
                    next_cohort = segout["next_cohort"]
                    next_bytes = segout["next_bytes"]
                    next_data_s = segout["next_data_s"]
                    active, n_samples = segout["active"], segout["n_samples"]

                if max_round_retries > 0 and not (
                    _tree_finite(metrics) and _tree_finite(out_vars)
                ):
                    raise NonFiniteRound(
                        f"round {r} produced non-finite weights/metrics"
                    )
                pipelined_state = None
                if round_overlap and r + 1 < n_rounds:
                    # Dispatch round r+1's init + segment 0 against this
                    # round's (still in-flight) output BEFORE blocking on
                    # its metrics — round N's aggregation-tail readback now
                    # rides under round N+1's first segment. Device
                    # ordering is by data dependency, so the math is
                    # bit-identical to the unpipelined schedule.
                    pipelined_state = _dispatch_pipelined_segment(
                        seg,
                        out_vars,
                        resident,
                        si=si,
                        sm=sm,
                        active=active,
                        n_samples=n_samples,
                        host_idx_cur=host_idx_cur,
                        segout=segout if seg is not None else None,
                        next_buffers=next_buffers,
                        next_cohort=next_cohort,
                    )
                # Round barrier: metrics depend on every step of every client.
                metrics_host = jax.tree_util.tree_map(np.asarray, metrics)
                variables = out_vars
                wall = time.perf_counter() - t0
                break
            except Exception as e:
                if attempt >= max_round_retries:
                    raise
                round_faults.append(f"{type(e).__name__}: {e}")
                attempt += 1
                # Drop whatever of the NEXT round landed during the failed
                # attempt; the retry re-produces it (deterministic data_fn).
                if next_buffers is not None:
                    if resident:
                        flat = (
                            next_buffers
                            if isinstance(next_buffers, tuple)
                            else (next_buffers,)
                        )
                    elif seg is not None:
                        flat = tuple(next_buffers[0]) + tuple(next_buffers[1])
                    else:
                        flat = next_buffers
                    _delete_staged(flat)
                acct["live"] = base_bytes + cur_bytes
                if resident:
                    # A real preemption may have taken the resident pool
                    # down with the device: drop the placement and re-stage
                    # pool AND plan from the retained host twin — bit
                    # identical (test-pinned), charged to this round's
                    # staging term.
                    rs = time.perf_counter()
                    _delete_staged(
                        tuple(si)
                        + (tuple(sm) if isinstance(sm, tuple) else (sm,))
                    )
                    si = sample_pool.stage(mesh)
                    sm = stage_round_indices(host_idx_cur, mesh, seg)
                    pending_staging_s += time.perf_counter() - rs
                # Restore the round's input weights: prefer the durable
                # checkpoint (it IS this round's boundary when present —
                # a real preemption may have taken the in-memory snapshot
                # down with the host), else the host snapshot.
                restored = None
                if checkpointer is not None:
                    try:
                        ck = checkpointer.restore(template=snapshot)
                        if ck is not None and ck.current_round == r:
                            restored = ck.variables
                    except Exception:
                        restored = None
                variables = restored if restored is not None else snapshot
                if codec_snapshot is not None:
                    round_fn.set_codec_state(codec_snapshot)

        if not overlap_staging and r + 1 < n_rounds:
            # Sequential mode: produce AND stage the next round's data after
            # the barrier, so the recorded wall is a pure round time and the
            # shuffle cost is paid (and accounted) outside it. The staging
            # time is charged to the NEXT round's record (the round that
            # consumes the data — see the RoundRecord boundary-term note).
            td = time.perf_counter()
            nxt = data_fn(r + 1)
            next_data_s = time.perf_counter() - td
            if nxt is not None:
                ts = time.perf_counter()
                if resident:
                    nidx, na, nn = nxt
                    next_host_idx = np.ascontiguousarray(
                        np.asarray(nidx, np.int32)
                    )
                    next_cohort = (na, nn)
                    next_bytes = int(next_host_idx.nbytes)
                    next_buffers = stage_round_indices(next_host_idx, mesh, seg)
                elif seg is not None:
                    ni, nm, na, nn = nxt
                    next_cohort = (na, nn)
                    next_bytes = int(ni.nbytes + nm.nbytes)
                    nic, nmc = split_epoch_slab(ni, nm, n_chunks)
                    pairs = [
                        stage_round_data(ci, cm, mesh, spec)
                        for ci, cm in zip(nic, nmc)
                    ]
                    next_buffers = (
                        [p[0] for p in pairs],
                        [p[1] for p in pairs],
                    )
                else:
                    ni, nm, na, nn = nxt
                    next_cohort = (na, nn)
                    next_bytes = int(ni.nbytes + nm.nbytes)
                    next_buffers = stage_round_data(ni, nm, mesh, spec)
                next_staging_s = time.perf_counter() - ts
                acct["live"] += next_bytes
                acct["round_max"] = max(acct["round_max"], acct["live"])

        wpc = getattr(round_fn, "wire_bytes_per_client", None)
        bytes_per_round = None
        if wpc:
            try:
                n_active = int(np.sum(np.asarray(active, np.float32) > 0.0))
            except Exception:
                # Cross-process sharded cohort mask: this process cannot
                # fetch it — charge the full client axis.
                n_active = int(mesh.shape[CLIENTS]) if CLIENTS in mesh.shape else 1
            bytes_per_round = int(wpc) * n_active
        record = RoundRecord(
            round_idx=r,
            metrics=metrics_host,
            wall_clock_s=wall,
            data_fn_s=data_s,
            staging_s=pending_staging_s,
            staged_bytes=staged_bytes,
            overlapped=overlap_staging and next_buffers is not None,
            segments=tuple(timeline),
            max_live_staged_bytes=acct["round_max"],
            retries=attempt,
            faults=tuple(round_faults),
            data_placement="resident" if resident else "streamed",
            bytes_per_round=bytes_per_round,
        )
        records.append(record)
        _observe_round_record(record, sentry=recompile_sentry)
        if on_round is not None:
            on_round(record, variables)
        if checkpointer is not None:
            _save_round_checkpoint(checkpointer, r, variables, record, hist)

        data_s = next_data_s
        pending_staging_s = next_staging_s
        if next_buffers is not None:
            # The round barrier above guarantees every consumer of the old
            # buffers has run; release them NOW so peak staged HBM stays at
            # ~2 epoch slabs instead of growing until GC. On the resident
            # plane only the gather plan rotates — the pool stays put.
            if resident:
                _delete_staged(tuple(sm) if isinstance(sm, tuple) else (sm,))
                sm = next_buffers
                host_idx_cur = next_host_idx
            elif seg is not None:
                _delete_staged(tuple(si) + tuple(sm))
                si = tuple(next_buffers[0])
                sm = tuple(next_buffers[1])
            else:
                _delete_staged((si, sm))
                si, sm = next_buffers
            acct["live"] -= cur_bytes
            cur_bytes = next_bytes
            active, n_samples = next_cohort
            staged_bytes = next_bytes
        else:
            staged_bytes = 0

    return variables, records


def _stage_group_slab(images, masks, mesh, spec):
    """Stage one GROUP's ``[G, steps, B, ...]`` slab pair and barrier."""
    return stage_round_data(
        np.ascontiguousarray(images), np.ascontiguousarray(masks), mesh, spec
    )


def _stage_group_resident(pool_i, pool_m, idx, mesh):
    """Stage one group's resident pool slice (sharded ``P('clients')``)
    plus its full-round gather plan, barriered."""
    sharding = NamedSharding(mesh, P(CLIENTS))
    si = jax.device_put(np.ascontiguousarray(pool_i), sharding)
    sm = jax.device_put(np.ascontiguousarray(pool_m), sharding)
    _barrier_read(si)
    _barrier_read(sm)
    sx = jax.device_put(
        np.ascontiguousarray(idx), NamedSharding(mesh, P(CLIENTS, None, None, BATCH))
    )
    _barrier_read(sx)
    return (si, sm), sx


def _prep_cohort_round(
    cohort_round: CohortRound,
    r: int,
    data,
    sample_pool: SamplePool | None,
    resident: bool,
) -> dict:
    """Validate + pad one round's cohort data into the staging-ready form
    (shared by the inline and the round-overlap pipelined paths)."""
    if data is None:
        raise ValueError(f"data_fn({r}) returned None: a cohort round never reuses")
    g = cohort_round.group_size
    prep: dict = {}
    if resident:
        idx, active, n_samples = data
        idx = np.ascontiguousarray(np.asarray(idx, np.int32))
        c = idx.shape[0]
        if sample_pool.n_clients != c:
            raise ValueError(
                f"sample_pool carries {sample_pool.n_clients} clients, "
                f"round {r}'s plan {c} — the pool's client axis must "
                "align with the cohort"
            )
        prep["idx"] = idx
    else:
        images, masks, active, n_samples = data
        images = np.asarray(images)
        masks = np.asarray(masks)
        c = images.shape[0]
        cohort_round.seg.validate_data(images)
        prep["images"], prep["masks"] = images, masks
    active = np.asarray(active, np.float32)
    n_samples = np.asarray(n_samples, np.float32)
    if active.shape[0] != c:
        raise ValueError(
            f"cohort data carries {c} clients, mask {active.shape[0]}"
        )
    if float(np.sum(active * n_samples)) <= 0.0:
        raise ValueError(
            "non-positive total FedAvg weight: every cohort client dropped"
        )
    n_groups = cohort_round.n_groups(c)
    c_pad = n_groups * g
    prep["active"] = pad_cohort_axis(active, c_pad)
    prep["n_samples"] = pad_cohort_axis(n_samples, c_pad)
    prep["c"], prep["n_groups"] = c, n_groups
    return prep


def _stage_cohort_group(
    prep: dict,
    gi: int,
    g: int,
    mesh: Mesh,
    spec: P,
    sample_pool: SamplePool | None,
    resident: bool,
):
    """Stage ONE group's slab (or resident pool slice + plan), padding only
    the last group's slice for ragged cohorts."""
    c = prep["c"]
    lo, hi = gi * g, (gi + 1) * g

    def slice_pad(arr):
        # Pad ONLY the last group's slice (ragged cohorts): padding the
        # whole cohort array up front would copy the entire pool/slab
        # host-side every round — GBs of memcpy for one short group.
        part = arr[lo:min(hi, c)]
        return part if part.shape[0] == hi - lo else pad_cohort_axis(part, hi - lo)

    ts = time.perf_counter()
    if resident:
        pi = slice_pad(sample_pool.images)
        pm = slice_pad(sample_pool.masks)
        ix = slice_pad(prep["idx"])
        bufs = _stage_group_resident(pi, pm, ix, mesh)
        nbytes = int(pi.nbytes + pm.nbytes + ix.nbytes)
    else:
        gi_imgs = slice_pad(prep["images"])
        gi_msks = slice_pad(prep["masks"])
        bufs = _stage_group_slab(gi_imgs, gi_msks, mesh, spec)
        nbytes = int(gi_imgs.nbytes + gi_msks.nbytes)
    return bufs, nbytes, time.perf_counter() - ts


def run_cohort_federation(
    cohort_round: CohortRound,
    variables: Any,
    data_fn: Callable[[int], Any],
    n_rounds: int,
    mesh: Mesh,
    *,
    sample_pool: SamplePool | None = None,
    image_spec: P | None = None,
    round_overlap: bool = False,
    on_round: Callable[[RoundRecord, Any], None] | None = None,
    recompile_sentry: Any | None = None,
) -> tuple[Any, list[RoundRecord]]:
    """Drive a time-multiplexed cohort federation (round 13): each round's
    C-client cohort executes as ``ceil(C / G)`` sequential group dispatches
    over the G-wide mesh, with PER-GROUP staging — group g+1's slab (or
    resident pool slice + plan) stages while group g's programs run, and
    group g's buffers are released at its barrier, so peak driver-staged
    HBM is ~2 group slices regardless of C.

    - ``cohort_round``: a :class:`~fedcrack_tpu.parallel.fedavg_mesh.
      CohortRound` from ``build_federated_cohort_round``.
    - ``data_fn(r)``: the round's cohort — streamed: ``(images [C, steps,
      B, ...], masks, active [C], n_samples [C])`` numpy arrays; resident
      (``sample_pool`` set): ``(idx [C, epochs, steps, B], active,
      n_samples)`` where ``idx`` indexes the COHORT-wide ``sample_pool``
      (the pool's host twin is sliced and staged per group — the r9
      resident plane at group grain). Cohort sampling composes here: a
      ``data_fn`` built on :func:`fedcrack_tpu.fed.algorithms.
      sample_cohort` makes the whole multi-round trajectory reproducible
      from one seed. Unlike ``run_mesh_federation`` there is no
      ``None``-reuse contract — every round supplies its cohort (cohorts
      change per round; that is the point).
    - ``on_round(record, variables)``: per-round hook, as in
      :func:`run_mesh_federation`.

    ``round_overlap`` (round 14): overlap round N+1's cohort production,
    first-group staging AND first-group dispatch with round N's
    aggregation tail — after round N's ``finish`` program is dispatched
    (asynchronously), round N+1's data_fn/staging/group-0 programs run
    against its output BEFORE the host blocks on round N's metrics
    readback. Pure host scheduling over the same data-dependency graph, so
    the trajectory is BIT-identical to the unoverlapped schedule
    (test-pinned). The pipelined group's dispatch/staging host time is
    recorded in the CONSUMING round's timeline (``"pipelined": True``) but
    rode under the previous round's wall.

    Returns the final global ``variables`` and one :class:`RoundRecord`
    per round; ``record.segments`` carries the per-GROUP host timeline
    (``{"group", "dispatch_s", "staging_s", "staged_bytes"}``) — round
    wall scales ~linearly in the number of group dispatches, the
    cohort-scale roofline BASELINE.md "Round 13" models.
    """
    if n_rounds <= 0:
        raise ValueError(f"n_rounds must be positive, got {n_rounds}")
    resident = sample_pool is not None
    if resident and cohort_round.data_placement != "resident":
        raise ValueError(
            "sample_pool given but cohort_round was built streamed — build "
            "it with data_placement='resident' for the pool/plan contract"
        )
    if not resident and cohort_round.data_placement == "resident":
        raise ValueError(
            "cohort_round is resident but no sample_pool was given"
        )
    spec = image_spec if image_spec is not None else P(CLIENTS, None, BATCH)
    g = cohort_round.group_size
    records: list[RoundRecord] = []
    # round_overlap: round r+1's prepped data + staged group 0 + its
    # dispatched (sums, raw) carry, produced at round r's tail.
    pipeline: dict | None = None

    for r in range(n_rounds):
        if pipeline is None:
            td = time.perf_counter()
            data = data_fn(r)
            data_s = time.perf_counter() - td
            prep = _prep_cohort_round(cohort_round, r, data, sample_pool, resident)
            t0 = time.perf_counter()
            cur, cur_bytes, stage_s = _stage_cohort_group(
                prep, 0, g, mesh, spec, sample_pool, resident
            )
            sums = cohort_round.zeros(variables)
            pre_raw = None
            pre_entry = None
        else:
            prep = pipeline["prep"]
            data_s = pipeline["data_s"]
            t0 = pipeline["t0"]
            cur, cur_bytes, stage_s = pipeline["staged"]
            sums = pipeline["sums"]
            pre_raw = pipeline["raw"]
            pre_entry = pipeline["entry"]
            pipeline = None
        active, n_samples = prep["active"], prep["n_samples"]
        n_groups = prep["n_groups"]
        raw_lasts = []
        timeline: list[dict] = []
        staged_total = 0
        staging_total = 0.0
        live = cur_bytes
        round_max = live
        for gi in range(n_groups):
            lo = gi * g
            if gi == 0 and pre_raw is not None:
                # Group 0 was dispatched by the previous round's tail
                # (round_overlap): its fold already sits in `sums`.
                raw = pre_raw
                entry = pre_entry
            else:
                tdp = time.perf_counter()
                if resident:
                    (pool_dev, idx_dev) = cur
                    sums, raw = cohort_round.run_group(
                        sums, variables, pool_dev, idx_dev,
                        active[lo : lo + g], n_samples[lo : lo + g],
                    )
                else:
                    si, sm = cur
                    sums, raw = cohort_round.run_group(
                        sums, variables, si, sm,
                        active[lo : lo + g], n_samples[lo : lo + g],
                    )
                entry = {
                    "group": gi,
                    "dispatch_s": round(time.perf_counter() - tdp, 4),
                    "staging_s": round(stage_s, 4),
                    "staged_bytes": cur_bytes,
                }
            staged_total += cur_bytes
            staging_total += stage_s
            nxt = None
            if gi + 1 < n_groups:
                # Next group's transfer rides under this group's compute
                # (the dispatches above are async; only the staging
                # barrier blocks the host).
                nxt, nxt_bytes, stage_s = _stage_cohort_group(
                    prep, gi + 1, g, mesh, spec, sample_pool, resident
                )
                live += nxt_bytes
                round_max = max(round_max, live)
            # Group barrier: raw_last depends on every step of every
            # client in the group, so fetching it proves the staged
            # buffers are consumed and safe to release.
            raw = jax.tree_util.tree_map(np.asarray, raw)
            raw_lasts.append(raw)
            if resident:
                _delete_staged(tuple(cur[0]) + (cur[1],))
            else:
                _delete_staged(cur)
            live -= cur_bytes
            timeline.append(entry)
            if nxt is not None:
                cur, cur_bytes = nxt, nxt_bytes
        out_vars, metrics = cohort_round.finish(
            sums, variables, raw_lasts, active, prep["c"]
        )
        if round_overlap and r + 1 < n_rounds:
            # Round r's finish is dispatched but not yet read back: produce
            # round r+1's cohort, stage its first group and dispatch its
            # first group program NOW, so all that host work (and the
            # metrics readback below) hides under device compute. Data
            # dependencies (out_vars) keep the device order — and thus the
            # trajectory — bit-identical.
            td = time.perf_counter()
            data2 = data_fn(r + 1)
            data2_s = time.perf_counter() - td
            prep2 = _prep_cohort_round(
                cohort_round, r + 1, data2, sample_pool, resident
            )
            t0n = time.perf_counter()
            cur2, cur2_bytes, stage2_s = _stage_cohort_group(
                prep2, 0, g, mesh, spec, sample_pool, resident
            )
            sums2 = cohort_round.zeros(out_vars)
            tdp = time.perf_counter()
            if resident:
                (pool2, idx2) = cur2
                sums2, raw2 = cohort_round.run_group(
                    sums2, out_vars, pool2, idx2,
                    prep2["active"][:g], prep2["n_samples"][:g],
                )
            else:
                si2, sm2 = cur2
                sums2, raw2 = cohort_round.run_group(
                    sums2, out_vars, si2, sm2,
                    prep2["active"][:g], prep2["n_samples"][:g],
                )
            pipeline = {
                "prep": prep2,
                "data_s": data2_s,
                "t0": t0n,
                "staged": (cur2, cur2_bytes, stage2_s),
                "sums": sums2,
                "raw": raw2,
                "entry": {
                    "group": 0,
                    "dispatch_s": round(time.perf_counter() - tdp, 4),
                    "staging_s": round(stage2_s, 4),
                    "staged_bytes": cur2_bytes,
                    "pipelined": True,
                },
            }
        # Round barrier (the aggregation-tail readback round_overlap hides
        # the pipelined work under).
        metrics_host = jax.tree_util.tree_map(np.asarray, metrics)
        variables = out_vars
        wall = time.perf_counter() - t0
        record = RoundRecord(
            round_idx=r,
            metrics=metrics_host,
            wall_clock_s=wall,
            data_fn_s=data_s,
            staging_s=staging_total,
            staged_bytes=staged_total,
            overlapped=n_groups > 1 or pre_raw is not None,
            segments=tuple(timeline),
            max_live_staged_bytes=round_max,
            data_placement="resident" if resident else "streamed",
        )
        records.append(record)
        _observe_round_record(record, sentry=recompile_sentry)
        if on_round is not None:
            on_round(record, variables)
    return variables, records


def shuffled_epoch_data(
    pool_images: np.ndarray,
    pool_masks: np.ndarray,
    steps: int,
    batch_size: int,
    rng: np.random.Generator,
) -> tuple[np.ndarray, np.ndarray]:
    """One client's reshuffled epoch in the round layout ``[1, steps, B, ...]``.

    A fresh permutation of the client's fixed sample pool per round — the
    reference reshuffles between fits the same way (keras Sequence +
    ``fit`` per round, client_fit_model.py:164-166). Returning new arrays
    per round is what makes per-round restaging (and thus the double
    buffer) load-bearing rather than decorative.
    """
    n = pool_images.shape[0]
    need = steps * batch_size
    if n < need:
        raise ValueError(f"pool has {n} samples, round needs {need}")
    idx = rng.permutation(n)[:need]
    images = np.ascontiguousarray(
        pool_images[idx].reshape(1, steps, batch_size, *pool_images.shape[1:])
    )
    masks = np.ascontiguousarray(
        pool_masks[idx].reshape(1, steps, batch_size, *pool_masks.shape[1:])
    )
    return images, masks
