"""Multi-round mesh federation driver with double-buffered staging.

The one-program round (``parallel.fedavg_mesh``) consumes per-client data
already resident on the chips; what turns it into a *federation* is this
loop: stage round r's data, dispatch the round program (asynchronously),
and — while the device computes — synthesize/shuffle and stage round r+1's
buffers, so host→device transfer rides under device time instead of adding
to it. The reference's input pipeline is the opposite architecture: a
synchronous per-batch cv2 decode in the middle of the hot loop
(reference: client_fit_model.py:30-43 inside fit, SURVEY.md §3.3) — the
first-order bottleneck SURVEY.md §7 told us to replace.

Round 3 proved the overlap inside ``bench.py`` only; this module is the
reusable component (round-3 verdict "what's weak" #2): ``bench.py``'s
reference-scale section, ``tools/measure_baseline``'s mesh rows, and
``tools/refscale_federation`` all drive rounds through it, and the overlap's
correctness (same weights as sequential staging) is test-pinned.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENTS, BATCH = "clients", "batch"


@dataclasses.dataclass
class RoundRecord:
    """One round's timing + metrics, host-side.

    COMPARABILITY NOTE (round 5+): in sequential mode
    (``overlap_staging=False``) the ``data_fn(r+1)`` host shuffle is ALSO
    deferred past the round barrier (previously only staging was serialized
    while the shuffle rode under the in-flight round). Sequential session
    totals (``sum(wall_clock_s + data_fn_s + staging_s)``) therefore now
    include the unoverlapped shuffle and are NOT comparable to pre-round-5
    sequential runs; per-round ``wall_clock_s`` is the intended pure round
    time either way. Overlap-mode records are unaffected.
    """

    round_idx: int
    metrics: dict[str, np.ndarray]  # per-client leaves from the round program
    # dispatch -> metrics readback. In overlap mode the NEXT round's data_fn
    # and staging ride under the in-flight round, so their host time is
    # EMBEDDED in this wall — summing wall_clock_s + data_fn_s across records
    # double-counts data_fn. Sum wall_clock_s alone for session time. In
    # sequential mode (overlap_staging=False) data_fn/staging run after the
    # round barrier, so wall_clock_s is a pure round time (and the session
    # total picks up the shuffle separately — see the class docstring).
    wall_clock_s: float
    data_fn_s: float  # host time data_fn spent producing THIS round's data
    staging_s: float  # sequential-mode next-round staging (0 when overlapped)
    staged_bytes: int  # bytes newly staged for THIS round (0 = buffers reused)
    overlapped: bool  # next round's staging rode under this round's compute


def _barrier_read(x: jax.Array) -> None:
    """Full transfer barrier: an on-device element readback is a real
    host round-trip even through remote-device tunnels, where
    ``block_until_ready`` has been observed returning early (bench.py)."""
    float(jnp.asarray(x[(0,) * x.ndim], jnp.float32))


def stage_round_data(
    images: np.ndarray,
    masks: np.ndarray,
    mesh: Mesh,
    image_spec: P | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Put one round's ``[C, steps, B, ...]`` arrays on the mesh and barrier
    until the bytes have landed.

    Staging shapes are layout-agnostic: under a transformed model layout
    (``ModelConfig.stem_layout``) ``images`` may be pre-packed to
    ``[C, steps, B, H/2, W/2, 4*ch]`` (``data.pipeline.space_to_depth_images``
    — identical byte count, so transfer estimates and ``staged_bytes``
    accounting are unchanged); the default ``P(clients, None, batch)`` spec
    shards the same leading axes either way. Masks always stage
    full-resolution."""
    sharding = NamedSharding(mesh, image_spec if image_spec is not None else P(CLIENTS, None, BATCH))
    si = jax.device_put(images, sharding)
    sm = jax.device_put(masks, sharding)
    _barrier_read(si)
    _barrier_read(sm)
    return si, sm


def run_mesh_federation(
    round_fn: Callable,
    variables: Any,
    data_fn: Callable[[int], Any],
    n_rounds: int,
    mesh: Mesh,
    *,
    image_spec: P | None = None,
    overlap_staging: bool = True,
    on_round: Callable[[RoundRecord, Any], None] | None = None,
) -> tuple[Any, list[RoundRecord]]:
    """Drive ``n_rounds`` federated rounds through ``round_fn``.

    - ``round_fn``: a round program from ``build_federated_round`` /
      ``build_spatial_federated_round`` (signature
      ``(variables, images, masks, active, n_samples) -> (variables,
      metrics)``).
    - ``data_fn(r)``: host data for round ``r`` as ``(images, masks,
      active, n_samples)`` numpy arrays, or ``None`` to reuse round
      ``r-1``'s staged buffers and cohort (a client whose local dataset
      doesn't change between rounds should not re-ship it). ``data_fn(0)``
      must return data. With ``overlap_staging`` on, ``data_fn(r+1)`` is
      called while round ``r`` runs on device, so per-round synthesis/
      shuffle cost also hides under compute; with it off, it is called after
      round ``r``'s barrier, so sequential timing charges it separately.
    - ``overlap_staging``: stage round r+1 while round r's program runs
      (double buffering). ``False`` serializes staging after the round
      barrier — the two orders produce bit-identical weights (staging is
      data-independent), which the driver's tests pin.
    - ``on_round(record, variables)``: per-round hook (metrics sinks,
      checkpointing, held-out eval). ``variables`` is the round's output
      pytree, still on device; the hook runs between rounds, so its cost is
      NOT overlapped with device compute.

    Returns the final global ``variables`` (on device) and one
    :class:`RoundRecord` per round. The first round's wall-clock includes
    XLA compilation; report post-compile medians from ``records[1:]``.

    Single-process staging only: ``stage_round_data`` device_puts host
    arrays this process can address in full. A multi-host job stages each
    process's client shards with ``jax.make_array_from_process_local_data``
    (see ``parallel.multihost`` and tests/test_multihost.py) and should
    drive its own round loop around ``round_fn``.
    """
    if n_rounds <= 0:
        raise ValueError(f"n_rounds must be positive, got {n_rounds}")
    spec = image_spec if image_spec is not None else P(CLIENTS, None, BATCH)

    t0 = time.perf_counter()
    first = data_fn(0)
    data_s = time.perf_counter() - t0
    if first is None:
        raise ValueError("data_fn(0) returned None: the first round has no data")
    images, masks, active, n_samples = first
    si, sm = stage_round_data(images, masks, mesh, spec)
    staged_bytes = int(images.nbytes + masks.nbytes)

    records: list[RoundRecord] = []
    for r in range(n_rounds):
        t0 = time.perf_counter()
        variables, metrics = round_fn(variables, si, sm, active, n_samples)

        next_buffers = None
        next_cohort = None
        next_host = None
        next_data_s = 0.0
        if overlap_staging and r + 1 < n_rounds:
            # The round program is in flight; data_fn's host work and the
            # staging transfers ride under it (the barrier inside
            # stage_round_data only waits for the *transfer*, not the round),
            # which is why this round's wall embeds them — see RoundRecord.
            td = time.perf_counter()
            nxt = data_fn(r + 1)
            next_data_s = time.perf_counter() - td
            if nxt is not None:
                ni, nm, na, nn = nxt
                next_host = (ni, nm)
                next_cohort = (na, nn)
                next_buffers = stage_round_data(ni, nm, mesh, spec)

        # Round barrier: the metrics depend on every step of every client.
        metrics_host = jax.tree_util.tree_map(np.asarray, metrics)
        wall = time.perf_counter() - t0

        staging_s = 0.0
        if not overlap_staging and r + 1 < n_rounds:
            # Sequential mode: produce AND stage the next round's data after
            # the barrier, so the recorded wall is a pure round time and the
            # shuffle cost is paid (and accounted) outside it.
            td = time.perf_counter()
            nxt = data_fn(r + 1)
            next_data_s = time.perf_counter() - td
            if nxt is not None:
                ni, nm, na, nn = nxt
                next_host = (ni, nm)
                next_cohort = (na, nn)
                ts = time.perf_counter()
                next_buffers = stage_round_data(ni, nm, mesh, spec)
                staging_s = time.perf_counter() - ts

        record = RoundRecord(
            round_idx=r,
            metrics=metrics_host,
            wall_clock_s=wall,
            data_fn_s=data_s,
            staging_s=staging_s,
            staged_bytes=staged_bytes,
            overlapped=overlap_staging and next_host is not None,
        )
        records.append(record)
        if on_round is not None:
            on_round(record, variables)

        data_s = next_data_s
        if next_buffers is not None:
            si, sm = next_buffers
            active, n_samples = next_cohort
            staged_bytes = int(next_host[0].nbytes + next_host[1].nbytes)
        else:
            staged_bytes = 0

    return variables, records


def shuffled_epoch_data(
    pool_images: np.ndarray,
    pool_masks: np.ndarray,
    steps: int,
    batch_size: int,
    rng: np.random.Generator,
) -> tuple[np.ndarray, np.ndarray]:
    """One client's reshuffled epoch in the round layout ``[1, steps, B, ...]``.

    A fresh permutation of the client's fixed sample pool per round — the
    reference reshuffles between fits the same way (keras Sequence +
    ``fit`` per round, client_fit_model.py:164-166). Returning new arrays
    per round is what makes per-round restaging (and thus the double
    buffer) load-bearing rather than decorative.
    """
    n = pool_images.shape[0]
    need = steps * batch_size
    if n < need:
        raise ValueError(f"pool has {n} samples, round needs {need}")
    idx = rng.permutation(n)[:need]
    images = np.ascontiguousarray(
        pool_images[idx].reshape(1, steps, batch_size, *pool_images.shape[1:])
    )
    masks = np.ascontiguousarray(
        pool_masks[idx].reshape(1, steps, batch_size, *pool_masks.shape[1:])
    )
    return images, masks
