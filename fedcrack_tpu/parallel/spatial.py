"""Spatial context parallelism: the U-Net sharded over image height with
halo exchange on the ICI mesh.

The reference has no sequence axis to parallelize (conv net on fixed
128x128 crops — SURVEY.md §5.7); the TPU-native analog of ring-attention /
sequence parallelism for this model family is **sharding the spatial H axis
across a ``space`` mesh axis** so arbitrarily tall images (large survey
photos, stitched crack panoramas) train and infer without replicating the
full activation map on any chip. Every 3x3 window that straddles a shard
boundary is fed by a one-row **halo exchange** (`lax.ppermute` with
neighbor permutation — zeros arrive at the global edges, which is exactly
SAME zero padding), so the sharded forward is numerically identical to the
single-device model: it consumes the *same* ``{'params', 'batch_stats'}``
pytree as :class:`fedcrack_tpu.models.ResUNet` and matches its output.

Per-op halo geometry (H axis; W stays shard-local), derived from the
reference architecture (client_fit_model.py:92-150):

- 3x3 stride-1 conv / depthwise / ConvTranspose, SAME: halo 1 up + 1 down
  (Keras/XLA pad (1,1)).
- 3x3 stride-2 conv (stem) and 3x3/2 max-pool, SAME on even H: XLA pads
  (0,1), so halo 1 *down* only; the pool's bottom-edge pad is -inf, not 0.
- 1x1 convs (residual projections, head) and x2 nearest upsampling: purely
  local — shard row offsets stay even because per-shard H is a multiple
  of 16 (stem /2 + three pools /2).

Training mode is **sync-BN**: batch moments are ``pmean``-ed over the
``space`` (and optional ``data``) axes, so the sharded train step computes
bit-for-bit the same update as the single-device
:func:`fedcrack_tpu.train.local.train_step` (gradients of the halo exchange
flow back through the transposed permutation automatically).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.jaxcompat import psum_if_no_auto, shard_map
from fedcrack_tpu.models.resunet import _BN_EPSILON, _BN_MOMENTUM, upsample2x
from fedcrack_tpu.ops.pallas_bce import fused_segmentation_metrics
from fedcrack_tpu.train.local import make_optimizer

SPACE, DATA = "space", "data"

_DIMNUMS = ("NHWC", "HWIO", "NHWC")


def halo_exchange(
    x: jax.Array,
    axis_name: str,
    axis_size: int,
    up: int = 1,
    down: int = 1,
    fill: float = 0.0,
) -> jax.Array:
    """Concatenate ``up`` rows from the previous shard and ``down`` rows from
    the next shard onto the H axis (axis 1 of NHWC). Global edges receive
    ``fill`` (0 for SAME conv padding, -inf for max-pool padding)."""
    parts = []
    if up:
        recv = _shift(x[:, -up:], axis_name, axis_size, toward="down")
        if fill != 0.0:
            is_first = lax.axis_index(axis_name) == 0
            recv = jnp.where(is_first, jnp.full_like(recv, fill), recv)
        parts.append(recv)
    parts.append(x)
    if down:
        recv = _shift(x[:, :down], axis_name, axis_size, toward="up")
        if fill != 0.0:
            is_last = lax.axis_index(axis_name) == axis_size - 1
            recv = jnp.where(is_last, jnp.full_like(recv, fill), recv)
        parts.append(recv)
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else x


def _shift(rows: jax.Array, axis_name: str, axis_size: int, toward: str) -> jax.Array:
    """ppermute neighbor shift; destinations with no source get zeros."""
    if axis_size == 1:
        return jnp.zeros_like(rows)
    if toward == "down":  # shard s receives shard s-1's rows
        perm = [(i, i + 1) for i in range(axis_size - 1)]
    else:  # shard s receives shard s+1's rows
        perm = [(i + 1, i) for i in range(axis_size - 1)]
    return lax.ppermute(rows, axis_name, perm)


def _conv(x, kernel, bias=None, *, strides=(1, 1), padding, groups=1):
    kernel = kernel.astype(x.dtype)
    bias = None if bias is None else bias.astype(x.dtype)
    y = lax.conv_general_dilated(
        x,
        kernel,
        window_strides=strides,
        padding=padding,
        dimension_numbers=_DIMNUMS,
        feature_group_count=groups,
    )
    if bias is not None:
        y = y + bias
    return y


def _conv3x3_s1(x, p, axis_name, axis_size, *, groups=1):
    """SAME stride-1 3x3 (plain, depthwise, or ConvTranspose — all reduce to
    pad-(1,1) cross-correlation; Flax ConvTranspose with stride 1 does not
    flip the kernel)."""
    xp = halo_exchange(x, axis_name, axis_size, up=1, down=1)
    return _conv(
        x=xp,
        kernel=p["kernel"],
        bias=p.get("bias"),
        padding=[(0, 0), (1, 1)],
        groups=groups,
    )


def _conv3x3_s2(x, p, axis_name, axis_size):
    """SAME stride-2 3x3 on even H: XLA pads (0, 1) so only a bottom halo."""
    xp = halo_exchange(x, axis_name, axis_size, up=0, down=1)
    return _conv(
        x=xp,
        kernel=p["kernel"],
        bias=p.get("bias"),
        strides=(2, 2),
        padding=[(0, 0), (0, 1)],
    )


def _conv1x1(x, p, *, strides=(1, 1)):
    return _conv(
        x=x, kernel=p["kernel"], bias=p.get("bias"), strides=strides, padding=[(0, 0), (0, 0)]
    )


def _maxpool3x3_s2(x, axis_name, axis_size):
    """SAME 3x3/2 max-pool; the implicit SAME padding value is -inf."""
    neg = float(jnp.finfo(x.dtype).min)
    xp = halo_exchange(x, axis_name, axis_size, up=0, down=1, fill=neg)
    return lax.reduce_window(
        xp,
        -jnp.inf,
        lax.max,
        window_dimensions=(1, 3, 3, 1),
        window_strides=(1, 2, 2, 1),
        padding=[(0, 0), (0, 0), (0, 1), (0, 0)],
    )


def _bn(x, params, stats, *, train, sync_axes):
    """Keras-default BatchNorm (momentum 0.99, eps 1e-3). In train mode the
    batch moments are pmean-synchronized over ``sync_axes`` so sharded
    normalization equals the single-device op; returns updated running
    stats (train) or None (inference).

    Dtype handling mirrors flax.linen.BatchNorm: moments are computed in
    (at least) float32, normalization runs in the activation dtype with
    params/stats cast down, and running stats stay in their storage dtype —
    so bfloat16 compute configs behave like the single-device model instead
    of silently promoting everything to float32."""
    dtype = x.dtype
    scale, bias = params["scale"].astype(dtype), params["bias"].astype(dtype)
    if not train:
        # Association matches flax.linen.BatchNorm exactly:
        # (x - mean) * (rsqrt(var + eps) * scale) + bias.
        var = stats["var"].astype(dtype)
        mean = stats["mean"].astype(dtype)
        mul = lax.rsqrt(var + jnp.asarray(_BN_EPSILON, dtype)) * scale
        return (x - mean) * mul + bias, None
    axes = (0, 1, 2)
    stats_dtype = jnp.promote_types(jnp.float32, dtype)
    xs = x.astype(stats_dtype)
    mean = jnp.mean(xs, axes)
    mean2 = jnp.mean(jnp.square(xs), axes)
    if sync_axes:
        # One collective per layer: stack both moments into a single pmean.
        mean, mean2 = lax.pmean(jnp.stack([mean, mean2]), sync_axes)
    var = mean2 - jnp.square(mean)
    y = (x - mean.astype(dtype)) * (
        lax.rsqrt(var.astype(dtype) + jnp.asarray(_BN_EPSILON, dtype))
        * scale
    ) + bias
    new_stats = {
        "mean": _BN_MOMENTUM * stats["mean"] + (1.0 - _BN_MOMENTUM) * mean.astype(stats["mean"].dtype),
        "var": _BN_MOMENTUM * stats["var"] + (1.0 - _BN_MOMENTUM) * var.astype(stats["var"].dtype),
    }
    return y, new_stats


def spatial_apply(
    variables: dict,
    x: jax.Array,
    *,
    config: ModelConfig | None = None,
    axis_name: str = SPACE,
    axis_size: int,
    train: bool = False,
    sync_axes: Sequence[str] | None = None,
):
    """H-sharded forward of the crack U-Net (reference architecture:
    client_fit_model.py:92-150), consuming :class:`ResUNet` variables
    unchanged. Call inside ``shard_map`` with ``x`` sharded on axis 1.

    Returns logits (``train=False``) or ``(logits, new_batch_stats)``
    (``train=True``, sync-BN over ``sync_axes`` — defaults to the space
    axis).
    """
    cfg = config or ModelConfig()
    if cfg.stem_layout != "reference" or cfg.res_layout != "reference":
        # The per-op halo geometry above is derived for the reference ops;
        # silently computing the reference program under a transformed-layout
        # config would make the flag a no-op here. (Parameter shapes are
        # layout-invariant, so the VALUES would even be right — but a config
        # that claims a layout must either run it or refuse.)
        raise ValueError(
            "spatial_apply supports the reference layout only; got "
            f"stem_layout={cfg.stem_layout!r}, res_layout={cfg.res_layout!r}"
        )
    p = variables["params"]
    bs = variables["batch_stats"]
    sync = tuple(sync_axes) if sync_axes is not None else (axis_name,)
    new_stats: dict[str, Any] = {}
    bn = functools.partial(_bn, train=train, sync_axes=sync)
    x = x.astype(jnp.dtype(cfg.compute_dtype))

    def apply_bn(x, name):
        y, updated = bn(x, p[name], bs[name])
        if updated is not None:
            new_stats[name] = updated
        return y

    # Stem: /2.
    x = _conv3x3_s2(x, p["stem_conv"], axis_name, axis_size)
    x = apply_bn(x, "stem_bn")
    x = jax.nn.relu(x)
    previous = x

    # Encoder.
    for i, _features in enumerate(cfg.encoder_features):
        x = jax.nn.relu(x)
        x = _sepconv(x, p[f"enc{i}_sep1"], axis_name, axis_size)
        x = apply_bn(x, f"enc{i}_bn1")
        x = jax.nn.relu(x)
        x = _sepconv(x, p[f"enc{i}_sep2"], axis_name, axis_size)
        x = apply_bn(x, f"enc{i}_bn2")
        x = _maxpool3x3_s2(x, axis_name, axis_size)
        residual = _conv1x1(previous, p[f"enc{i}_res"], strides=(2, 2))
        x = x + residual
        previous = x

    # Decoder.
    for i, _features in enumerate(cfg.decoder_features):
        x = jax.nn.relu(x)
        x = _conv3x3_s1(x, p[f"dec{i}_convT1"], axis_name, axis_size)
        x = apply_bn(x, f"dec{i}_bn1")
        x = jax.nn.relu(x)
        x = _conv3x3_s1(x, p[f"dec{i}_convT2"], axis_name, axis_size)
        x = apply_bn(x, f"dec{i}_bn2")
        # Same algebraic fusion as models/resunet.py: the 1x1 residual conv
        # commutes with nearest upsampling, so conv + add happen pre-upsample
        # and one broadcast replaces two (also halves the halo shard's HBM
        # traffic here).
        residual = _conv1x1(previous, p[f"dec{i}_res"])
        x = x + residual
        if i + 1 < len(cfg.decoder_features):
            x = upsample2x(x)
            previous = x
        # else: final upsample deferred past the head, as in resunet.py.

    # Head at half resolution, then upsample the single logit channel —
    # the same head/upsample commute as models/resunet.py (upsampling is
    # shard-local: it only replicates within rows this shard owns).
    logits = upsample2x(_conv1x1(x.astype(jnp.float32), jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32), p["head"]
    )))
    if not train:
        return logits
    return logits, new_stats


def _sepconv(x, p, axis_name, axis_size):
    """Keras SeparableConv2D: bias-free depthwise 3x3 + biased pointwise."""
    c = x.shape[-1]
    x = _conv3x3_s1(x, p["depthwise"], axis_name, axis_size, groups=c)
    return _conv1x1(x, p["pointwise"])


def _validate_shape(h: int, w: int, axis_size: int) -> None:
    # Per-shard H must survive stem /2 + three pools /2 with even alignment
    # at every stage, i.e. be a multiple of 16 (ModelConfig.__post_init__'s
    # single-device constraint, applied per shard). W stays local but the
    # hardcoded even-size SAME pads need the same /16 divisibility.
    if h % (16 * axis_size) != 0:
        raise ValueError(
            f"image height {h} must be a multiple of 16 x {axis_size} shards "
            f"= {16 * axis_size} for the spatially-sharded U-Net"
        )
    if w % 16 != 0:
        raise ValueError(
            f"image width {w} must be a multiple of 16 for the U-Net"
        )


def _image_spec(mesh: Mesh, batch_axis: str, space_axis: str) -> P:
    if space_axis not in mesh.shape:
        raise ValueError(f"mesh {mesh.axis_names} has no '{space_axis}' axis")
    batch = batch_axis if batch_axis in mesh.shape else None
    return P(batch, space_axis)


def build_spatial_predict(
    mesh: Mesh,
    config: ModelConfig | None = None,
    batch_axis: str = DATA,
    space_axis: str = SPACE,
):
    """Compile-once sharded inference: ``fn(variables, images[B,H,W,3]) ->
    sigmoid probabilities [B,H,W,1]``, H sharded over ``space_axis`` (and B
    over ``batch_axis`` when the mesh has one). Output equals
    :func:`fedcrack_tpu.models.predict` on one device."""
    cfg = config or ModelConfig()
    s = mesh.shape[space_axis]
    spec = _image_spec(mesh, batch_axis, space_axis)

    def fwd(variables, images):
        logits = spatial_apply(
            variables, images, config=cfg, axis_name=space_axis, axis_size=s
        )
        return jax.nn.sigmoid(logits)

    jitted = jax.jit(
        shard_map(fwd, mesh=mesh, in_specs=(P(), spec), out_specs=spec)
    )

    def predict_fn(variables, images):
        _validate_shape(images.shape[1], images.shape[2], s)
        return jitted(variables, images)

    return predict_fn


def build_spatial_train_step(
    mesh: Mesh,
    config: ModelConfig | None = None,
    learning_rate: float = 1e-3,
    batch_axis: str = DATA,
    space_axis: str = SPACE,
    tx: optax.GradientTransformation | None = None,
    pos_weight: float = 1.0,
):
    """Compile-once sharded train step, numerically equivalent to the
    single-device :func:`fedcrack_tpu.train.local.train_step` (Adam + fused
    BCE, sync-BN): ``step(params, batch_stats, opt_state, images, masks) ->
    (params, batch_stats, opt_state, metrics)`` with images/masks sharded
    ``P(batch_axis?, space_axis)`` and all states replicated.

    ``tx`` overrides the default Adam (e.g. SGD for gradient-parity tests).
    Use ``step_fn.tx.init(params)`` for the initial ``opt_state``.
    """
    cfg = config or ModelConfig()
    tx = tx if tx is not None else make_optimizer(learning_rate)
    s = mesh.shape[space_axis]
    spec = _image_spec(mesh, batch_axis, space_axis)
    sync = tuple(a for a in (batch_axis, space_axis) if a in mesh.shape)
    pw = float(pos_weight)

    def step(params, batch_stats, opt_state, images, masks):
        def loss_fn(prm):
            logits, new_stats = spatial_apply(
                {"params": prm, "batch_stats": batch_stats},
                images,
                config=cfg,
                axis_name=space_axis,
                axis_size=s,
                train=True,
                sync_axes=sync,
            )
            m = fused_segmentation_metrics(
                logits, masks, pos_weight=jnp.asarray(pw, jnp.float32)
            )
            return m["loss"], (m, new_stats)

        (_, (m, new_stats)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        # `params` is replicated (unvarying) over the mesh, so shard_map's AD
        # already psums the per-shard cotangents to keep the gradient
        # replicated; with equal-sized shards dividing by the shard count
        # turns that sum of local-mean gradients into the gradient of the
        # global-mean loss. Pre-vma JAX performs NO such AD psum — jaxcompat
        # inserts the equivalent explicit one there (identity on current JAX).
        grads = psum_if_no_auto(grads, sync)
        n_shards = 1
        for a in sync:
            n_shards *= mesh.shape[a]
        grads = jax.tree_util.tree_map(lambda g: g / n_shards, grads)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        metrics = {
            "loss": lax.pmean(m["loss"], sync),
            "pixel_acc": lax.pmean(m["pixel_acc"], sync),
            "iou_inter": lax.psum(m["iou_inter"], sync),
            "iou_union": lax.psum(m["iou_union"], sync),
        }
        return new_params, new_stats, new_opt_state, metrics

    jitted = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(P(), P(), P(), spec, spec),
            out_specs=(P(), P(), P(), P()),
        )
    )

    def step_fn(params, batch_stats, opt_state, images, masks):
        _validate_shape(images.shape[1], images.shape[2], s)
        return jitted(params, batch_stats, opt_state, images, masks)

    step_fn.tx = tx
    return step_fn


def make_spatial_mesh(
    n_space: int,
    n_data: int = 1,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh with axes ``('data', 'space')`` for spatially-sharded jobs."""
    from fedcrack_tpu.parallel.mesh import make_mesh

    return make_mesh(n_data, n_space, devices, axis_names=(DATA, SPACE))
