"""TPU data plane: federated rounds as single compiled XLA programs.

The reference's "collectives" are Python loops over pickled weight lists
shipped through gRPC (reference: fl_server.py:92-105, fl_client.py:63). Here
the whole round — K clients' local SGD plus FedAvg aggregation — runs as one
``shard_map`` program over a ``Mesh(('clients', 'batch'))``: one federated
client per chip (or chip group), aggregation as a masked ``lax.psum`` over
the ``clients`` axis riding ICI, gradient data-parallelism as ``lax.pmean``
over the ``batch`` axis (SURVEY.md §5.8, §7 step 5).
"""

from fedcrack_tpu.parallel.mesh import make_mesh  # noqa: F401
from fedcrack_tpu.parallel.driver import (  # noqa: F401
    RoundRecord,
    resident_pool_fits,
    run_cohort_federation,
    run_mesh_federation,
    shuffled_epoch_data,
    stage_round_data,
    stage_round_indices,
)
from fedcrack_tpu.parallel.fedavg_mesh import (  # noqa: F401
    CohortRound,
    SegmentedRound,
    build_federated_cohort_round,
    build_federated_round,
    build_federated_round_segments,
    build_spatial_federated_round,
    mesh_fedavg,
    pad_cohort_axis,
    stack_client_data,
)
from fedcrack_tpu.parallel.multihost import (  # noqa: F401
    global_mesh_devices,
    initialize_if_needed,
    is_coordinator,
)
from fedcrack_tpu.parallel.spatial import (  # noqa: F401
    build_spatial_predict,
    build_spatial_train_step,
    halo_exchange,
    make_spatial_mesh,
    spatial_apply,
)
