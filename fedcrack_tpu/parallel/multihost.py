"""Multi-host bring-up: ``jax.distributed`` over ICI/DCN.

The reference's only distribution mechanism is one gRPC server and N client
processes on a LAN (SURVEY.md §5.8) — every byte crosses the DCN through
pickle blobs. On a TPU pod slice the data plane instead spans hosts through
XLA's collectives: each host runs one process, ``jax.distributed.initialize``
wires them into a single logical device set, and the same ``shard_map``
programs in this package (``fedavg_mesh``, ``spatial``) run unchanged with
their ``psum``/``ppermute`` traffic riding ICI within a slice and DCN across
slices. The gRPC control plane remains for cross-trust-boundary federation
(clients that are NOT part of the pod).

Single-process usage (tests, one chip, CPU meshes) needs no initialization —
every helper here degrades to a no-op.

The round builders accept cross-process inputs directly: stage each
process's client shards with ``jax.make_array_from_process_local_data`` over
the global mesh and call ``build_federated_round``'s round_fn unchanged —
``tests/test_multihost.py::test_two_process_federated_round`` runs one
FedAvg round across two OS processes and pins bit-equality of the resulting
global model against the single-process round.
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger("fedcrack.multihost")


def initialize_if_needed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize ``jax.distributed`` when running as one process of a
    multi-host job; no-op otherwise.

    Resolution order (standard JAX bring-up):

    1. explicit arguments;
    2. TPU pod metadata / cluster env (``jax.distributed.initialize()`` with
       no args auto-detects on Cloud TPU and SLURM);
    3. ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``
       environment variables.

    Returns True when distributed mode was (already or newly) initialized.
    """
    # NB: probed WITHOUT jax.process_count() — that call initializes the XLA
    # backend, after which jax.distributed.initialize() unconditionally
    # raises ("must be called before any JAX calls").
    from fedcrack_tpu.jaxcompat import is_distributed_initialized

    if is_distributed_initialized():
        return True
    env_addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None and env_addr:
        coordinator_address = env_addr
        num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "0"))
        process_id = (
            process_id
            if process_id is not None
            else int(os.environ.get("JAX_PROCESS_ID", "-1"))
        )
    if coordinator_address is None:
        # Auto-detection path: on a TPU pod slice initialize() discovers the
        # topology itself; off-pod it raises, which we treat as single-host.
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError):
            return False
        return jax.process_count() > 1
    if not num_processes or process_id is None or process_id < 0:
        raise ValueError(
            "multi-host bring-up needs coordinator_address, num_processes and "
            f"process_id together (got {coordinator_address=}, "
            f"{num_processes=}, {process_id=})"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "jax.distributed up: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )
    return True


def global_mesh_devices() -> list[jax.Device]:
    """All devices across all processes, in (process, local) order — the
    device list to hand to ``make_mesh``/``make_spatial_mesh`` so mesh rows
    align with hosts (collectives between row-neighbors stay on-host or
    one ICI hop where possible)."""
    return sorted(jax.devices(), key=lambda d: (d.process_index, d.id))


def is_coordinator() -> bool:
    """True on the process that should run the gRPC control plane and write
    checkpoints (process 0 by convention)."""
    return jax.process_index() == 0
