"""A federated round as ONE compiled XLA program.

The reference runs a round as N processes x (3880 Python-driven Keras steps)
followed by a server-side numpy loop over pickled weight lists
(reference: client_fit_model.py:166, fl_server.py:92-105). Here the entire
round is a single ``shard_map`` over ``Mesh(('clients', 'batch'))``:

- each client's local fit is a ``lax.scan`` over its batches (epochs as an
  outer scan) — no Python in the loop, one compilation for all rounds;
- gradients ``lax.pmean`` over the ``batch`` axis (intra-client DP);
- FedAvg is a **masked, sample-weighted ``lax.psum`` over the ``clients``
  axis**: dropped-out clients carry ``active=0`` and the divisor is
  ``psum(active * n_samples)``, so a shrunken cohort needs no recompilation
  (SURVEY.md §7 "masked/variable cohort psum").

BatchNorm moving statistics are carried per client and averaged with the
kernels, matching the reference's implicit behavior (``get_weights()``
includes BN moments — SURVEY.md §7 "hard parts"). BatchNorm is
**sync-BN over the ``batch`` axis** (flax ``axis_name``), so the round is
invariant to how a client's batch is split across its DP shards — the
(clients=C, batch=B) mesh trains exactly like (clients=C, batch=1).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedcrack_tpu.compress.codecs import encoded_bytes_model
from fedcrack_tpu.compress.mesh import (
    int8_roundtrip,
    topk_roundtrip,
    validate_mesh_codec,
)
from fedcrack_tpu.configs import ModelConfig
from fedcrack_tpu.data.pipeline import as_model_batch
from fedcrack_tpu.fed.algorithms import fedprox_penalty
from fedcrack_tpu.jaxcompat import pcast_varying, psum_if_no_auto, shard_map
from fedcrack_tpu.models import ResUNet
from fedcrack_tpu.ops.losses import iou_from_counts
from fedcrack_tpu.ops.pallas_bce import fused_segmentation_metrics
from fedcrack_tpu.train.local import make_optimizer

CLIENTS, BATCH = "clients", "batch"

# The ordered cohort fold moved to fed/aggregation.py (round 21) — the one
# module owning "how updates combine" owns the mesh instance too. Aliased
# under the historical names so every traced program here is the identical
# expression tree (the r13 groups_bitwise_equal contract is unchanged);
# ``axis_name`` defaults to "clients" == CLIENTS.
from fedcrack_tpu.fed.aggregation import (  # noqa: E402
    mesh_finish_cohort_mean as _finish_cohort_mean,
    mesh_ordered_fold as _ordered_cohort_sums,
    mesh_zero_sums as _zero_sums_like,
)


def _host_view(x) -> np.ndarray | None:
    """Host-fetchable float32 view of a cohort mask/weight vector, or None
    when ``x`` is a cross-process sharded jax.Array whose global value this
    process cannot fetch (multi-host jobs — the in-mesh empty-cohort guard
    covers that case)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        return None
    return np.asarray(x, np.float32)


def _epoch_runner(
    tx, apply_fn, inner_axis, n_inner, anchor, mu_arr, pw_arr,
    weight_transform=None, dp=None,
):
    """The per-client local-fit core, shared OP FOR OP by the monolithic
    round (``_build_round``) and the epoch-segmented variant
    (``_build_round_segments``): returns ``run_epochs(carry, chunks,
    n_epochs)`` scanning ``sgd_step`` over each step-axis data chunk in
    order (carry threaded across chunks) inside an outer epoch scan.

    Sharing this closure is what makes "segmented == monolithic, byte for
    byte" hold by construction rather than by parallel maintenance: a
    single-chunk call is exactly the historical monolithic epoch body, and
    splitting one scan into consecutive scans with the carry threaded
    through is the identical step sequence (test-pinned).

    ``weight_transform`` (round 20, the lowp twin): an optional traceable
    map applied to the params INSIDE the loss — the forward computes with
    ``weight_transform(params)`` (e.g. the straight-through int8 fake-quant
    of ``kernels.dequant.fake_quant_params``) while the optimizer, FedProx
    anchor and FedAvg all keep operating on the float32 master weights.
    ``None`` leaves the traced program byte-identical to a pre-r20 build
    (the conditional is Python-level — the codec-twin discipline).

    ``dp`` (round 23, the DP-SGD twin — fedcrack_tpu/privacy/dpsgd.py):
    ``None`` leaves the program untouched (the same Python-level-
    conditional discipline, test-pinned); otherwise a dict ``{"clip",
    "sigma", "seed", "round_seed", "client_index"}`` turns on per-step
    gradient clipping + seeded Gaussian noise right after the grads/
    n_inner divide. The noise key chain is (dp_seed, round_seed, client,
    step) — the round seed is the replicated per-dispatch scalar the int8
    codec already threads (restored on chaos replay via ``codec_state``),
    the step counter rides the scan carry (dp-on only).
    """
    if dp is not None:
        from fedcrack_tpu.privacy.dpsgd import dp_grad_transform, dp_step_key

    def sgd_step(carry, batch):
        if dp is None:
            params, batch_stats, opt_state = carry
        else:
            params, batch_stats, opt_state, dp_step = carry
        # Accept uint8 transport bytes (1/4 the staging traffic); the
        # on-device normalization reproduces float32 staging values
        # bit for bit (data.pipeline.as_model_batch).
        imgs, msks = as_model_batch(*batch)

        def loss_fn(p):
            p_eff = p if weight_transform is None else weight_transform(p)
            logits, new_stats = apply_fn(p_eff, batch_stats, imgs)
            # One fused pass for BCE + all statistics (Pallas kernel on
            # TPU, XLA reference elsewhere — ops/pallas_bce.py).
            m = fused_segmentation_metrics(logits, msks, pos_weight=pw_arr)
            prox = fedprox_penalty(p, anchor, mu_arr)
            return m["loss"] + prox, (m, new_stats)

        (loss, (m, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        # `params` is unvarying over the inner axis, so shard_map's AD
        # already psums the per-shard cotangents; dividing by the shard
        # count turns that sum of local-mean gradients into the gradient
        # of the client's full mean loss (a pmean here would be an
        # identity on the already-summed value and double-count).
        # Pre-vma JAX performs NO such AD psum — jaxcompat inserts the
        # equivalent explicit one there (identity on current JAX).
        # CAUTION: that AD-inserted psum spans ONLY the inner axis — not
        # the clients axis — solely because the lax.scan carry makes
        # params clients-VARYING after step one (carry-vma unification
        # promotes the whole carry; in the segmented variant the carry
        # arrives already clients-sharded, the same varying state). For
        # fully replicated params the AD psum spans ALL mesh axes
        # (spatial.py's scan-free step divides by the product of both
        # axis sizes for exactly that reason). If this round is ever
        # restructured without the scan, the divisor must change;
        # test_dp_gradient_not_double_counted pins the current behavior.
        grads = psum_if_no_auto(grads, (inner_axis,))
        grads = jax.tree_util.tree_map(lambda g: g / n_inner, grads)
        if dp is not None:
            # DP-SGD (Abadi et al. 2016): clip the client's mean gradient
            # to L2 norm C, then add N(0, (sigma*C)^2) noise keyed per
            # (client, round, step, leaf) — replay-identical by seed chain.
            key = dp_step_key(
                dp["seed"], dp["round_seed"], dp["client_index"], dp_step
            )
            grads = dp_grad_transform(grads, key, dp["clip"], dp["sigma"])
        # BN moments are already pmean-synced inside the forward; this
        # keeps the carried stats bitwise identical across inner shards.
        new_stats = lax.pmean(new_stats, inner_axis)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        metrics = {
            "loss": lax.pmean(loss, inner_axis),
            "pixel_acc": lax.pmean(m["pixel_acc"], inner_axis),
            "iou_inter": lax.psum(m["iou_inter"], inner_axis),
            "iou_union": lax.psum(m["iou_union"], inner_axis),
        }
        if dp is None:
            return (new_params, new_stats, new_opt_state), metrics
        return (new_params, new_stats, new_opt_state, dp_step + 1), metrics

    def epoch_reductions(step_metrics):
        return {
            "loss": jnp.mean(step_metrics["loss"]),
            "pixel_acc": jnp.mean(step_metrics["pixel_acc"]),
            "iou_inter": jnp.sum(step_metrics["iou_inter"]),
            "iou_union": jnp.sum(step_metrics["iou_union"]),
        }

    def run_epochs(carry, chunks, n_epochs, idx=None):
        if idx is not None:
            # Resident (gather-assembly) mode: `chunks` is the single
            # ``(pool_images, pool_masks)`` device-resident pool, `idx` the
            # ``[epochs, steps, B]`` int32 gather plan. Each step jnp.takes
            # its batch from the pool — pure data movement, so the gathered
            # batch is byte-identical to the host-assembled slab batch the
            # streamed path stages (pool[idx] on host == take(pool, idx) on
            # device) — then runs the SAME sgd_step closure. The epoch scan
            # consumes one idx row per epoch (epoch-constant rows reproduce
            # the streamed round's reuse-one-slab-per-epoch semantics).
            if len(chunks) != 1:
                raise ValueError("resident mode takes exactly one pool chunk")
            if idx.shape[0] != n_epochs:
                raise ValueError(
                    f"idx carries {idx.shape[0]} epochs, round runs {n_epochs}"
                )
            pool_imgs, pool_msks = chunks[0]

            def gather_epoch(carry, epoch_idx):
                def gather_step(c, step_idx):
                    batch = (
                        jnp.take(pool_imgs, step_idx, axis=0),
                        jnp.take(pool_msks, step_idx, axis=0),
                    )
                    return sgd_step(c, batch)

                carry, step_metrics = lax.scan(gather_step, carry, epoch_idx)
                return carry, epoch_reductions(step_metrics)

            return lax.scan(gather_epoch, carry, idx)

        def epoch_body(carry, _):
            parts = []
            for imgs, msks in chunks:
                carry, part = lax.scan(sgd_step, carry, (imgs, msks))
                parts.append(part)
            # Single-chunk (monolithic) keeps the historical graph exactly;
            # multi-chunk concatenates the stacked per-step metrics back
            # into one [steps] axis so the epoch reductions below see the
            # same array a monolithic scan would have produced.
            step_metrics = (
                parts[0]
                if len(parts) == 1
                else jax.tree_util.tree_map(
                    lambda *xs: jnp.concatenate(xs), *parts
                )
            )
            return carry, epoch_reductions(step_metrics)

        return lax.scan(epoch_body, carry, None, length=n_epochs)

    return run_epochs


def _aggregate_and_guard(
    params, batch_stats, fallback_params, fallback_stats, active_i, n_i
):
    """Masked sample-weighted FedAvg over the clients axis, with the in-mesh
    empty-cohort guard: when every client dropped out return the round's
    incoming global model unchanged instead of an all-zero mean. Shared by
    the monolithic round's tail and the segmented variant's finalize program
    (same ops, same order). Round 13: the reduction is the ORDERED client
    fold (``_ordered_cohort_sums``), not a psum, so a time-multiplexed
    cohort accumulating group partials reproduces this tail bitwise."""
    w = active_i * n_i
    update = {"params": params, "batch_stats": batch_stats}
    num, total_w = _ordered_cohort_sums(update, w, _zero_sums_like(update))
    return _finish_cohort_mean(
        num, total_w, {"params": fallback_params, "batch_stats": fallback_stats}
    )


def _require_axes(mesh: Mesh, *axes: str) -> None:
    missing = [a for a in axes if a not in mesh.shape]
    if missing:
        raise ValueError(
            f"mesh has axes {mesh.axis_names}, but this round builder needs "
            f"{axes} (missing {missing})"
        )


def _tree_sub(a, b):
    return jax.tree_util.tree_map(
        lambda x, y: x.astype(jnp.float32) - y.astype(jnp.float32), a, b
    )


def _tree_add_cast(base, delta):
    return jax.tree_util.tree_map(
        lambda b, d: (b.astype(jnp.float32) + d.astype(jnp.float32)).astype(b.dtype),
        base,
        delta,
    )


def _build_round(
    mesh: Mesh,
    model_config: ModelConfig,
    learning_rate: float,
    local_epochs: int,
    fedprox_mu: float,
    *,
    inner_axis: str,
    apply_fn,
    image_spec: P,
    validate_data,
    pos_weight: float = 1.0,
    remat: bool = False,
    data_placement: str = "streamed",
    update_codec: str | None = None,
    topk_fraction: float = 0.01,
    lowp: str | None = None,
    dp_clip_norm: float = 0.0,
    dp_noise_multiplier: float = 0.0,
    dp_seed: int = 0,
):
    """Shared core of the one-program federated round.

    Both public builders are this skeleton with a different intra-client
    sharding: ``apply_fn(params, batch_stats, images) -> (logits,
    new_batch_stats)`` is the train-mode forward (plain sync-BN-over-batch
    model, or the halo-exchange spatial forward), ``inner_axis`` is the mesh
    axis the client's work is split over (``batch`` or ``space``), and
    ``image_spec`` shards the data accordingly.

    ``data_placement="resident"`` (plain rounds only) swaps the data
    contract from staged epoch slabs to a device-resident sample pool plus
    a per-round gather plan: ``round_fn(variables, (pool_images,
    pool_masks), idx, active, n_samples)`` where the pool pair is
    ``[C, N, ...]`` sharded ``P('clients')`` and ``idx`` is
    ``[C, epochs, steps, B]`` int32 with the per-step batch ``B`` split
    over the inner axis. Each step gathers its batch from the pool on
    device and runs the identical sgd_step closure, so the round is
    byte-identical to the streamed round over ``pool[idx]`` (test-pinned).

    ``remat=True`` wraps the forward in ``jax.checkpoint``: the backward
    pass recomputes activations instead of keeping the whole U-Net's
    feature maps live through the scan — the standard HBM/FLOPs trade for
    crops or per-chip batches that don't otherwise fit (~1/2 the
    activation footprint for ~1/3 more forward FLOPs).
    """
    tx = make_optimizer(learning_rate)
    mu = float(fedprox_mu)
    pw = float(pos_weight)
    if remat:
        # prevent_cse=False is documented-safe (and faster) when the
        # checkpointed function is differentiated inside lax.scan — which is
        # the only place apply_fn is ever differentiated here (sgd_step).
        apply_fn = jax.checkpoint(apply_fn, prevent_cse=False)
    n_client_shards = mesh.shape[CLIENTS]
    n_inner = mesh.shape[inner_axis]
    resident = data_placement == "resident"
    if data_placement not in ("streamed", "resident"):
        raise ValueError(
            f"data_placement must be 'streamed' or 'resident', got {data_placement!r}"
        )
    # On-device update-compression twin (round 12, compress/mesh.py): apply
    # the codec's encode∘decode value map to each client's round delta
    # BEFORE the FedAvg psum, so the mesh trajectory reflects exactly what
    # the gRPC plane's compressed uploads would aggregate to — at zero host
    # cost. "null" leaves the traced program UNTOUCHED (the conditionals
    # below are Python-level, so the null build is byte-identical to a
    # pre-codec build — test-pinned).
    codec = validate_mesh_codec(update_codec)
    if not 0.0 < topk_fraction <= 1.0:
        raise ValueError(f"topk_fraction must be in (0, 1], got {topk_fraction}")
    topk = codec == "topk_delta"
    # Low-precision training twin (round 20, kernels/dequant.py): the local
    # fit's forward computes with straight-through int8 fake-quant weights —
    # the same quantize/dequant math the fused serve plane loads — while the
    # optimizer and FedAvg keep the float32 masters. Same null-build
    # discipline as the codec: None/"null" leaves the traced program
    # byte-identical to a pre-r20 build (Python-level conditional,
    # test-pinned); monolithic-only, like the codec twin.
    if lowp in (None, "null"):
        lowp = "null"
        weight_transform = None
    elif lowp == "fake_quant_int8":
        from fedcrack_tpu.kernels.dequant import fake_quant_params

        weight_transform = fake_quant_params
    else:
        raise ValueError(
            f"lowp must be None, 'null' or 'fake_quant_int8', got {lowp!r}"
        )
    # DP-SGD twin (round 23, privacy/dpsgd.py): per-step clip + seeded
    # Gaussian noise inside sgd_step. Same null-build discipline as the
    # codec and lowp twins — dp off (clip_norm == 0) leaves the traced
    # program byte-identical (test-pinned); monolithic-only.
    if dp_clip_norm < 0.0:
        raise ValueError(f"dp_clip_norm must be >= 0, got {dp_clip_norm}")
    if dp_noise_multiplier < 0.0:
        raise ValueError(
            f"dp_noise_multiplier must be >= 0, got {dp_noise_multiplier}"
        )
    dp_on = dp_clip_norm > 0.0
    if dp_noise_multiplier > 0.0 and not dp_on:
        raise ValueError(
            "dp_noise_multiplier > 0 requires dp_clip_norm > 0 (noise is "
            "calibrated to the clip norm)"
        )
    # The replicated per-dispatch seed scalar feeds int8's stochastic
    # rounding AND the DP noise chain; either consumer pulls it in.
    needs_seed = codec == "int8" or dp_on
    # Normalised at build time: these are static Python config scalars and
    # must stay host casts OUTSIDE the shard_map'd body (TRACE001).
    dp_clip_f = float(dp_clip_norm)
    dp_sigma_f = float(dp_noise_multiplier)
    dp_seed_i = int(dp_seed)

    # `extras` is the side channel: the P('clients')-sharded error-feedback
    # pytree for topk_delta (first), then the replicated per-call seed
    # scalar (int8 stochastic rounding / DP round seed), absent for null.
    def client_fit(variables, data_a, data_b, active, n_samples, *extras):
        # Per-shard blocks: leading clients-axis block is exactly one client.
        # Streamed: data_a/data_b are the [C, steps, B, ...] epoch slabs.
        # Resident: data_a is the (pool_images, pool_masks) pair, data_b the
        # [C, epochs, steps, B] gather plan.
        if resident:
            chunk = (data_a[0][0], data_a[1][0])
            idx = data_b[0]
        else:
            chunk = (data_a[0], data_b[0])
            idx = None
        active_i, n_i = active[0], n_samples[0]
        ei = 0
        ef_extra = None
        if topk:
            ef_extra = extras[ei]
            ei += 1
        seed_in = extras[ei] if needs_seed else None
        params = variables["params"]
        batch_stats = variables["batch_stats"]
        anchor = params  # FedProx anchor = this round's global weights
        opt_state = tx.init(params)
        mu_arr = jnp.asarray(mu, jnp.float32)
        pw_arr = jnp.asarray(pw, jnp.float32)

        dp = None
        if dp_on:
            dp = {
                "clip": dp_clip_f,
                "sigma": dp_sigma_f,
                "seed": dp_seed_i,
                "round_seed": seed_in,
                "client_index": lax.axis_index(CLIENTS),
            }
        run_epochs = _epoch_runner(
            tx, apply_fn, inner_axis, n_inner, anchor, mu_arr, pw_arr,
            weight_transform=weight_transform, dp=dp,
        )
        # The carry becomes client-varying after the first data-dependent
        # update; promote the (replicated) initial carry so scan's carry type
        # is stable under shard_map's varying-axes tracking. The dp-on carry
        # also threads the per-step noise counter (Python-level: absent from
        # the dp-off program).
        carry0 = (params, batch_stats, opt_state)
        if dp_on:
            carry0 = carry0 + (jnp.uint32(0),)
        carry = jax.tree_util.tree_map(
            lambda x: pcast_varying(x, (CLIENTS,)), carry0
        )
        carry, per_epoch = run_epochs(
            carry, [chunk], max(1, local_epochs), idx=idx
        )
        params, batch_stats = carry[0], carry[1]

        ef_out = None
        if codec == "int8":
            update = {"params": params, "batch_stats": batch_stats}
            base = {"params": anchor, "batch_stats": variables["batch_stats"]}
            # Per-client stochastic-rounding stream: the replicated per-call
            # seed folded with this shard's client index.
            key = jax.random.fold_in(
                jax.random.PRNGKey(seed_in), lax.axis_index(CLIENTS)
            )
            update = _tree_add_cast(
                base, int8_roundtrip(_tree_sub(update, base), key)
            )
            params, batch_stats = update["params"], update["batch_stats"]
        elif topk:
            update = {"params": params, "batch_stats": batch_stats}
            base = {"params": anchor, "batch_stats": variables["batch_stats"]}
            ef_block = jax.tree_util.tree_map(lambda x: x[0], ef_extra)
            kept, ef_new = topk_roundtrip(
                _tree_sub(update, base), ef_block, topk_fraction
            )
            update = _tree_add_cast(base, kept)
            params, batch_stats = update["params"], update["batch_stats"]
            # EF advances only for ACTIVE clients: on the wire an inactive
            # client never encodes, so its residual is untouched — without
            # this gate the twin would bank residual mass from a delta the
            # round's active-mask discards and leak it into the client's
            # next active round, diverging from the host-codec semantics.
            is_active = active[0] > 0.0
            ef_new = jax.tree_util.tree_map(
                lambda new, old: jnp.where(is_active, new, old), ef_new, ef_block
            )
            ef_out = jax.tree_util.tree_map(lambda x: x[None], ef_new)

        new_variables = _aggregate_and_guard(
            params,
            batch_stats,
            anchor,
            variables["batch_stats"],
            active_i,
            n_i,
        )

        last = jax.tree_util.tree_map(lambda a: a[-1], per_epoch)
        metrics = {
            "loss": last["loss"],
            "pixel_acc": last["pixel_acc"],
            "iou": iou_from_counts(last["iou_inter"], last["iou_union"]),
            "active": active_i,
        }
        # [1]-shaped leaves tile back onto the clients axis.
        metrics = jax.tree_util.tree_map(lambda a: a[None], metrics)
        if topk:
            return new_variables, metrics, ef_out
        return new_variables, metrics

    if resident:
        in_specs = (
            P(),
            (P(CLIENTS), P(CLIENTS)),  # pool pair: replicated over inner axis
            _idx_spec(inner_axis),
            P(CLIENTS),
            P(CLIENTS),
        )
    else:
        in_specs = (P(), image_spec, image_spec, P(CLIENTS), P(CLIENTS))
    # Side-channel specs, in the extras order client_fit unpacks: the
    # error-feedback accumulator rides through the program as a
    # P('clients')-sharded pytree (in as this round's residual, out as the
    # next round's — it never leaves device); one replicated uint32 seed
    # per call feeds int8's stochastic rounding and/or the DP noise chain.
    extra_specs: tuple = ()
    if topk:
        extra_specs += (P(CLIENTS),)
    if needs_seed:
        extra_specs += (P(),)
    sharded = shard_map(
        client_fit,
        mesh=mesh,
        in_specs=in_specs + extra_specs,
        out_specs=(P(), P(CLIENTS), P(CLIENTS)) if topk else (P(), P(CLIENTS)),
    )
    jitted = jax.jit(sharded)

    def _wire_bytes_per_client(variables) -> int:
        """Analytic wire bytes ONE client's upload would cost under this
        codec (compress.codecs.encoded_bytes_model) — the mesh plane never
        materializes host bytes, so the counter is a model, not a measure."""
        sizes = [
            int(leaf.size)
            for leaf in jax.tree_util.tree_leaves(
                {
                    "params": variables["params"],
                    "batch_stats": variables["batch_stats"],
                }
            )
        ]
        return encoded_bytes_model(sizes, codec, topk_fraction=topk_fraction)

    def _init_ef(variables):
        """Round-0 error-feedback state: per-client float32 zeros for every
        update leaf, placed sharded P('clients') — C model-sized copies of
        HBM, the price of faithful per-client DGC on the mesh."""
        zeros = jax.tree_util.tree_map(
            lambda t: np.zeros((n_client_shards,) + tuple(np.shape(t)), np.float32),
            {"params": variables["params"], "batch_stats": variables["batch_stats"]},
        )
        return jax.device_put(zeros, NamedSharding(mesh, P(CLIENTS)))

    ef_state: dict = {"ef": None, "calls": 0}

    def _dispatch(variables, *data_args):
        """Shared jitted-call tail: lazily prices the wire-bytes counter
        from the first call's leaf sizes and threads the codec side
        channel — the device-resident error-feedback state for the topk
        twin, or the call-counter seed for int8's stochastic rounding.
        Both commit as soon as the async dispatch returns — BEFORE a
        non-finite output can surface at the host fetch — so a replaying
        driver must restore ``codec_state()`` alongside its weights
        snapshot (parallel.driver does; the null twin carries no state)."""
        if round_fn.wire_bytes_per_client is None:
            round_fn.wire_bytes_per_client = _wire_bytes_per_client(variables)
        extras = []
        if topk:
            if ef_state["ef"] is None:
                ef_state["ef"] = _init_ef(variables)
            extras.append(ef_state["ef"])
        if needs_seed:
            extras.append(jnp.uint32(ef_state["calls"]))
        out = jitted(variables, *data_args, *extras)
        if needs_seed:
            ef_state["calls"] += 1
        if topk:
            new_vars, metrics, ef_new = out
            ef_state["ef"] = ef_new
            return new_vars, metrics
        return out

    if resident:

        def round_fn(variables, pool, idx, active, n_samples):
            _check_resident_inputs(
                pool, idx, n_client_shards, max(1, local_epochs),
                n_inner, validate_data,
            )
            active, n_samples = _host_cohort_check(active, n_samples)
            return _dispatch(variables, tuple(pool), idx, active, n_samples)

    else:

        def round_fn(variables, images, masks, active, n_samples):
            if images.shape[0] != n_client_shards:
                raise ValueError(
                    f"data carries {images.shape[0]} clients, mesh has "
                    f"{n_client_shards} on the '{CLIENTS}' axis"
                )
            validate_data(images)

            # Same contract as fed.algorithms.fedavg: an empty effective
            # cohort is an error, never a silently-zeroed global model. In a
            # multi-host job the mask arrives as a cross-process sharded
            # jax.Array whose global value THIS process cannot fetch — the
            # check then happens in-mesh instead (all-dropout returns the
            # incoming global model unchanged; see the `keep` guard in
            # client_fit).
            active, n_samples = _host_cohort_check(active, n_samples)
            return _dispatch(variables, images, masks, active, n_samples)

    # Drivers key on this tag to refuse a round/data-contract mismatch
    # before any bytes move (parallel.driver.run_mesh_federation).
    round_fn.data_placement = data_placement
    # Compressed-transport observability (round 12): which codec twin this
    # round simulates, the analytic per-client upload bytes under it
    # (priced on first call; parallel.driver folds it into
    # RoundRecord.bytes_per_round), and — for the topk twin — a reset hook
    # dropping the cross-round error-feedback state.
    round_fn.update_codec = codec
    # Which low-precision training twin this round runs ("null" = the exact
    # pre-r20 program).
    round_fn.lowp = lowp
    # Which DP twin this round runs ("null" = the exact pre-r23 program;
    # "dpsgd" = per-step clip + seeded noise in sgd_step). The seed counter
    # DP keys its rounds on is the codec_state "calls" field — replay
    # restores it with the rest of the codec state.
    round_fn.dp = "dpsgd" if dp_on else "null"
    round_fn.wire_bytes_per_client = None
    round_fn.reset_ef = lambda: ef_state.update(ef=None, calls=0)
    # Test hook: the device-resident EF pytree ([C, ...] per leaf), None
    # before the first topk dispatch. Read-only observability.
    round_fn.ef_state = lambda: ef_state["ef"]
    # Retry contract (r12 review fix): a failed round attempt surfaces
    # AFTER the async dispatch already committed this state (JAX defers
    # the non-finite discovery to the host fetch), so the driver's
    # replay path snapshots it alongside its weights snapshot and
    # restores it before the retry — otherwise the topk twin banks
    # residual mass from a round that was never applied (kept mass lost,
    # dropped mass double-counted) and the int8 seed counter drifts.
    # Shallow dict copy is a true snapshot: "ef" holds immutable jax
    # arrays (pointer copy suffices), "calls" an int. Restoring makes
    # the replayed attempt BIT-identical for every codec twin.
    round_fn.codec_state = lambda: dict(ef_state)
    round_fn.set_codec_state = lambda s: (
        ef_state.clear(), ef_state.update(s)
    )
    return round_fn


def _idx_spec(inner_axis: str) -> P:
    """Sharding of the ``[C, epochs, steps, B]`` gather plan: clients on the
    leading axis, the per-step batch split over the inner axis — the same
    per-shard batch the streamed ``P(clients, None, batch)`` slab delivers."""
    return P(CLIENTS, None, None, inner_axis)


def _check_resident_inputs(
    pool, idx, n_client_shards, epochs, n_inner, validate_data
) -> None:
    """Host-side validation of the resident round's data contract."""
    pool_imgs, pool_msks = pool
    if pool_imgs.shape[0] != n_client_shards:
        raise ValueError(
            f"pool carries {pool_imgs.shape[0]} clients, mesh has "
            f"{n_client_shards} on the '{CLIENTS}' axis"
        )
    if pool_imgs.shape[:2] != pool_msks.shape[:2]:
        raise ValueError(
            f"pool images/masks disagree on [C, N]: {pool_imgs.shape[:2]} "
            f"vs {pool_msks.shape[:2]}"
        )
    validate_data(pool_imgs)
    if idx.ndim != 4 or idx.shape[0] != n_client_shards:
        raise ValueError(
            f"idx must be [C={n_client_shards}, epochs, steps, B]; got "
            f"{tuple(idx.shape)}"
        )
    if idx.shape[1] != epochs:
        raise ValueError(
            f"idx carries {idx.shape[1]} epochs, the round runs {epochs}"
        )
    if idx.shape[-1] % n_inner:
        raise ValueError(
            f"per-step batch {idx.shape[-1]} does not divide over the "
            f"{n_inner}-way inner axis"
        )
    # Bounds-check the plan against the pool NOW: jnp.take's in-jit clip
    # mode would silently clamp an out-of-range index to a valid sample —
    # training on wrong data where the streamed fallback's numpy gather
    # raises — and a negative index would clamp to 0 where numpy wraps.
    # Either way the streamed==resident byte-identity contract breaks
    # silently; one host-side reduction over the KB-scale plan closes it.
    if isinstance(idx, jax.Array) and not idx.is_fully_addressable:
        return  # cross-process plan: this process cannot fetch it to check
    n_pool = pool_imgs.shape[1]
    lo, hi = int(np.min(idx)), int(np.max(idx))
    if lo < 0 or hi >= n_pool:
        raise ValueError(
            f"gather plan indexes [{lo}, {hi}] outside the {n_pool}-sample "
            "pool (jnp.take would silently clamp)"
        )


def _host_cohort_check(active, n_samples):
    """Raise on an all-dropped cohort where the mask is host-visible; return
    host float32 views when fetchable (multi-host sharded masks pass through
    untouched — the in-mesh ``keep`` guard covers them)."""
    active_h, n_samples_h = _host_view(active), _host_view(n_samples)
    if active_h is not None and n_samples_h is not None:
        if float(np.sum(active_h * n_samples_h)) <= 0.0:
            raise ValueError(
                "non-positive total FedAvg weight: every client dropped "
                f"out (active={active_h.tolist()}, "
                f"n_samples={n_samples_h.tolist()})"
            )
        return active_h, n_samples_h
    return active, n_samples


def _plain_apply_and_validate(model_config: ModelConfig):
    """The plain (sync-BN-over-batch) forward + staging-layout validator,
    shared by the monolithic and segmented round builders."""
    model = ResUNet(config=model_config, bn_axis_name=BATCH)
    in_ch = model_config.in_channels
    packed_ok = model_config.stem_layout != "reference"

    def validate_channels(images) -> None:
        ch = images.shape[-1]
        allowed = (in_ch, 4 * in_ch) if packed_ok else (in_ch,)
        if ch not in allowed:
            raise ValueError(
                f"images carry {ch} channels; stem_layout="
                f"{model_config.stem_layout!r} accepts {allowed} "
                "(4x = space_to_depth-packed staging)"
            )

    def apply_fn(params, batch_stats, imgs):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            imgs,
            train=True,
            mutable=["batch_stats"],
        )
        return logits, mutated["batch_stats"]

    return apply_fn, validate_channels


def build_federated_round(
    mesh: Mesh,
    model_config: ModelConfig | None = None,
    learning_rate: float = 1e-3,
    local_epochs: int = 1,
    fedprox_mu: float = 0.0,
    pos_weight: float = 1.0,
    remat: bool = False,
    data_placement: str = "streamed",
    update_codec: str | None = None,
    topk_fraction: float = 0.01,
    lowp: str | None = None,
    dp_clip_norm: float = 0.0,
    dp_noise_multiplier: float = 0.0,
    dp_seed: int = 0,
):
    """Compile-once round function over ``Mesh(('clients', 'batch'))``.

    Returns ``round_fn(variables, images, masks, active, n_samples)``:

    - ``variables``: the global ``{'params', 'batch_stats'}`` pytree
      (replicated over the mesh);
    - ``images``  float32 ``[C, steps, B, H, W, 3]``,
      ``masks``   float32 ``[C, steps, B, H, W, 1]`` — per-client local data,
      ``C == mesh.shape['clients']``; the per-step batch ``B`` is split over
      the ``batch`` axis (must divide evenly);
    - ``active``  float32 ``[C]`` participation mask (1 = reported, 0 =
      dropped out mid-round);
    - ``n_samples`` float32 ``[C]`` per-client sample counts (FedAvg
      weights).

    Returns ``(new_variables, per_client_metrics)`` where metrics leaves are
    ``[C]`` arrays from each client's final local epoch. Adam state is fresh
    each round (the reference rebuilds its model per round,
    client_fit_model.py:155-157; here only the optimizer moments reset).

    Transformed layouts: when ``model_config.stem_layout`` is a
    space-to-depth variant, ``images`` may instead arrive PRE-PACKED as
    ``[C, steps, B, H/2, W/2, 4*ch]`` (``data.pipeline.space_to_depth_images``
    — same bytes, packed on the host instead of on device); the round
    program consumes either staging layout (pick one per federation — the
    two compile to different programs). Masks stay full-resolution always.

    ``data_placement="resident"`` switches to the gather-assembly data
    contract (round 9): ``round_fn(variables, (pool_images, pool_masks),
    idx, active, n_samples)`` over a device-resident
    ``data.pipeline.SamplePool`` placement and a ``[C, epochs, steps, B]``
    int32 gather plan — byte-identical to this streamed round over
    ``pool[idx]`` (test-pinned), at kilobytes of per-round staging instead
    of the full epoch slab.

    ``update_codec`` (round 12): ``None``/``"null"`` leaves the program
    untouched (byte-identical to a pre-codec build, test-pinned);
    ``"int8"``/``"topk_delta"`` apply the on-device encode∘decode twin of
    the wire codec to each client's round delta before the FedAvg psum
    (``compress.mesh``), so ``run_mesh_federation`` A/Bs compressed-
    trajectory quality at zero host cost. The topk twin carries its
    per-client error-feedback accumulator device-resident across calls
    (``round_fn.reset_ef()`` drops it); the returned ``round_fn`` also
    tags ``update_codec`` and prices ``wire_bytes_per_client`` on first
    call for the driver's ``bytes_per_round`` counter. The codec twin is
    monolithic-only — ``build_federated_round_segments`` has no codec arg.

    ``lowp`` (round 20): ``None``/``"null"`` leaves the program untouched
    (byte-identical build, same discipline as the codec); ``"fake_quant_int8"``
    runs every local-fit forward with straight-through int8 fake-quant
    weights (``kernels.dequant.fake_quant_params`` — the quantize/dequant
    math the fused serve plane loads), optimizer/anchor/FedAvg staying on
    the float32 masters. Trajectory pinned within the r12 int8-mesh-twin
    IoU tolerance vs the reference round (tests/test_kernels.py).
    Monolithic-only, like the codec twin.

    ``dp_clip_norm``/``dp_noise_multiplier``/``dp_seed`` (round 23, the
    DP-SGD twin — ``fedcrack_tpu/privacy/dpsgd.py``): ``dp_clip_norm=0``
    leaves the program untouched (byte-identical build, test-pinned, same
    discipline as the codec twin); ``> 0`` clips each client's per-step
    mean gradient to that L2 norm inside ``sgd_step`` and (when
    ``dp_noise_multiplier > 0``) adds ``N(0, (multiplier*clip)^2)`` noise
    keyed per (dp_seed, round, client, step, leaf). The round axis of the
    key chain is the same replicated per-dispatch seed scalar the int8
    codec threads, restored on driver replay via ``codec_state()`` — a
    chaos-retried round reproduces bit-identical noise (test-pinned).
    Monolithic-only, like the codec and lowp twins.
    """
    model_config = model_config or ModelConfig()
    _require_axes(mesh, CLIENTS, BATCH)
    apply_fn, validate_channels = _plain_apply_and_validate(model_config)
    return _build_round(
        mesh,
        model_config,
        learning_rate,
        local_epochs,
        fedprox_mu,
        inner_axis=BATCH,
        apply_fn=apply_fn,
        image_spec=P(CLIENTS, None, BATCH),
        validate_data=validate_channels,
        pos_weight=pos_weight,
        remat=remat,
        data_placement=data_placement,
        update_codec=update_codec,
        topk_fraction=topk_fraction,
        lowp=lowp,
        dp_clip_norm=dp_clip_norm,
        dp_noise_multiplier=dp_noise_multiplier,
        dp_seed=dp_seed,
    )


def _as_chunks(x) -> tuple:
    """Normalize a round data argument to a tuple of step-axis chunks: a
    single ``[C, steps, B, ...]`` array is one chunk; a tuple/list of such
    arrays is consumed as consecutive step ranges (their concatenation
    along axis 1 is the monolithic layout)."""
    if isinstance(x, (tuple, list)):
        if not x:
            raise ValueError("empty chunk list for round data")
        return tuple(x)
    return (x,)


@dataclasses.dataclass(frozen=True)
class SegmentedRound:
    """An epoch-segmented federated round: K device-resident-carry segment
    programs instead of one monolithic K*epochs-steps scan.

    The monolithic round (``build_federated_round``) compiles the whole
    ``local_epochs x steps`` trajectory plus FedAvg into ONE XLA program —
    great for dispatch overhead, but it forces round-grain staging (the
    full epoch slab must land before any step runs), caps staging/compute
    overlap at round grain, and at 256 px the 3,880-step program is too
    large for some remote-compile paths (VERDICT r5 #6). This variant
    splits the trajectory into ``n_segments`` programs of
    ``segment_epochs`` epochs each; the per-client ``(params, batch_stats,
    opt_state)`` carry stays ON DEVICE between segments as a
    ``P('clients')``-sharded pytree and is DONATED to the next segment
    call, so the split costs K-1 extra dispatches and zero extra HBM.

    Byte-exactness contract (test-pinned): for any K dividing
    ``local_epochs`` — and any step-axis chunking of the data — the final
    global weights AND the returned metrics are bit-identical to the
    monolithic round on the same inputs. The segment body is the SAME
    closure the monolithic round traces (``_epoch_runner``), the carry
    crosses program boundaries as pure data movement, and the finalize
    program runs the same masked-psum FedAvg tail.

    Calling the object is round_fn-compatible
    (``(variables, images, masks, active, n_samples) -> (new_variables,
    metrics)``, with ``images``/``masks`` each either one array or a tuple
    of step-axis chunks); ``parallel.driver.run_mesh_federation`` instead
    drives ``init``/``segment``/``finalize`` itself so next-round staging
    can stream at segment grain between dispatches.
    """

    n_segments: int
    segment_epochs: int
    local_epochs: int
    n_client_shards: int
    init_fn: Callable = dataclasses.field(repr=False)
    segment_fn: Callable = dataclasses.field(repr=False)
    finalize_fn: Callable = dataclasses.field(repr=False)
    validate_data: Callable = dataclasses.field(repr=False)
    # "streamed" (staged epoch-slab chunks) or "resident" (device-resident
    # sample pool + per-segment gather plans — see build_federated_round's
    # data_placement doc); drivers key on this to match the data contract.
    data_placement: str = "streamed"
    n_inner: int = 1

    def check_inputs(self, img_chunks, active, n_samples, idx=None):
        """Host-side validation mirroring the monolithic ``round_fn``;
        returns the (possibly host-viewed) cohort arrays. In resident mode
        ``img_chunks`` is the ``(pool_images, pool_masks)`` pair and ``idx``
        the full-round ``[C, local_epochs, steps, B]`` gather plan."""
        if self.data_placement == "resident":
            _check_resident_inputs(
                img_chunks, idx, self.n_client_shards, self.local_epochs,
                self.n_inner, self.validate_data,
            )
            return _host_cohort_check(active, n_samples)
        for c in img_chunks:
            if c.shape[0] != self.n_client_shards:
                raise ValueError(
                    f"data carries {c.shape[0]} clients, mesh has "
                    f"{self.n_client_shards} on the '{CLIENTS}' axis"
                )
        self.validate_data(img_chunks[0])
        return _host_cohort_check(active, n_samples)

    def init(self, variables):
        """Fresh per-client carry from the round's global variables (Adam
        state zeroed — the reference rebuilds its model per round)."""
        return self.init_fn(variables)

    def segment(self, carry, variables, img_chunks, msk_chunks):
        """Run one segment (``segment_epochs`` epochs over all chunks).
        ``carry`` is DONATED — the caller must thread the returned carry
        and never reuse the argument. Returns ``(carry, raw_last)`` where
        ``raw_last`` is the segment's last-epoch metric counts ([C] each).
        Resident mode: ``img_chunks`` is the pool pair, ``msk_chunks`` the
        segment's ``[C, segment_epochs, steps, B]`` gather-plan slice."""
        if self.data_placement == "resident":
            return self.segment_fn(
                carry, variables, tuple(img_chunks), msk_chunks
            )
        return self.segment_fn(
            carry, variables, _as_chunks(img_chunks), _as_chunks(msk_chunks)
        )

    def finalize(self, carry, variables, active, n_samples, raw_last):
        """Masked FedAvg over the clients axis plus the monolithic round's
        metrics dict from the last segment's counts."""
        # jnp.asarray (not np.asarray): a multi-host cohort mask arrives as
        # a cross-process sharded jax.Array that no single process can
        # fetch to host — the same passthrough contract the monolithic
        # round_fn honors (_host_cohort_check returns it untouched and the
        # in-mesh `keep` guard covers the empty-cohort case).
        active32 = jnp.asarray(active, jnp.float32)
        n32 = jnp.asarray(n_samples, jnp.float32)
        new_variables = self.finalize_fn(carry, variables, active32, n32)
        metrics = {
            "loss": raw_last["loss"],
            "pixel_acc": raw_last["pixel_acc"],
            "iou": iou_from_counts(raw_last["iou_inter"], raw_last["iou_union"]),
            "active": active32,
        }
        return new_variables, metrics

    def __call__(self, variables, images, masks, active, n_samples):
        if self.data_placement == "resident":
            # images = (pool_images, pool_masks), masks = the full-round
            # gather plan [C, local_epochs, steps, B]; each segment consumes
            # its own epochs-axis slice.
            pool, idx = tuple(images), masks
            active, n_samples = self.check_inputs(pool, active, n_samples, idx=idx)
            carry = self.init(variables)
            raw_last = None
            se = self.segment_epochs
            for k in range(self.n_segments):
                carry, raw_last = self.segment(
                    carry, variables, pool, idx[:, k * se : (k + 1) * se]
                )
            return self.finalize(carry, variables, active, n_samples, raw_last)
        img_chunks, msk_chunks = _as_chunks(images), _as_chunks(masks)
        active, n_samples = self.check_inputs(img_chunks, active, n_samples)
        carry = self.init(variables)
        raw_last = None
        for _ in range(self.n_segments):
            carry, raw_last = self.segment(carry, variables, img_chunks, msk_chunks)
        return self.finalize(carry, variables, active, n_samples, raw_last)


def _build_round_segments(
    mesh: Mesh,
    model_config: ModelConfig,
    learning_rate: float,
    local_epochs: int,
    fedprox_mu: float,
    *,
    inner_axis: str,
    apply_fn,
    image_spec: P,
    validate_data,
    pos_weight: float = 1.0,
    remat: bool = False,
    segments: int = 0,
    data_placement: str = "streamed",
) -> SegmentedRound:
    """Segmented twin of ``_build_round`` (same skeleton, same shared
    ``_epoch_runner``/``_aggregate_and_guard`` closures — see
    :class:`SegmentedRound` for the exactness contract)."""
    tx = make_optimizer(learning_rate)
    mu = float(fedprox_mu)
    pw = float(pos_weight)
    if remat:
        apply_fn = jax.checkpoint(apply_fn, prevent_cse=False)
    if data_placement not in ("streamed", "resident"):
        raise ValueError(
            f"data_placement must be 'streamed' or 'resident', got {data_placement!r}"
        )
    resident = data_placement == "resident"
    n_client_shards = mesh.shape[CLIENTS]
    n_inner = mesh.shape[inner_axis]
    epochs = max(1, local_epochs)
    n_segments = epochs if not segments else int(segments)
    if n_segments <= 0 or epochs % n_segments:
        raise ValueError(
            f"segments={segments!r} must be a positive divisor of "
            f"local_epochs={epochs} (epoch-grain segmentation)"
        )
    segment_epochs = epochs // n_segments

    def init_shard(variables):
        params = variables["params"]
        opt_state = tx.init(params)
        # Same promotion as the monolithic round's initial carry: the carry
        # is client-varying from the first data-dependent update on, and
        # here it must leave the program through a P('clients') out_spec.
        carry = jax.tree_util.tree_map(
            lambda x: pcast_varying(x, (CLIENTS,)),
            (params, variables["batch_stats"], opt_state),
        )
        return jax.tree_util.tree_map(lambda x: x[None], carry)

    init_fn = jax.jit(
        shard_map(init_shard, mesh=mesh, in_specs=(P(),), out_specs=P(CLIENTS))
    )

    def segment_shard(carry, variables, img_chunks, msk_chunks):
        # Resident mode: img_chunks is the (pool_images, pool_masks) pair,
        # msk_chunks the segment's [C, segment_epochs, steps, B] gather plan.
        carry = jax.tree_util.tree_map(lambda x: x[0], carry)
        anchor = variables["params"]  # FedProx anchor = round-start globals
        mu_arr = jnp.asarray(mu, jnp.float32)
        pw_arr = jnp.asarray(pw, jnp.float32)
        run_epochs = _epoch_runner(
            tx, apply_fn, inner_axis, n_inner, anchor, mu_arr, pw_arr
        )
        if resident:
            chunks = [(img_chunks[0][0], img_chunks[1][0])]
            idx = msk_chunks[0]
        else:
            chunks = [(i[0], m[0]) for i, m in zip(img_chunks, msk_chunks)]
            idx = None
        carry, per_epoch = run_epochs(carry, chunks, segment_epochs, idx=idx)
        last = jax.tree_util.tree_map(lambda a: a[-1], per_epoch)
        return (
            jax.tree_util.tree_map(lambda x: x[None], carry),
            jax.tree_util.tree_map(lambda a: a[None], last),
        )

    if resident:
        seg_in_specs = (
            P(CLIENTS),
            P(),
            (P(CLIENTS), P(CLIENTS)),
            _idx_spec(inner_axis),
        )
    else:
        seg_in_specs = (P(CLIENTS), P(), image_spec, image_spec)
    segment_fn = jax.jit(
        shard_map(
            segment_shard,
            mesh=mesh,
            in_specs=seg_in_specs,
            out_specs=(P(CLIENTS), P(CLIENTS)),
        ),
        # The previous segment's carry buffers back the next segment's: the
        # split adds zero steady-state HBM over the monolithic scan.
        donate_argnums=(0,),
    )

    def finalize_shard(carry, variables, active, n_samples):
        params, batch_stats, _ = jax.tree_util.tree_map(lambda x: x[0], carry)
        return _aggregate_and_guard(
            params,
            batch_stats,
            variables["params"],
            variables["batch_stats"],
            active[0],
            n_samples[0],
        )

    # No donation here: the finalize outputs (the replicated averaged tree)
    # cannot alias the clients-sharded carry blocks, so donating would only
    # emit "donated buffers were not usable" warnings; the carry dies by
    # refcount right after this call anyway.
    finalize_fn = jax.jit(
        shard_map(
            finalize_shard,
            mesh=mesh,
            in_specs=(P(CLIENTS), P(), P(CLIENTS), P(CLIENTS)),
            out_specs=P(),
        )
    )

    return SegmentedRound(
        n_segments=n_segments,
        segment_epochs=segment_epochs,
        local_epochs=epochs,
        n_client_shards=n_client_shards,
        init_fn=init_fn,
        segment_fn=segment_fn,
        finalize_fn=finalize_fn,
        validate_data=validate_data,
        data_placement=data_placement,
        n_inner=n_inner,
    )


def build_federated_round_segments(
    mesh: Mesh,
    model_config: ModelConfig | None = None,
    learning_rate: float = 1e-3,
    local_epochs: int = 1,
    fedprox_mu: float = 0.0,
    pos_weight: float = 1.0,
    remat: bool = False,
    segments: int = 0,
    data_placement: str = "streamed",
) -> SegmentedRound:
    """Epoch-segmented variant of :func:`build_federated_round`.

    Same data contract and semantics (including ``data_placement`` — in
    resident mode each segment gathers from the shared device-resident
    pool by its own epochs-axis slice of the round's gather plan);
    ``segments`` (default 0 = one segment per local epoch) must divide
    ``local_epochs``. ``segments=1``
    still differs from the monolithic builder operationally — the carry
    crosses one program boundary and FedAvg runs as a separate finalize
    program — but the result is bit-identical (test-pinned), which makes
    K=1 the cheap cross-check of the whole mechanism.

    Why segment: staging can stream at segment grain under the in-flight
    segments (``parallel.driver``), each compiled program is
    ``1/n_segments`` the size (the 256 px reference-scale round compiles
    as 10 x 388-step programs where the 3,880-step monolith fails —
    VERDICT r5 #6), and carry donation keeps the split HBM-neutral.
    """
    model_config = model_config or ModelConfig()
    _require_axes(mesh, CLIENTS, BATCH)
    apply_fn, validate_channels = _plain_apply_and_validate(model_config)
    return _build_round_segments(
        mesh,
        model_config,
        learning_rate,
        local_epochs,
        fedprox_mu,
        inner_axis=BATCH,
        apply_fn=apply_fn,
        image_spec=P(CLIENTS, None, BATCH),
        validate_data=validate_channels,
        pos_weight=pos_weight,
        remat=remat,
        segments=segments,
        data_placement=data_placement,
    )


def pad_cohort_axis(arr: np.ndarray, c_pad: int) -> np.ndarray:
    """Zero-pad the leading (cohort) axis of a per-client array to
    ``c_pad`` entries. Padding clients ride with ``active = 0`` /
    ``n_samples = 0``, so their weighted contribution to the ordered fold
    is ``±0.0`` — a bitwise no-op (see ``_ordered_cohort_sums``)."""
    arr = np.asarray(arr)
    c = arr.shape[0]
    if c >= c_pad:
        return arr
    pad = np.zeros((c_pad - c,) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)


@dataclasses.dataclass(frozen=True)
class CohortRound:
    """A time-multiplexed federated round: a cohort of C clients executed
    as ``ceil(C / G)`` SEQUENTIAL groups of ``G = mesh.shape['clients']``
    over the same mesh, with a device-resident partial-aggregate carry.

    The chip count bounds how many clients one mesh program can train at
    once; production cohorts are far larger (ROADMAP "Cohort scale: 8 →
    1,000+"). This round keeps the per-group training programs exactly the
    segmented round's (``_build_round_segments`` — same ``_epoch_runner``
    closure, same carry contract) and splits ONLY the aggregation: each
    group's ``partial`` program folds its clients' weighted updates into a
    replicated ``(num_tree, total_weight)`` carry via the ordered client
    fold, and one ``finish`` program divides + guards at the end.

    Byte-exactness contract (test-pinned for groups in {1, 2, 4}, with
    segments > 0): the final global weights AND the per-client metrics are
    bit-identical to the single-group mesh round over the same C-wide
    cohort whenever C fits the chip count — the ordered fold is ONE
    expression tree regardless of the group split (``_ordered_cohort_sums``
    explains why a psum could never give this), per-client local fits are
    mesh-width-independent, and metrics carry no cross-client reduction.
    Cohorts not divisible by G pad the last group with inactive zero-weight
    clients (bitwise no-ops in the fold, sliced out of the metrics).

    Calling the object is round_fn-compatible over FULL-COHORT arrays
    (``(variables, images [C, ...], masks, active [C], n_samples [C])``,
    or the resident pool/plan contract); ``parallel.driver.
    run_cohort_federation`` instead drives ``zeros``/``run_group``/
    ``finish`` itself so each group's slab (or resident pool slice) can
    stage right before its dispatch and release right after — peak staged
    HBM is ~2 GROUP slices, never the C-wide cohort.

    Update-codec twins are monolithic-only (same precedent as the
    segmented builder); the cohort round has no codec arg.
    """

    group_size: int
    n_segments: int
    segment_epochs: int
    local_epochs: int
    n_inner: int
    seg: SegmentedRound = dataclasses.field(repr=False)
    partial_fn: Callable = dataclasses.field(repr=False)
    zeros_fn: Callable = dataclasses.field(repr=False)
    finish_fn: Callable = dataclasses.field(repr=False)
    data_placement: str = "streamed"

    def n_groups(self, cohort_size: int) -> int:
        if cohort_size <= 0:
            raise ValueError(f"cohort_size must be positive, got {cohort_size}")
        return -(-cohort_size // self.group_size)

    def zeros(self, variables):
        """The round's initial partial-aggregate carry (f32 zeros),
        replicated on the mesh so every group program reads it in-place."""
        return self.zeros_fn(variables)

    def run_group(self, sums, variables, data_a, data_b, active_g, n_g):
        """Train ONE group of G clients (init → ``n_segments`` segment
        programs) and fold its weighted updates into the partial-aggregate
        carry. Streamed: ``data_a``/``data_b`` are the group's ``[G, steps,
        B, ...]`` slab pair; resident: the ``(pool_images, pool_masks)``
        pair and the group's ``[G, local_epochs, steps, B]`` plan. Returns
        ``(sums', raw_last)`` where ``raw_last`` is the group's last-epoch
        metric counts ([G] leaves). An all-inactive group (pure padding)
        is legal and leaves ``sums`` bitwise unchanged."""
        carry = self.seg.init(variables)
        raw_last = None
        if self.data_placement == "resident":
            se = self.segment_epochs
            for k in range(self.n_segments):
                carry, raw_last = self.seg.segment(
                    carry, variables, data_a, data_b[:, k * se : (k + 1) * se]
                )
        else:
            for _ in range(self.n_segments):
                carry, raw_last = self.seg.segment(carry, variables, data_a, data_b)
        sums = self.partial_fn(sums, carry, active_g, n_g)
        return sums, raw_last

    def finish(self, sums, variables, raw_lasts, active, cohort_size):
        """Divide the cross-group sums into the new global variables and
        assemble the per-client metrics from the concatenated group counts
        (padding lanes sliced off). Same expression tree as the monolithic
        round's in-program tail — bitwise equal on equal inputs."""
        new_variables = self.finish_fn(sums, variables)
        last = jax.tree_util.tree_map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs])[:cohort_size],
            *raw_lasts,
        )
        active32 = jnp.asarray(np.asarray(active)[:cohort_size], jnp.float32)
        metrics = {
            "loss": jnp.asarray(last["loss"]),
            "pixel_acc": jnp.asarray(last["pixel_acc"]),
            "iou": iou_from_counts(
                jnp.asarray(last["iou_inter"]), jnp.asarray(last["iou_union"])
            ),
            "active": active32,
        }
        return new_variables, metrics

    def _padded_cohort(self, active, n_samples):
        active = np.asarray(active, np.float32)
        n_samples = np.asarray(n_samples, np.float32)
        c = active.shape[0]
        c_pad = self.n_groups(c) * self.group_size
        return (
            pad_cohort_axis(active, c_pad),
            pad_cohort_axis(n_samples, c_pad),
            c,
            c_pad,
        )

    def __call__(self, variables, images, masks, active, n_samples):
        if self.data_placement == "resident":
            pool, idx = tuple(images), np.asarray(masks, np.int32)
            c = idx.shape[0]
            _check_resident_inputs(
                pool, idx, c, self.local_epochs, self.n_inner,
                self.seg.validate_data,
            )
            _host_cohort_check(active, n_samples)
            active, n_samples, c, c_pad = self._padded_cohort(active, n_samples)
            pool_i = pad_cohort_axis(pool[0], c_pad)
            pool_m = pad_cohort_axis(pool[1], c_pad)
            idx = pad_cohort_axis(idx, c_pad)
            sums = self.zeros(variables)
            raw_lasts = []
            g = self.group_size
            for lo in range(0, c_pad, g):
                sums, raw = self.run_group(
                    sums,
                    variables,
                    (pool_i[lo : lo + g], pool_m[lo : lo + g]),
                    idx[lo : lo + g],
                    active[lo : lo + g],
                    n_samples[lo : lo + g],
                )
                raw_lasts.append(raw)
            return self.finish(sums, variables, raw_lasts, active, c)
        images = np.asarray(images)
        masks = np.asarray(masks)
        if images.shape[0] != np.asarray(active).shape[0]:
            raise ValueError(
                f"data carries {images.shape[0]} clients, cohort mask "
                f"{np.asarray(active).shape[0]}"
            )
        self.seg.validate_data(images)
        _host_cohort_check(active, n_samples)
        active, n_samples, c, c_pad = self._padded_cohort(active, n_samples)
        images = pad_cohort_axis(images, c_pad)
        masks = pad_cohort_axis(masks, c_pad)
        sums = self.zeros(variables)
        raw_lasts = []
        g = self.group_size
        for lo in range(0, c_pad, g):
            sums, raw = self.run_group(
                sums,
                variables,
                images[lo : lo + g],
                masks[lo : lo + g],
                active[lo : lo + g],
                n_samples[lo : lo + g],
            )
            raw_lasts.append(raw)
        return self.finish(sums, variables, raw_lasts, active, c)


def build_federated_cohort_round(
    mesh: Mesh,
    model_config: ModelConfig | None = None,
    learning_rate: float = 1e-3,
    local_epochs: int = 1,
    fedprox_mu: float = 0.0,
    pos_weight: float = 1.0,
    remat: bool = False,
    segments: int = 1,
    data_placement: str = "streamed",
) -> CohortRound:
    """Time-multiplexed cohort variant of :func:`build_federated_round`
    (round 13): the returned :class:`CohortRound` executes any cohort size
    as sequential groups of ``mesh.shape['clients']`` with a
    device-resident partial-aggregate carry — byte-identical to a
    hypothetical cohort-wide mesh (see the class docstring for the
    contract and why the aggregation is an ordered fold, not a psum).

    ``segments`` is per GROUP (default 1: one training program per group —
    grouping already bounds program size); values > 1 stream exactly like
    :func:`build_federated_round_segments` and must divide
    ``local_epochs``. ``data_placement="resident"`` takes the pool/plan
    contract with a COHORT-wide pool, sliced per group
    (``parallel.driver.run_cohort_federation`` stages each slice right
    before its group's dispatch).
    """
    model_config = model_config or ModelConfig()
    _require_axes(mesh, CLIENTS, BATCH)
    apply_fn, validate_channels = _plain_apply_and_validate(model_config)
    seg = _build_round_segments(
        mesh,
        model_config,
        learning_rate,
        local_epochs,
        fedprox_mu,
        inner_axis=BATCH,
        apply_fn=apply_fn,
        image_spec=P(CLIENTS, None, BATCH),
        validate_data=validate_channels,
        pos_weight=pos_weight,
        remat=remat,
        segments=segments,
        data_placement=data_placement,
    )

    def partial_shard(sums, carry, active, n_samples):
        params, batch_stats, _ = jax.tree_util.tree_map(lambda x: x[0], carry)
        w = active[0] * n_samples[0]
        return _ordered_cohort_sums(
            {"params": params, "batch_stats": batch_stats}, w, sums
        )

    partial_fn = jax.jit(
        shard_map(
            partial_shard,
            mesh=mesh,
            in_specs=(P(), P(CLIENTS), P(CLIENTS), P(CLIENTS)),
            out_specs=P(),
        )
    )

    def zeros_fn(variables):
        update = {
            "params": variables["params"],
            "batch_stats": variables["batch_stats"],
        }
        zeros = (
            jax.tree_util.tree_map(
                lambda t: np.zeros(np.shape(t), np.float32), update
            ),
            np.zeros((), np.float32),
        )
        return jax.device_put(zeros, NamedSharding(mesh, P()))

    @jax.jit
    def finish_fn(sums, variables):
        num, total_w = sums
        return _finish_cohort_mean(
            num,
            total_w,
            {
                "params": variables["params"],
                "batch_stats": variables["batch_stats"],
            },
        )

    return CohortRound(
        group_size=mesh.shape[CLIENTS],
        n_segments=seg.n_segments,
        segment_epochs=seg.segment_epochs,
        local_epochs=seg.local_epochs,
        n_inner=seg.n_inner,
        seg=seg,
        partial_fn=partial_fn,
        zeros_fn=zeros_fn,
        finish_fn=finish_fn,
        data_placement=data_placement,
    )


def build_spatial_federated_round(
    mesh: Mesh,
    model_config: ModelConfig | None = None,
    learning_rate: float = 1e-3,
    local_epochs: int = 1,
    fedprox_mu: float = 0.0,
    pos_weight: float = 1.0,
    remat: bool = False,
):
    """Federated round over a ``Mesh(('clients', 'space'))``: FedAvg across
    clients whose local fits are each **spatially sharded** over image
    height with halo exchange + sync-BN (``parallel.spatial``). This is the
    composition for crops too large for one chip per client — e.g. 8 chips
    = 4 clients x 2-way spatial — and trains identically to the plain
    (clients, batch=1) round on the same data (cross-checked in tests).

    Same signature/contract as :func:`build_federated_round`, with
    ``images [C, steps, B, H, W, 3]`` sharded ``P('clients', None, None,
    'space')``; H must be a multiple of 16 x n_space.
    """
    from fedcrack_tpu.parallel.spatial import SPACE, _validate_shape, spatial_apply

    model_config = model_config or ModelConfig()
    if model_config.stem_layout != "reference" or model_config.res_layout != "reference":
        # The spatial forward re-implements the reference op-by-op with halo
        # exchange (parallel.spatial's per-op geometry table); the layout
        # transforms repack H/W into channels, which would change every halo
        # width. Layout levers target the per-chip-resident planes.
        raise ValueError(
            "spatial sharding supports the reference layout only; got "
            f"stem_layout={model_config.stem_layout!r}, "
            f"res_layout={model_config.res_layout!r}"
        )
    _require_axes(mesh, CLIENTS, SPACE)
    n_space = mesh.shape[SPACE]

    def apply_fn(params, batch_stats, imgs):
        return spatial_apply(
            {"params": params, "batch_stats": batch_stats},
            imgs,
            config=model_config,
            axis_name=SPACE,
            axis_size=n_space,
            train=True,
            sync_axes=(SPACE,),
        )

    return _build_round(
        mesh,
        model_config,
        learning_rate,
        local_epochs,
        fedprox_mu,
        inner_axis=SPACE,
        apply_fn=apply_fn,
        image_spec=P(CLIENTS, None, None, SPACE),
        validate_data=lambda images: _validate_shape(
            images.shape[3], images.shape[4], n_space
        ),
        pos_weight=pos_weight,
        remat=remat,
    )


@jax.jit
def _weighted_mean(stacked: Any, w: jax.Array) -> Any:
    def leaf(x):
        acc = jnp.tensordot(w, x.astype(jnp.float32), axes=1)
        return acc.astype(x.dtype)

    return jax.tree_util.tree_map(leaf, stacked)


def mesh_fedavg(
    stacked: Any,
    weights: Sequence[float] | jax.Array | None = None,
    active: Sequence[float] | jax.Array | None = None,
) -> Any:
    """Masked weighted mean over the leading (client) axis of a stacked
    pytree — the host-callable form of the in-mesh aggregation, used as the
    golden cross-check against :func:`fedcrack_tpu.fed.algorithms.fedavg`
    (SURVEY.md §4: "mesh FedAvg == gRPC FedAvg == numpy mean")."""
    leaves = jax.tree_util.tree_leaves(stacked)
    if not leaves:
        raise ValueError("empty pytree")
    k = leaves[0].shape[0]
    w = (
        jnp.ones((k,), jnp.float32)
        if weights is None
        else jnp.asarray(weights, jnp.float32)
    )
    if active is not None:
        w = w * jnp.asarray(active, jnp.float32)
    total = float(jnp.sum(w))
    if total <= 0.0:
        raise ValueError("non-positive total FedAvg weight (empty effective cohort)")
    return _weighted_mean(stacked, w / total)


def stack_client_data(
    client_batches: Sequence[tuple[np.ndarray, np.ndarray]],
    steps: int,
    batch_size: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Pack per-client (images, masks) sample arrays into the round_fn layout
    ``[C, steps, B, H, W, ch]``, truncating/cycling each client's samples to
    exactly ``steps * batch_size`` (static shapes — SURVEY.md §7)."""
    need = steps * batch_size
    imgs_out, masks_out = [], []
    for images, masks in client_batches:
        n = images.shape[0]
        if n == 0:
            raise ValueError("client with zero samples")
        idx = np.resize(np.arange(n), need)  # cycle if short, truncate if long
        imgs_out.append(images[idx].reshape(steps, batch_size, *images.shape[1:]))
        masks_out.append(masks[idx].reshape(steps, batch_size, *masks.shape[1:]))
    return np.stack(imgs_out), np.stack(masks_out)
