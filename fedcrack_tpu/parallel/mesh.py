"""Device-mesh construction for the federated data plane.

Axes:

- ``clients`` — one federated client per mesh row (the reference's
  cross-process FedAvg cohort, fl_server.py:45-81, becomes a mesh axis).
- ``batch``  — intra-client data parallelism over the local batch
  (BASELINE.md config 5: "per-client pmap data-parallel").

On a v5e-8 the default is ``(8, 1)`` — 8 clients, one chip each; the same
code runs on a virtual CPU mesh in CI via
``--xla_force_host_platform_device_count`` (SURVEY.md §4).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    n_clients: int,
    n_batch: int = 1,
    devices: Sequence[jax.Device] | None = None,
    axis_names: tuple[str, str] = ("clients", "batch"),
) -> Mesh:
    """Build a two-axis ``Mesh`` (default axes ``('clients', 'batch')``).

    Uses the first ``n_clients * n_batch`` devices. Raises if the host does
    not expose enough devices (the caller decides whether to shrink the
    cohort or multiplex clients per chip).
    """
    if n_clients <= 0 or n_batch <= 0:
        raise ValueError(f"mesh axes must be positive, got ({n_clients}, {n_batch})")
    devs = list(devices) if devices is not None else jax.devices()
    need = n_clients * n_batch
    if len(devs) < need:
        raise ValueError(
            f"mesh ({n_clients} {axis_names[0]} x {n_batch} {axis_names[1]}) "
            f"needs {need} devices, host exposes {len(devs)}"
        )
    grid = np.asarray(devs[:need], dtype=object).reshape(n_clients, n_batch)
    return Mesh(grid, axis_names)
