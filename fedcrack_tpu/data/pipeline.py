"""Host-side input pipeline.

Capability parity with the reference's ``Generator`` + split logic
(reference: client_fit_model.py:19-43,54-90) with the accidents fixed and the
throughput problems solved:

- **Pairing by stem**, not by parallel independent shuffles. The reference
  shuffles image and mask path lists *independently* with the same seed and
  relies on identical filename sort order for pairing (client_fit_model.py:77-78,
  SURVEY.md §2.2(9)); here pairs are formed explicitly and shuffled together.
- **Same tensor contract**: BGR→RGB, resize to ``img_size``, /255 float32
  images; masks resized then binarized ``>0`` to {0,1} float32 with a channel
  dim (client_fit_model.py:30-43).
- **Prefetch**: the reference decodes 16 images synchronously before every
  train step (SURVEY.md §3.3 "the input pipeline is a first-order bottleneck");
  here a thread pool decodes ahead of the device and batches are handed off
  through a bounded queue.

Static shapes: batches are always exactly ``batch_size`` (last partial batch
dropped) so every train step hits the same compiled program.
"""

from __future__ import annotations

import collections
import os
import queue
import random
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Sequence

import numpy as np

_CV2 = None
_CV2_PROBED = False


def _cv2():
    """One cv2 import probe per process — a failed import is not cached by
    Python, and the probe sits on the per-sample decode path."""
    global _CV2, _CV2_PROBED
    if not _CV2_PROBED:
        try:
            import cv2 as _mod

            _CV2 = _mod
        except ImportError:
            _CV2 = None
        _CV2_PROBED = True
    return _CV2


def list_pairs(image_dir: str, mask_dir: str) -> list[tuple[str, str]]:
    """Paired (image_path, mask_path) lists, matched by filename stem."""

    def stems(d: str) -> dict[str, str]:
        out = {}
        for fname in sorted(os.listdir(d)):
            if fname.startswith(".") or not fname.lower().endswith(
                (".jpg", ".jpeg", ".png", ".bmp")
            ):
                continue
            out[os.path.splitext(fname)[0]] = os.path.join(d, fname)
        return out

    imgs, masks = stems(image_dir), stems(mask_dir)
    common = sorted(imgs.keys() & masks.keys())
    if not common:
        raise FileNotFoundError(
            f"no paired images/masks between {image_dir!r} and {mask_dir!r}"
        )
    return [(imgs[s], masks[s]) for s in common]


def reference_split(
    pairs: Sequence[tuple[str, str]],
    train_samples: int = 6213,
    seed: int = 1337,
) -> tuple[list[tuple[str, str]], list[tuple[str, str]]]:
    """Deterministic train/val split with the reference's semantics.

    The reference shuffles with ``random.Random(1337)`` and takes the first
    ``train_samples`` paths as train, the rest as val (client_fit_model.py:76-82).
    Pairs are shuffled jointly here (see module docstring).
    """
    shuffled = list(pairs)
    random.Random(seed).shuffle(shuffled)
    train_samples = min(train_samples, max(1, len(shuffled) - 1))
    return shuffled[:train_samples], shuffled[train_samples:]


# One shared normalization constant for BOTH the host decode path and the
# on-device path: written as an explicit reciprocal multiply because XLA
# rewrites a divide-by-constant into exactly this multiply — with the host
# doing a true division the two paths would differ by 1 ulp and "uint8
# transport is bit-identical" would be a lie.
_INV255 = np.float32(1.0 / 255.0)


def normalize_images(images):
    """On-device image normalization: uint8 transport bytes -> the model's
    float32-in-[0,1] contract; float32 passes through. jnp, jit-traceable —
    the dtype branch resolves at trace time and the multiply fuses into the
    first conv's input pipeline."""
    import jax.numpy as jnp

    if images.dtype == jnp.uint8:
        return images.astype(jnp.float32) * _INV255
    return images


def to_uint8_transport(images: np.ndarray, masks: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Encode float32 model-contract arrays as uint8 transport bytes: images
    [0,1] -> round-to-nearest u8 (the inverse of ``normalize_images``'s /255),
    masks {0,1} -> u8 {0,1}. Single source for every producer of synthetic
    uint8 staging data (bench.py, tools/refscale_federation) — the bit-exact
    round-trip claim holds only if encode and decode stay paired."""
    images_u8 = np.clip(np.rint(images * np.float32(255.0)), 0, 255).astype(np.uint8)
    return images_u8, masks.astype(np.uint8)


def space_to_depth_images(images: np.ndarray) -> np.ndarray:
    """Host-side space-to-depth packing for STAGING: ``[..., H, W, C] ->
    [..., H/2, W/2, 4C]`` with the same block-position-major channel order as
    ``models.resunet.space_to_depth`` (its device twin — the model accepts
    either layout when a ``stem_layout`` transform is on, skipping the
    on-device relayout for pre-packed arrays). Works on any leading batch
    dims (``[B, ...]`` or the round layout ``[C, steps, B, ...]``) and any
    dtype — uint8 transport bytes pack identically to float32 (pure data
    movement). Masks are NEVER packed: the loss runs at full resolution.
    """
    *lead, h, w, c = images.shape
    if h % 2 or w % 2:
        raise ValueError(f"space_to_depth_images needs even H,W; got {(h, w)}")
    x = images.reshape(*lead, h // 2, 2, w // 2, 2, c)
    n = len(lead)
    x = x.transpose(*range(n), n, n + 2, n + 1, n + 3, n + 4)
    return np.ascontiguousarray(x.reshape(*lead, h // 2, w // 2, 4 * c))


class SamplePool:
    """Deduplicated per-client sample pool: the resident data plane's source
    of truth (round 9).

    The streamed plane re-ships the SAME samples every round in a new
    shuffle order (``parallel.driver.shuffled_epoch_data`` + per-round
    restaging) — the bytes on the wire are a permutation of bytes already
    in HBM. This class keeps the deduplicated pool as a HOST TWIN
    (``images [C, N, H, W, ch]``, ``masks [C, N, H, W, 1]``, uint8
    transport canon) and stages it ONCE onto the mesh sharded
    ``P('clients')``; per round only an ``[C, epochs, steps, batch]``
    int32 index array ships (kilobytes), and the round program gathers
    each step's batch on device (``parallel.fedavg_mesh``,
    ``data_placement="resident"``).

    ``layout="s2d"`` stores the images pre-packed through
    :func:`space_to_depth_images` (the PR-1 staging twin): gathering from
    the packed pool is byte-identical to packing the gathered slab, because
    the packing is per-sample and commutes with sample selection. Masks are
    never packed (the loss runs at full resolution).

    The host twin is deliberately retained: a chaos/preemption replay
    (``max_round_retries``) re-stages the pool from it bit-identically,
    and the HBM-guard fallback assembles streamed epoch slabs from it
    (:meth:`assemble_round_slab`).
    """

    LAYOUTS = ("reference", "s2d")

    def __init__(self, images: np.ndarray, masks: np.ndarray, *, layout: str = "reference"):
        if layout not in self.LAYOUTS:
            raise ValueError(f"layout must be one of {self.LAYOUTS}, got {layout!r}")
        images = np.asarray(images)
        masks = np.asarray(masks)
        if images.ndim != 5 or masks.ndim != 5:
            raise ValueError(
                "SamplePool wants [C, N, H, W, ch] images and [C, N, H, W, 1] "
                f"masks; got {images.shape} / {masks.shape}"
            )
        if images.shape[:2] != masks.shape[:2]:
            raise ValueError(
                f"images/masks disagree on [C, N]: {images.shape[:2]} vs "
                f"{masks.shape[:2]}"
            )
        if layout == "s2d":
            images = space_to_depth_images(images)
        self.images = np.ascontiguousarray(images)
        self.masks = np.ascontiguousarray(masks)
        self.layout = layout
        # Growable-pool bookkeeping (round 13 satellite, the serve→train
        # flywheel's prerequisite): per-client VALID counts (capacity may
        # exceed them after evictions) and per-client content digests of
        # the STORED sample bytes, so append() preserves the pool's
        # dedup invariant byte-exactly.
        self._counts = np.full(self.images.shape[0], self.images.shape[1], np.int64)
        # Built lazily on the first append/evict: hashing a reference-scale
        # pool costs seconds, and read-only pools (every pre-flywheel user)
        # never pay it.
        self._digests: list[dict[bytes, int]] | None = None

    @classmethod
    def stack(
        cls, client_pools: Sequence[tuple[np.ndarray, np.ndarray]], *, layout: str = "reference"
    ) -> "SamplePool":
        """Pool from per-client ``(images [N, ...], masks [N, ...])`` pairs.
        Every client must hold the same N (static shapes — the mesh round
        is one program over all clients)."""
        if not client_pools:
            raise ValueError("no client pools")
        ns = {p[0].shape[0] for p in client_pools}
        if len(ns) != 1:
            raise ValueError(f"clients disagree on pool size: {sorted(ns)}")
        return cls(
            np.stack([p[0] for p in client_pools]),
            np.stack([p[1] for p in client_pools]),
            layout=layout,
        )

    @property
    def n_clients(self) -> int:
        return self.images.shape[0]

    @property
    def n_samples(self) -> int:
        """Pool CAPACITY per client (the device array's sample axis);
        :meth:`counts` gives the per-client valid counts, which trail
        capacity after evictions."""
        return self.images.shape[1]

    @property
    def nbytes(self) -> int:
        return int(self.images.nbytes + self.masks.nbytes)

    def counts(self) -> np.ndarray:
        """Per-client valid sample counts ``[C]`` (gather plans index only
        ``[0, counts[c])``; capacity lanes past them are retired padding)."""
        return self._counts.copy()

    @staticmethod
    def _digest(image: np.ndarray, mask: np.ndarray) -> bytes:
        import hashlib

        h = hashlib.sha256(np.ascontiguousarray(image).tobytes())
        h.update(np.ascontiguousarray(mask).tobytes())
        return h.digest()

    def _ensure_digests(self) -> list[dict[bytes, int]]:
        if self._digests is None:
            self._digests = [
                {
                    self._digest(self.images[c, i], self.masks[c, i]): i
                    for i in range(int(self._counts[c]))
                }
                for c in range(self.n_clients)
            ]
        return self._digests

    def append(self, client: int, images: np.ndarray, masks: np.ndarray) -> int:
        """Grow one client's pool by the given ``[k, H, W, ch]`` samples
        (REFERENCE layout in; an ``s2d`` pool packs on the way in, exactly
        like the constructor), skipping any sample whose stored bytes are
        already in that client's pool — the dedup invariant the resident
        plane was built on survives growth. Returns how many samples were
        actually kept.

        Capacity grows for ALL clients when one client outgrows it (the
        mesh round's static shapes want one rectangular ``[C, N, ...]``
        placement); other clients' new lanes are zero padding outside
        their valid counts. The host twin stays the byte oracle: a staged
        device pool is a bit-exact copy of these arrays, so re-staging
        after an append reproduces gathers over the old indices exactly.
        """
        if not 0 <= client < self.n_clients:
            raise ValueError(f"client {client} outside [0, {self.n_clients})")
        images = np.asarray(images)
        masks = np.asarray(masks)
        if images.ndim != 4 or masks.ndim != 4:
            raise ValueError(
                "append wants [k, H, W, ch] images and [k, H, W, 1] masks; "
                f"got {images.shape} / {masks.shape}"
            )
        if images.shape[0] != masks.shape[0]:
            raise ValueError(
                f"images/masks disagree on k: {images.shape[0]} vs {masks.shape[0]}"
            )
        if self.layout == "s2d":
            images = space_to_depth_images(images)
        if images.shape[1:] != self.images.shape[2:]:
            raise ValueError(
                f"sample shape {images.shape[1:]} does not match pool "
                f"{self.images.shape[2:]}"
            )
        if masks.shape[1:] != self.masks.shape[2:]:
            raise ValueError(
                f"mask shape {masks.shape[1:]} does not match pool "
                f"{self.masks.shape[2:]}"
            )
        images = images.astype(self.images.dtype, copy=False)
        masks = masks.astype(self.masks.dtype, copy=False)
        fresh_i, fresh_m, fresh_d = [], [], []
        seen = self._ensure_digests()[client]
        for i in range(images.shape[0]):
            d = self._digest(images[i], masks[i])
            if d in seen or any(d == fd for fd in fresh_d):
                continue
            fresh_i.append(images[i])
            fresh_m.append(masks[i])
            fresh_d.append(d)
        if not fresh_i:
            return 0
        need = int(self._counts[client]) + len(fresh_i)
        if need > self.n_samples:
            grow = need - self.n_samples
            self.images = np.ascontiguousarray(
                np.concatenate(
                    [
                        self.images,
                        np.zeros(
                            (self.n_clients, grow) + self.images.shape[2:],
                            self.images.dtype,
                        ),
                    ],
                    axis=1,
                )
            )
            self.masks = np.ascontiguousarray(
                np.concatenate(
                    [
                        self.masks,
                        np.zeros(
                            (self.n_clients, grow) + self.masks.shape[2:],
                            self.masks.dtype,
                        ),
                    ],
                    axis=1,
                )
            )
        base = int(self._counts[client])
        for j, (im, mk, d) in enumerate(zip(fresh_i, fresh_m, fresh_d)):
            self.images[client, base + j] = im
            self.masks[client, base + j] = mk
            seen[d] = base + j
        self._counts[client] = base + len(fresh_i)
        return len(fresh_i)

    def evict(self, client: int, indices) -> int:
        """Retire samples from one client's pool by index. The survivors
        compact to the front IN ORDER (so a plan regenerated from the new
        counts stays dense) and the freed tail lanes zero out; capacity
        never shrinks — the device placement's shape is stable until the
        next capacity growth. Returns how many samples were evicted.
        Out-of-range / already-invalid indices are an error (silently
        ignoring them would desync the dedup digests)."""
        if not 0 <= client < self.n_clients:
            raise ValueError(f"client {client} outside [0, {self.n_clients})")
        self._ensure_digests()
        n_valid = int(self._counts[client])
        drop = sorted(set(int(i) for i in np.atleast_1d(np.asarray(indices))))
        if not drop:
            return 0
        if drop[0] < 0 or drop[-1] >= n_valid:
            raise ValueError(
                f"evict indices {drop} outside the valid range [0, {n_valid})"
            )
        drop_set = set(drop)
        keep = [i for i in range(n_valid) if i not in drop_set]
        new_imgs = self.images[client, keep]
        new_msks = self.masks[client, keep]
        self.images[client, : len(keep)] = new_imgs
        self.masks[client, : len(keep)] = new_msks
        self.images[client, len(keep) : n_valid] = 0
        self.masks[client, len(keep) : n_valid] = 0
        self._counts[client] = len(keep)
        # Remap the surviving digests to their compacted indices instead of
        # re-hashing the whole surviving pool (hashing a reference-scale
        # client costs seconds; the digests already exist).
        remap = {old: new for new, old in enumerate(keep)}
        self._digests[client] = {
            d: remap[i]
            for d, i in self._digests[client].items()
            if i in remap
        }
        return len(drop)

    def round_indices(
        self,
        rngs: Sequence[np.random.Generator],
        epochs: int,
        steps: int,
        batch_size: int,
    ) -> np.ndarray:
        """One round's gather plan: ``[C, epochs, steps, batch]`` int32.

        Per client, ONE fresh permutation of the pool per round — drawn
        exactly like ``parallel.driver.shuffled_epoch_data``
        (``rng.permutation(n)[:steps*batch]``), then tiled across the
        epochs axis (the mesh round consumes one epoch slab for all local
        epochs). Same rng state in, same trajectory out — that equivalence
        is what makes resident == streamed byte-identical (test-pinned).
        """
        if len(rngs) != self.n_clients:
            raise ValueError(f"{len(rngs)} rngs for {self.n_clients} clients")
        need = steps * batch_size
        per_client = []
        for c, rng in enumerate(rngs):
            # Permute each client's VALID samples only (== the whole pool
            # until the first append/evict, so untouched pools consume the
            # rng identically to the pre-growable plane — the byte-oracle
            # parity the resident tests pin).
            n_valid = int(self._counts[c])
            if n_valid < need:
                raise ValueError(
                    f"client {c} pool has {n_valid} valid samples, round "
                    f"needs {need}"
                )
            perm = rng.permutation(n_valid)[:need].reshape(steps, batch_size)
            per_client.append(np.broadcast_to(perm, (max(1, epochs), steps, batch_size)))
        return np.ascontiguousarray(np.stack(per_client).astype(np.int32))

    def assemble_round_slab(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Host-assembled ``[C, steps, B, ...]`` epoch slab from a round's
        index array — the HBM-guard fallback's bridge back to the streamed
        plane, and the byte-identity test oracle (``pool[idx]`` on host is
        the same data movement the device gather performs).

        Requires the index array to be constant along the epochs axis (the
        round layout holds ONE epoch of data; a per-epoch-varying plan has
        no streamed equivalent)."""
        idx = np.asarray(idx)
        if idx.ndim != 4 or idx.shape[0] != self.n_clients:
            raise ValueError(
                f"idx must be [C={self.n_clients}, epochs, steps, batch]; got {idx.shape}"
            )
        if not (idx == idx[:, :1]).all():
            raise ValueError(
                "idx varies across the epochs axis: no streamed-slab equivalent"
            )
        e0 = idx[:, 0]  # [C, steps, B]
        images = np.ascontiguousarray(
            np.stack([self.images[c][e0[c]] for c in range(self.n_clients)])
        )
        masks = np.ascontiguousarray(
            np.stack([self.masks[c][e0[c]] for c in range(self.n_clients)])
        )
        return images, masks

    def stage(self, mesh) -> tuple:
        """Device placement: one ``device_put`` of each array, sharded
        ``P('clients')`` over the mesh (replicated over every other axis),
        barriered until the bytes have landed. Returns the
        ``(images, masks)`` device pair the resident round programs consume.
        Re-staging from the retained host twin is bit-identical — the
        chaos-replay contract."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P("clients"))
        si = jax.device_put(self.images, sharding)
        sm = jax.device_put(self.masks, sharding)
        for a in (si, sm):
            # Element readback = a real transfer barrier even through
            # remote-device tunnels (see parallel.driver._barrier_read).
            float(jnp.asarray(a[(0,) * a.ndim], jnp.float32))
        return si, sm


def split_epoch_slab(
    images: np.ndarray, masks: np.ndarray, n_chunks: int
) -> tuple[tuple[np.ndarray, ...], tuple[np.ndarray, ...]]:
    """Split one round's ``[C, steps, B, ...]`` epoch slab into ``n_chunks``
    contiguous step-range chunks (zero-copy views) for segment-grain staging.

    The chunks concatenate back to the original along the steps axis, so a
    round program consuming them in order is byte-identical to one consuming
    the monolithic slab (``parallel.fedavg_mesh.SegmentedRound``). Chunk
    boundaries follow ``np.array_split`` (first ``steps % n_chunks`` chunks
    one step longer); ``n_chunks`` is clamped to ``steps`` so tiny rounds
    never produce empty chunks."""
    if images.shape[:3] != masks.shape[:3]:
        raise ValueError(
            f"images/masks round layouts disagree: {images.shape[:3]} vs "
            f"{masks.shape[:3]}"
        )
    if n_chunks < 1:
        raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")
    steps = images.shape[1]
    n_chunks = min(n_chunks, steps)
    bounds = np.array_split(np.arange(steps), n_chunks)
    img_chunks = tuple(images[:, b[0] : b[-1] + 1] for b in bounds)
    msk_chunks = tuple(masks[:, b[0] : b[-1] + 1] for b in bounds)
    return img_chunks, msk_chunks


def as_model_batch(images, masks):
    """Normalize a transport batch (possibly uint8, see ``transport_dtype``)
    to the model contract: float32 [0,1] images, float32 {0,1} masks.
    Images may be space-to-depth-packed (``space_to_depth_images``) when the
    model runs a ``stem_layout`` transform — normalization is elementwise and
    layout-blind, and the model accepts both layouts.

    Why uint8 transport exists: the decode path resizes in uint8 BEFORE the
    /255 normalization (exactly like the reference, client_fit_model.py:30-43),
    so shipping the uint8 bytes and dividing on device is bit-identical to
    shipping float32 — at 1/4 the host->device bytes (SURVEY.md §7 "input
    pipeline at TPU speed").
    """
    import jax.numpy as jnp

    images = normalize_images(images)
    if masks.dtype == jnp.uint8:
        masks = masks.astype(jnp.float32)
    return images, masks


def load_example(
    image_path: str,
    mask_path: str,
    img_size: int,
    transport_dtype: str = "float32",
) -> tuple[np.ndarray, np.ndarray]:
    """Decode one pair to the reference's tensor contract.

    OpenCV when available (its AVX2 fixed-point resize is fastest); otherwise
    PIL decode + the first-party native resize (fedcrack_tpu.native) — the
    framework does not hard-require cv2 the way the reference does
    (client_fit_model.py:12).

    ``transport_dtype="uint8"`` keeps the resized uint8 bytes (images RGB u8,
    masks {0,1} u8) for device-side normalization via :func:`as_model_batch`.
    Honored on BOTH decode backends: cv2 resizes in uint8 natively, and the
    PIL path uses the native uint8-domain kernel (round-to-nearest), so the
    1/4-staging-bytes property never silently degrades with OpenCV absent.
    On the cv2 path the float32 variant is computed from the same uint8
    bytes, so the two transport dtypes are bit-identical after on-device
    normalization; on the PIL path the float32 variant interpolates in
    float, so uint8 transport differs from it by at most the 1/510
    quantization step (masks are bit-identical on both backends).
    """
    cv2 = _cv2()
    want_u8 = transport_dtype == "uint8"

    if cv2 is not None:
        img = cv2.imread(image_path, cv2.IMREAD_COLOR)
        if img is None:
            raise FileNotFoundError(image_path)
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        img = cv2.resize(img, (img_size, img_size))

        m = cv2.imread(mask_path, cv2.IMREAD_GRAYSCALE)
        if m is None:
            raise FileNotFoundError(mask_path)
        m = cv2.resize(m, (img_size, img_size))
        if want_u8:
            return img, (m > 0).astype(np.uint8)[..., None]
        return img.astype(np.float32) * _INV255, (m > 0).astype(np.float32)[..., None]

    from PIL import Image

    from fedcrack_tpu import native

    with Image.open(image_path) as im:
        rgb = np.asarray(im.convert("RGB"), np.uint8)
    with Image.open(mask_path) as im:
        gray = np.asarray(im.convert("L"), np.uint8)
    if want_u8:
        return (
            native.resize_u8(rgb, img_size),
            native.resize_binarize_u8(gray, img_size),
        )
    return (
        native.resize_normalize(rgb, img_size),
        native.resize_binarize(gray, img_size),
    )


def _num_batches(n_samples: int, batch_size: int, drop_last: bool) -> int:
    n = n_samples // batch_size
    if not drop_last and n_samples % batch_size:
        n += 1
    return n


def _epoch_order(n_samples: int, shuffle: bool, seed: int, epoch: int) -> np.ndarray:
    order = np.arange(n_samples)
    if shuffle:
        np.random.default_rng(seed + epoch).shuffle(order)
    return order


def _check_yields_batches(n_samples: int, batch_size: int, drop_last: bool) -> None:
    if _num_batches(n_samples, batch_size, drop_last) == 0:
        raise ValueError(
            f"{n_samples} samples with batch_size={batch_size} and "
            f"drop_last={drop_last} would yield zero batches — training would "
            "silently be a no-op"
        )


class CrackDataset:
    """Batched, shuffled, prefetching iterator over paired crack images.

    Yields numpy ``(images [B,S,S,3] float32, masks [B,S,S,1] float32)``.
    """

    def __init__(
        self,
        pairs: Sequence[tuple[str, str]],
        img_size: int = 128,
        batch_size: int = 16,
        shuffle: bool = True,
        seed: int = 0,
        num_workers: int = 4,
        prefetch: int = 2,
        drop_last: bool = True,
        transport_dtype: str = "float32",
    ):
        if not pairs:
            raise ValueError("empty dataset")
        if transport_dtype not in ("float32", "uint8"):
            raise ValueError(f"transport_dtype must be float32 or uint8, got {transport_dtype!r}")
        _check_yields_batches(len(pairs), batch_size, drop_last)
        self.pairs = list(pairs)
        self.img_size = img_size
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.num_workers = num_workers
        self.prefetch = prefetch
        self.drop_last = drop_last
        # uint8 is honored on both decode backends (cv2's native u8 resize,
        # or the first-party uint8-domain kernel on the PIL path) — no
        # silent downgrade with OpenCV absent.
        self.transport_dtype = transport_dtype
        self._epoch = 0

    def __len__(self) -> int:
        return _num_batches(len(self.pairs), self.batch_size, self.drop_last)

    def _batch_indices(self) -> list[np.ndarray]:
        order = _epoch_order(len(self.pairs), self.shuffle, self.seed, self._epoch)
        return [
            order[i * self.batch_size : (i + 1) * self.batch_size]
            for i in range(len(self))
        ]

    def _load_batch(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        dt = np.uint8 if self.transport_dtype == "uint8" else np.float32
        images = np.empty((len(idx), self.img_size, self.img_size, 3), dt)
        masks = np.empty((len(idx), self.img_size, self.img_size, 1), dt)
        for j, i in enumerate(idx):
            images[j], masks[j] = load_example(
                *self.pairs[i], self.img_size, transport_dtype=self.transport_dtype
            )
        return images, masks

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        batches = self._batch_indices()
        self._epoch += 1
        if self.num_workers <= 0:
            for idx in batches:
                yield self._load_batch(idx)
            return

        # Bounded producer/consumer: workers decode ahead of the device, but
        # only `num_workers + prefetch` batches are ever in flight — the
        # submission is lazy, so a slow consumer bounds memory, and every
        # q.put observes `stop` so an early consumer exit can't strand the
        # producer thread.
        q: queue.Queue = queue.Queue(maxsize=max(1, self.prefetch))
        stop = threading.Event()

        def put_or_abort(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            max_outstanding = self.num_workers + max(1, self.prefetch)
            batch_iter = iter(batches)
            pending: collections.deque = collections.deque()
            with ThreadPoolExecutor(self.num_workers) as pool:
                while not stop.is_set():
                    while len(pending) < max_outstanding:
                        idx = next(batch_iter, None)
                        if idx is None:
                            break
                        pending.append(pool.submit(self._load_batch, idx))
                    if not pending:
                        break
                    fut = pending.popleft()
                    try:
                        item = ("ok", fut.result())
                    except Exception as e:  # surface decode errors to consumer
                        item = ("err", e)
                    if not put_or_abort(item) or item[0] == "err":
                        break
                for fut in pending:
                    fut.cancel()
            put_or_abort(("end", None))

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                kind, payload = q.get()
                if kind == "end":
                    return
                if kind == "err":
                    raise payload
                yield payload
        finally:
            stop.set()
            # unblock a producer mid-put; it exits via the stop check
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5.0)


class ArrayDataset:
    """In-memory (images, masks) batcher with the same epoch semantics as
    :class:`CrackDataset` — used for synthetic fixtures and benchmarks."""

    def __init__(
        self,
        images: np.ndarray,
        masks: np.ndarray,
        batch_size: int = 16,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ):
        if len(images) != len(masks) or len(images) == 0:
            raise ValueError("images/masks length mismatch or empty")
        _check_yields_batches(len(images), batch_size, drop_last)
        self.images, self.masks = images, masks
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0

    def __len__(self) -> int:
        return _num_batches(len(self.images), self.batch_size, self.drop_last)

    def __iter__(self):
        order = _epoch_order(len(self.images), self.shuffle, self.seed, self._epoch)
        self._epoch += 1
        for i in range(len(self)):
            idx = order[i * self.batch_size : (i + 1) * self.batch_size]
            yield self.images[idx], self.masks[idx]


def device_prefetch(iterator, size: int = 2):
    """Overlap host decode with device compute: device_put batches ahead."""
    import jax

    buf = collections.deque()
    it = iter(iterator)
    try:
        for _ in range(size):
            buf.append(jax.device_put(next(it)))
    except StopIteration:
        pass
    while buf:
        nxt = buf.popleft()
        try:
            buf.append(jax.device_put(next(it)))
        except StopIteration:
            pass
        yield nxt


def dataset_from_source(
    synthetic: int,
    image_dir: str | None,
    mask_dir: str | None,
    *,
    img_size: int,
    batch_size: int,
    seed: int = 0,
    drop_last: bool = True,
    num_workers: int | None = None,
    prefetch: int | None = None,
    pair_filter=None,
    transport_dtype: str = "float32",
):
    """One dataset from either source the CLIs accept: ``--synthetic N``
    (generated fixtures -> :class:`ArrayDataset`) or paired
    ``--image-dir/--mask-dir`` (-> :class:`CrackDataset`). Shared by the
    client, centralized-trainer and quantifier entry points so batch
    clamping and error behavior stay consistent.

    ``pair_filter`` selects a subset of the listed pairs (e.g. one side of
    :func:`reference_split`). The batch size is clamped to the dataset size
    so small datasets yield batches instead of crashing at startup.
    """
    from fedcrack_tpu.data.synthetic import synth_crack_batch

    if synthetic:
        images, masks = synth_crack_batch(synthetic, img_size, seed=seed)
        return ArrayDataset(
            images,
            masks,
            batch_size=max(1, min(batch_size, len(images))),
            seed=seed,
            drop_last=drop_last,
        )
    if not (image_dir and mask_dir):
        raise ValueError("need --image-dir/--mask-dir or --synthetic N")
    pairs = list_pairs(image_dir, mask_dir)
    if pair_filter is not None:
        pairs = pair_filter(pairs)
    if not pairs:
        raise ValueError(
            f"no image/mask pairs selected from {image_dir!r}/{mask_dir!r}"
        )
    kw = {}
    if num_workers is not None:
        kw["num_workers"] = num_workers
    if prefetch is not None:
        kw["prefetch"] = prefetch
    return CrackDataset(
        pairs,
        img_size=img_size,
        batch_size=max(1, min(batch_size, len(pairs))),
        seed=seed,
        drop_last=drop_last,
        transport_dtype=transport_dtype,
        **kw,
    )
