"""Synthetic crack-image fixtures.

The real dataset (paired crack photos + binary masks, ≥6213 train samples —
reference: client_fit_model.py:58-59,76) is not shipped with the snapshot
(SURVEY.md §0.1), so tests and benchmarks run on generated fixtures: a noisy
concrete-like texture with a dark random-walk crack polyline; the mask is the
crack's footprint. Deterministic per seed.
"""

from __future__ import annotations

import os

import numpy as np


def _crack_polyline(
    rng: np.random.Generator, size: int, min_thickness: int | None = None
) -> np.ndarray:
    """Boolean crack footprint: a jittered random walk across the tile."""
    mask = np.zeros((size, size), dtype=bool)
    # start on a random edge, walk to the opposite side
    y = rng.integers(0, size)
    lo_t = 1 if min_thickness is None else min_thickness
    thickness = int(rng.integers(lo_t, max(lo_t + 1, size // 24)))
    for x in range(size):
        y = int(np.clip(y + rng.integers(-2, 3), 0, size - 1))
        lo = max(0, y - thickness)
        hi = min(size, y + thickness + 1)
        mask[lo:hi, x] = True
    if rng.random() < 0.5:
        mask = mask.T
    return mask


def synth_crack_batch(
    n: int,
    img_size: int = 128,
    seed: int = 0,
    crack_prob: float = 0.8,
    min_thickness: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Generate ``n`` (image, mask) pairs.

    Returns ``images`` float32 [n, s, s, 3] in [0, 1] and ``masks`` float32
    [n, s, s, 1] in {0, 1} — the exact tensor contract of the reference's
    ``Generator`` (client_fit_model.py:30-43: RGB /255; mask binarized >0).

    ``min_thickness`` widens the crack stroke (default: hairline, 1 px
    half-width). IoU on hairline structures is boundary-dominated — at
    64 px the measured quality CEILING of a 40-epoch fit is ~0.38
    (bench_runs/r03_quality_posweight_64px.json) — so quality GATES use a
    thicker stroke where "IoU >= 0.5" separates real localization from
    luck, while parity fixtures keep the default geometry.
    """
    rng = np.random.default_rng(seed)
    images = np.empty((n, img_size, img_size, 3), np.float32)
    masks = np.zeros((n, img_size, img_size, 1), np.float32)
    for i in range(n):
        base = rng.uniform(0.45, 0.75)
        texture = rng.normal(base, 0.06, size=(img_size, img_size, 1)).astype(np.float32)
        img = np.clip(np.repeat(texture, 3, axis=-1), 0.0, 1.0)
        if rng.random() < crack_prob:
            crack = _crack_polyline(rng, img_size, min_thickness)
            darkness = rng.uniform(0.15, 0.35)
            img[crack] = darkness + rng.normal(0, 0.02, size=(int(crack.sum()), 3)).astype(
                np.float32
            )
            masks[i, crack, 0] = 1.0
        images[i] = np.clip(img, 0.0, 1.0)
    return images, masks


def write_synthetic_dataset(
    root: str,
    n: int = 32,
    img_size: int = 128,
    seed: int = 0,
    crack_prob: float = 0.8,
    min_thickness: int | None = None,
) -> tuple[str, str]:
    """Materialize a fixture dataset on disk in the reference's layout:
    paired files with identical stems under ``images/`` and ``masks/``
    (reference layout: crack_segmentation_dataset/train/{images,masks},
    test/Segmentation.py:13-17). Returns (image_dir, mask_dir).
    ``min_thickness`` as in :func:`synth_crack_batch` (quality-gate fixtures
    use a thick stroke).
    """
    import cv2

    image_dir = os.path.join(root, "images")
    mask_dir = os.path.join(root, "masks")
    os.makedirs(image_dir, exist_ok=True)
    os.makedirs(mask_dir, exist_ok=True)
    images, masks = synth_crack_batch(n, img_size, seed, crack_prob, min_thickness)
    for i in range(n):
        bgr = cv2.cvtColor((images[i] * 255).astype(np.uint8), cv2.COLOR_RGB2BGR)
        cv2.imwrite(os.path.join(image_dir, f"img_{i:05d}.jpg"), bgr)
        # Masks must be lossless: JPEG ringing would leak nonzero background
        # pixels through the ``>0`` binarization.
        cv2.imwrite(
            os.path.join(mask_dir, f"img_{i:05d}.png"),
            (masks[i, :, :, 0] * 255).astype(np.uint8),
        )
    return image_dir, mask_dir
