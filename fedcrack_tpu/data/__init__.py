from fedcrack_tpu.data.pipeline import (  # noqa: F401
    ArrayDataset,
    CrackDataset,
    SamplePool,
    as_model_batch,
    dataset_from_source,
    list_pairs,
    load_example,
    normalize_images,
    reference_split,
)
from fedcrack_tpu.data.sharding import partition_iid, partition_skew  # noqa: F401
from fedcrack_tpu.data.synthetic import synth_crack_batch, write_synthetic_dataset  # noqa: F401
