"""Client shard assignment for federated training.

The reference has no sharding at all — every client reads the same local
dataset directory (client_fit_model.py:58-59). Here the coordinator (or an
offline tool) assigns disjoint shards: IID uniform, or non-IID with
per-client crack-density skew (BASELINE.md config 4: "non-IID client shards
(per-client crack-type skew) + FedProx mu>0").
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def partition_iid(
    n_samples: int, num_clients: int, seed: int = 0
) -> list[np.ndarray]:
    """Uniform random disjoint shards, near-equal sizes."""
    if num_clients <= 0:
        raise ValueError("num_clients must be positive")
    order = np.arange(n_samples)
    np.random.default_rng(seed).shuffle(order)
    return [np.sort(s) for s in np.array_split(order, num_clients)]


def partition_skew(
    scores: Sequence[float],
    num_clients: int,
    alpha: float = 0.3,
    seed: int = 0,
) -> list[np.ndarray]:
    """Non-IID shards skewed by a per-sample score (e.g. crack density).

    Samples are bucketed into ``num_clients`` score quantiles; a Dirichlet(α)
    mixing matrix assigns each bucket across clients, so small α → each client
    sees mostly one crack-density regime (heavy cracks vs hairline vs clean).
    Every sample lands on exactly one client; shards are disjoint and cover.
    """
    if num_clients <= 0:
        raise ValueError("num_clients must be positive")
    scores = np.asarray(scores, np.float64)
    n = scores.shape[0]
    rng = np.random.default_rng(seed)
    by_score = np.argsort(scores, kind="stable")
    buckets = np.array_split(by_score, num_clients)

    shards: list[list[int]] = [[] for _ in range(num_clients)]
    for b, bucket in enumerate(buckets):
        # proportions of this quantile bucket going to each client; biased
        # toward client b so α→0 degenerates to "client b owns quantile b"
        props = rng.dirichlet(np.full(num_clients, alpha) + (np.arange(num_clients) == b))
        counts = np.floor(props * len(bucket)).astype(int)
        counts[b] += len(bucket) - counts.sum()  # remainder to the home client
        perm = rng.permutation(bucket)
        start = 0
        for c in range(num_clients):
            shards[c].extend(perm[start : start + counts[c]].tolist())
            start += counts[c]
    return [np.sort(np.asarray(s, dtype=np.int64)) for s in shards]


def crack_density(masks: np.ndarray) -> np.ndarray:
    """Per-sample fraction of crack pixels — the default skew score."""
    masks = np.asarray(masks)
    return masks.reshape(masks.shape[0], -1).mean(axis=1)
