"""Client shard assignment for federated training.

The reference has no sharding at all — every client reads the same local
dataset directory (client_fit_model.py:58-59). Here the coordinator (or an
offline tool) assigns disjoint shards: IID uniform, or non-IID with
per-client crack-density skew (BASELINE.md config 4: "non-IID client shards
(per-client crack-type skew) + FedProx mu>0").
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def partition_iid(
    n_samples: int, num_clients: int, seed: int = 0
) -> list[np.ndarray]:
    """Uniform random disjoint shards, near-equal sizes."""
    if num_clients <= 0:
        raise ValueError("num_clients must be positive")
    order = np.arange(n_samples)
    np.random.default_rng(seed).shuffle(order)
    return [np.sort(s) for s in np.array_split(order, num_clients)]


def partition_skew(
    scores: Sequence[float],
    num_clients: int,
    alpha: float = 0.3,
    seed: int = 0,
) -> list[np.ndarray]:
    """Non-IID shards skewed by a per-sample score (e.g. crack density).

    Samples are bucketed into ``num_clients`` score quantiles; a Dirichlet(α)
    mixing matrix assigns each bucket across clients, so small α → each client
    sees mostly one crack-density regime (heavy cracks vs hairline vs clean).
    Every sample lands on exactly one client; shards are disjoint and cover.
    """
    if num_clients <= 0:
        raise ValueError("num_clients must be positive")
    scores = np.asarray(scores, np.float64)
    n = scores.shape[0]
    rng = np.random.default_rng(seed)
    by_score = np.argsort(scores, kind="stable")
    buckets = np.array_split(by_score, num_clients)

    shards: list[list[int]] = [[] for _ in range(num_clients)]
    for b, bucket in enumerate(buckets):
        # proportions of this quantile bucket going to each client; biased
        # toward client b so α→0 degenerates to "client b owns quantile b"
        props = rng.dirichlet(np.full(num_clients, alpha) + (np.arange(num_clients) == b))
        counts = np.floor(props * len(bucket)).astype(int)
        counts[b] += len(bucket) - counts.sum()  # remainder to the home client
        perm = rng.permutation(bucket)
        start = 0
        for c in range(num_clients):
            shards[c].extend(perm[start : start + counts[c]].tolist())
            start += counts[c]
    # No shard may come out empty (an empty shard would silently drop a
    # client from the federation at startup): deterministically move one
    # sample from the largest shard until every shard has at least one, when
    # the dataset allows it.
    if n >= num_clients:
        while any(len(s) == 0 for s in shards):
            src = max(range(num_clients), key=lambda c: len(shards[c]))
            dst = next(c for c in range(num_clients) if not shards[c])
            shards[dst].append(shards[src].pop())
    return [np.sort(np.asarray(s, dtype=np.int64)) for s in shards]


def crack_density(masks: np.ndarray) -> np.ndarray:
    """Per-sample fraction of crack pixels — the default skew score."""
    masks = np.asarray(masks)
    return masks.reshape(masks.shape[0], -1).mean(axis=1)


def mask_density_scores(
    pairs: Sequence[tuple[str, str]], img_size: int = 64
) -> np.ndarray:
    """Crack-density score per (image, mask) pair, decoding masks only at a
    small size — the scoring pass for non-IID sharding over an on-disk
    dataset.

    Deliberately pinned to the PIL + first-party-native decode path (NOT the
    pipeline's cv2 fast path): every client must compute bit-identical
    scores or the uncoordinated shard assignment stops being disjoint, and
    cv2 vs PIL grayscale conversions can differ by a bit on some inputs.
    Decodes run on a thread pool — this is a startup pass over the whole
    train split."""
    from concurrent.futures import ThreadPoolExecutor

    from PIL import Image

    from fedcrack_tpu import native

    def score_one(pair):
        _, mask_path = pair
        mask = np.asarray(Image.open(mask_path).convert("L"))
        return float(native.resize_binarize(mask, img_size).mean())

    with ThreadPoolExecutor(max_workers=8) as pool:
        scores = list(pool.map(score_one, pairs))
    return np.asarray(scores, np.float64)


def shard_pairs(
    pairs: Sequence[tuple[str, str]],
    num_clients: int,
    client_index: int,
    partition: str = "iid",
    alpha: float = 0.3,
    seed: int = 0,
) -> list[tuple[str, str]]:
    """This client's shard of the pair list — the CLI-facing composition of
    the partitioners (every client process runs the same deterministic
    assignment and picks its own row, so shards are disjoint and cover
    without any coordination)."""
    if not 0 <= client_index < num_clients:
        raise ValueError(
            f"client_index {client_index} out of range for {num_clients} clients"
        )
    if num_clients == 1:
        return list(pairs)
    if partition == "iid":
        shards = partition_iid(len(pairs), num_clients, seed=seed)
    elif partition == "skew":
        shards = partition_skew(
            mask_density_scores(pairs), num_clients, alpha=alpha, seed=seed
        )
    else:
        raise ValueError(f"unknown partition {partition!r} (iid or skew)")
    return [pairs[i] for i in shards[client_index]]
