"""Kill→restart recovery drill: time the mid-round server crash path.

``python -m fedcrack_tpu.tools.chaos_drill --out drill.json``

The scripted scenario (deterministic, raw-RPC driven, tiny weights — no
JAX model, runs in seconds on any host):

1. boot a coordinator with a durable statefile (``FedConfig.state_path``),
2. enroll a 2-client cohort, deliver client A's round-1 update,
3. KILL the server with zero grace mid-round (client B still training),
4. boot a fresh coordinator over the same statefile,
5. deliver client B's update — the round must aggregate using A's update
   restored from disk, with the exact weighted average and an unbroken
   history prefix — then drive the remaining rounds to FIN.

Timings reported: ``restore_s`` (dead process → resumed state machine),
``kill_to_recover_s`` (kill instant → the interrupted round's aggregation),
and ``session_s``. bench.py embeds this via :func:`run_kill_restart_drill`
as ``detail.chaos_recovery``; tests/test_chaos.py pins the semantics
(identical history prefix, exact average) so the timing artifact can never
go green on wrong recovery.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes


def _vars(value: float):
    return {"params": {"w": np.full((4, 4), value, np.float32)}}


def _raw_caller(port: int):
    """One-message-per-call raw client (transport.edge.raw_caller — the
    same caller the edge tier's upstream relay is built on)."""
    from fedcrack_tpu.transport.edge import raw_caller

    return raw_caller(port)


def _ready(cname: str):
    from fedcrack_tpu.transport import transport_pb2 as pb

    msg = pb.ClientMessage(cname=cname)
    msg.ready.SetInParent()
    return msg


def _done(cname: str, rnd: int, value: float, ns: int):
    from fedcrack_tpu.transport import transport_pb2 as pb

    msg = pb.ClientMessage(cname=cname)
    msg.done.round = rnd
    msg.done.weights = tree_to_bytes(_vars(value))
    msg.done.sample_count = ns
    return msg


def _wait_for_statefile(path: str, config: FedConfig, pred, timeout_s: float = 10.0):
    """Poll the durable snapshot until ``pred(state)`` holds — the drill's
    kill must land AFTER the update it relies on has been made durable
    (a real kill races this too; the drill pins the recoverable side)."""
    from fedcrack_tpu.ckpt import load_state_file

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        state = load_state_file(path, config)
        if state is not None and pred(state):
            return state
        time.sleep(0.01)
    raise TimeoutError(f"statefile {path} never satisfied the predicate")


def run_kill_restart_drill(rounds: int = 3, workdir: str | None = None) -> dict:
    """The scripted scenario; returns the timing/verification artifact."""
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    ctx = (
        tempfile.TemporaryDirectory(prefix="chaos_drill_")
        if workdir is None
        else None
    )
    base = ctx.name if ctx is not None else workdir
    try:
        cfg = FedConfig(
            max_rounds=rounds,
            cohort_size=2,
            registration_window_s=5.0,
            round_deadline_s=60.0,  # backstop only; the drill never waits it out
            port=0,
            state_path=os.path.join(base, "server_state.msgpack"),
        )
        t_session = time.perf_counter()
        server1 = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
        with ServerThread(server1) as st1:
            channel, call = _raw_caller(st1.port)
            assert call(_ready("a")).status == R.SW
            assert call(_ready("b")).status == R.SW
            assert call(_done("a", 1, 1.0, 10)).status == R.RESP_ACY
            channel.close()
            # The kill must strike after A's update is durable.
            _wait_for_statefile(
                cfg.state_path, cfg, lambda s: "a" in s.received
            )
            t_kill = time.perf_counter()
            st1.kill()

        server2 = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
        resumed = server2.state
        t_restored = time.perf_counter()
        if not (
            resumed.phase == R.PHASE_RUNNING
            and resumed.current_round == 1
            and "a" in resumed.received
            and resumed.cohort == frozenset({"a", "b"})
        ):
            raise RuntimeError(
                f"restart did not resume the round: phase={resumed.phase} "
                f"round={resumed.current_round} received={sorted(resumed.received)}"
            )
        with ServerThread(server2) as st2:
            channel, call = _raw_caller(st2.port)
            rep = call(_done("b", 1, 3.0, 30))
            t_recovered = time.perf_counter()
            if rep.status != R.RESP_ARY:
                raise RuntimeError(f"recovery aggregation failed: {rep.status}")
            # Weighted average over BOTH updates — A's restored from disk:
            # (10*1 + 30*3) / 40 = 2.5.
            got = tree_from_bytes(rep.weights)["params"]["w"]
            avg_exact = bool(np.allclose(got, 2.5, atol=1e-6))
            for rnd in range(2, rounds + 1):
                call(_done("a", rnd, 1.0, 10))
                rep = call(_done("b", rnd, 3.0, 30))
            channel.close()
            state = st2.state
        history_rounds = [h["round"] for h in state.history]
        return {
            "rounds": rounds,
            "restore_s": round(t_restored - t_kill, 4),
            "kill_to_recover_s": round(t_recovered - t_kill, 4),
            "session_s": round(time.perf_counter() - t_session, 4),
            "resumed_mid_round": True,
            "received_preserved": True,
            "recovered_avg_exact": avg_exact,
            "finished": state.phase == R.PHASE_FINISHED,
            "history_rounds": history_rounds,
            "history_gapless": history_rounds
            == list(range(1, len(history_rounds) + 1)),
        }
    finally:
        if ctx is not None:
            ctx.cleanup()


def run_corrupt_frame_drill() -> dict:
    """CORRUPT_COMPRESSED_FRAME drill (round 12): a cohort uploading int8
    compressed frames where one client's frame takes a single bit-flip on
    the wire. The server must reject it on the frame CRC — BEFORE any
    reconstruction — log it to the round's ``rejected`` history map, and
    still close the round at quorum from the two clean frames. The
    aggregation result is checked EXACTLY against the weighted average of
    what decode_update reconstructs from the two clean frames (int8 encode
    is seeded, so frames and reconstructions are deterministic)."""
    from fedcrack_tpu.chaos.inject import _poison_weights
    from fedcrack_tpu.chaos.plan import CORRUPT_COMPRESSED_FRAME
    from fedcrack_tpu.compress import decode_update, get_codec, is_frame
    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    cfg = FedConfig(
        max_rounds=1,
        cohort_size=3,
        quorum_fraction=2.0 / 3.0,  # 2 of 3: the poisoned client must not stall it
        registration_window_s=5.0,
        round_deadline_s=60.0,
        update_codec="int8",
        port=0,
    )
    base_vars = _vars(0.0)
    server = FedServer(cfg, base_vars, tick_period_s=0.02)
    base_blob = server.state.broadcast_blob

    def framed(cname: str, value: float, ns: int, corrupt: bool) -> pb.ClientMessage:
        frame = get_codec("int8", client_tag=cname).encode_update(
            tree_to_bytes(_vars(value)), base_blob, round=1, base_version=0
        )
        assert is_frame(frame)
        if corrupt:
            frame = _poison_weights(frame, CORRUPT_COMPRESSED_FRAME)
        msg = pb.ClientMessage(cname=cname)
        msg.done.round = 1
        msg.done.weights = frame
        msg.done.sample_count = ns
        return msg

    t0 = time.perf_counter()
    with ServerThread(server) as st:
        channel, call = _raw_caller(st.port)
        for c in ("a", "b", "c"):
            assert call(_ready(c)).status == R.SW
        # The corrupt frame lands FIRST: rejection, not a stale-round resync.
        rej = call(framed("c", 9.0, 20, corrupt=True))
        rep_a = call(framed("a", 1.0, 10, corrupt=False))
        rep_b = call(framed("b", 3.0, 30, corrupt=False))
        t_quorum = time.perf_counter()
        channel.close()
        state = st.state
    got = tree_from_bytes(rep_b.weights)["params"]["w"]
    base_tree = tree_from_bytes(base_blob)
    dec = {}
    for cname, value in (("a", 1.0), ("b", 3.0)):
        frame = get_codec("int8", client_tag=cname).encode_update(
            tree_to_bytes(_vars(value)), base_blob, round=1, base_version=0
        )
        tree, _ = decode_update(
            frame, template=base_tree, base=base_tree, expected_base_version=0
        )
        dec[cname] = np.asarray(tree["params"]["w"], np.float32)
    want = (10 * dec["a"] + 30 * dec["b"]) / 40
    entry = state.history[0] if state.history else {}
    return {
        "corrupt_rejected": rej.status == R.REJECTED,
        "reject_reason_is_checksum": "checksum" in (
            entry.get("rejected", {}).get("c", "")
        ),
        "quorum_reached": rep_a.status == R.RESP_ACY
        and rep_b.status in (R.RESP_ARY, R.FIN),
        "clean_clients_aggregated": entry.get("clients") == ["a", "b"],
        "codecs": entry.get("codecs"),
        "wire_bytes_received": entry.get("bytes_received"),
        "decoded_bytes_received": entry.get("decoded_bytes_received"),
        "avg_matches_decoded_frames": bool(np.allclose(got, want, atol=1e-5)),
        "reject_to_quorum_s": round(t_quorum - t0, 4),
    }


def run_edge_crash_drill(workdir: str | None = None) -> dict:
    """EDGE_AGGREGATOR_CRASH drill (round 13): a 2-edge aggregation tree
    where one edge tier is KILLED mid-round — after 2 of its 3 leaves
    reported — and restarted from its statefile. The restarted edge must
    resume the SAME round with the already-received updates intact, accept
    the third leaf, close its K-of-N quorum, and push its partial to the
    root (a real gRPC FedServer) so the root round still closes — with the
    root average EXACTLY the sample-weighted mean over both edges'
    partials, and the recovered edge's partial EXACTLY the weighted mean
    of all three leaves (nothing lost to the crash). The scripted kill is
    scheduled and recorded through a chaos FaultPlan so the artifact
    proves the fault actually fired."""
    from fedcrack_tpu.chaos.plan import EDGE_AGGREGATOR_CRASH, Fault, FaultPlan
    from fedcrack_tpu.fed.tree import EdgeAggregator
    from fedcrack_tpu.transport.edge import EdgeRelay
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    ctx = (
        tempfile.TemporaryDirectory(prefix="edge_crash_drill_")
        if workdir is None
        else None
    )
    base = ctx.name if ctx is not None else workdir
    try:
        cfg = FedConfig(
            max_rounds=1,
            cohort_size=2,  # the ROOT's cohort is the two edges
            registration_window_s=5.0,
            round_deadline_s=60.0,
            port=0,
        )
        plan = FaultPlan(
            [Fault(kind=EDGE_AGGREGATOR_CRASH, round=1, client="edge-0")]
        )
        t0 = time.perf_counter()
        root = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
        template = root.state.template
        with ServerThread(root) as st:
            relay0 = EdgeRelay("edge-0", st.port)
            relay1 = EdgeRelay("edge-1", st.port)
            h0 = relay0.enroll()
            relay1.enroll()
            base_blob = relay0.pull()
            base_version = int(h0["model_version"])
            round_no = int(h0["current_round"])

            state_path = os.path.join(base, "edge-0.msgpack")
            edge0 = EdgeAggregator(
                "edge-0", template, quorum_fraction=1.0, state_path=state_path
            )
            edge0.begin_round(
                round_no, base_blob, base_version, ["a", "b", "c"]
            )
            assert edge0.offer("a", tree_to_bytes(_vars(1.0)), 10)[0]
            assert edge0.offer("b", tree_to_bytes(_vars(2.0)), 10)[0]
            # KILL edge-0 mid-round (leaf c still training): drop the
            # in-memory aggregator; durable state is whatever the atomic
            # writer had renamed.
            assert plan.take(EDGE_AGGREGATOR_CRASH, client="edge-0", round=round_no)
            t_kill = time.perf_counter()
            del edge0

            restored = EdgeAggregator.restore(
                state_path, template, quorum_fraction=1.0
            )
            t_restored = time.perf_counter()
            if restored is None or sorted(restored.received) != ["a", "b"]:
                raise RuntimeError("edge restart did not resume from its statefile")
            resumed_mid_round = (
                restored.round == round_no
                and restored.base_version == base_version
            )
            assert restored.offer("c", tree_to_bytes(_vars(6.0)), 20)[0]
            assert restored.quorum_met()
            partial0, total0 = restored.partial()
            status0, _, _ = relay0.push_partial(round_no, partial0, total0)

            edge1 = EdgeAggregator("edge-1", template, quorum_fraction=1.0)
            edge1.begin_round(round_no, base_blob, base_version, ["d"])
            assert edge1.offer("d", tree_to_bytes(_vars(8.0)), 40)[0]
            partial1, total1 = edge1.partial()
            status1, new_global, _ = relay1.push_partial(round_no, partial1, total1)
            t_recovered = time.perf_counter()
            relay0.close()
            relay1.close()
            state = st.state
        # edge-0's partial: (10*1 + 10*2 + 20*6) / 40 = 3.75 — A and B
        # restored from disk, C delivered post-restart.
        p0 = tree_from_bytes(partial0)["params"]["w"]
        # root: (40*3.75 + 40*8) / 80 = 5.875.
        got = tree_from_bytes(new_global)["params"]["w"]
        entry = state.history[0] if state.history else {}
        return {
            "fault_fired": [f.kind for f in plan.triggered] == [EDGE_AGGREGATOR_CRASH],
            "resumed_mid_round": bool(resumed_mid_round),
            "received_preserved": True,
            "edge_partial_exact": bool(np.allclose(p0, 3.75, atol=1e-6)),
            "root_round_closed": status0 == R.RESP_ACY
            and status1 in (R.RESP_ARY, R.FIN),
            "root_avg_exact": bool(np.allclose(got, 5.875, atol=1e-6)),
            "root_clients": entry.get("clients"),
            "root_cohort_size": entry.get("cohort_size"),
            "restore_s": round(t_restored - t_kill, 4),
            "kill_to_recover_s": round(t_recovered - t_kill, 4),
            "session_s": round(time.perf_counter() - t0, 4),
        }
    finally:
        if ctx is not None:
            ctx.cleanup()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--rounds", type=int, default=3)
    args = p.parse_args(argv)
    artifact = {
        "generated_by": "fedcrack_tpu.tools.chaos_drill",
        "kill_restart": run_kill_restart_drill(rounds=args.rounds),
        "corrupt_frame": run_corrupt_frame_drill(),
        "edge_crash": run_edge_crash_drill(),
    }
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
    print(json.dumps(artifact["kill_restart"]), flush=True)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
