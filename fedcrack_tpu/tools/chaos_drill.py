"""Kill→restart recovery drill: time the mid-round server crash path.

``python -m fedcrack_tpu.tools.chaos_drill --out drill.json``

The scripted scenario (deterministic, raw-RPC driven, tiny weights — no
JAX model, runs in seconds on any host):

1. boot a coordinator with a durable statefile (``FedConfig.state_path``),
2. enroll a 2-client cohort, deliver client A's round-1 update,
3. KILL the server with zero grace mid-round (client B still training),
4. boot a fresh coordinator over the same statefile,
5. deliver client B's update — the round must aggregate using A's update
   restored from disk, with the exact weighted average and an unbroken
   history prefix — then drive the remaining rounds to FIN.

Timings reported: ``restore_s`` (dead process → resumed state machine),
``kill_to_recover_s`` (kill instant → the interrupted round's aggregation),
and ``session_s``. bench.py embeds this via :func:`run_kill_restart_drill`
as ``detail.chaos_recovery``; tests/test_chaos.py pins the semantics
(identical history prefix, exact average) so the timing artifact can never
go green on wrong recovery.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

from fedcrack_tpu.configs import FedConfig
from fedcrack_tpu.fed import rounds as R
from fedcrack_tpu.fed.serialization import tree_from_bytes, tree_to_bytes


def _vars(value: float):
    return {"params": {"w": np.full((4, 4), value, np.float32)}}


def _raw_caller(port: int):
    """One-message-per-call raw client (transport.edge.raw_caller — the
    same caller the edge tier's upstream relay is built on)."""
    from fedcrack_tpu.transport.edge import raw_caller

    return raw_caller(port)


def _ready(cname: str):
    from fedcrack_tpu.transport import transport_pb2 as pb

    msg = pb.ClientMessage(cname=cname)
    msg.ready.SetInParent()
    return msg


def _done(cname: str, rnd: int, value: float, ns: int):
    from fedcrack_tpu.transport import transport_pb2 as pb

    msg = pb.ClientMessage(cname=cname)
    msg.done.round = rnd
    msg.done.weights = tree_to_bytes(_vars(value))
    msg.done.sample_count = ns
    return msg


def _wait_for_statefile(path: str, config: FedConfig, pred, timeout_s: float = 10.0):
    """Poll the durable snapshot until ``pred(state)`` holds — the drill's
    kill must land AFTER the update it relies on has been made durable
    (a real kill races this too; the drill pins the recoverable side)."""
    from fedcrack_tpu.ckpt import load_state_file

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        state = load_state_file(path, config)
        if state is not None and pred(state):
            return state
        time.sleep(0.01)
    raise TimeoutError(f"statefile {path} never satisfied the predicate")


def run_kill_restart_drill(rounds: int = 3, workdir: str | None = None) -> dict:
    """The scripted scenario; returns the timing/verification artifact."""
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    ctx = (
        tempfile.TemporaryDirectory(prefix="chaos_drill_")
        if workdir is None
        else None
    )
    base = ctx.name if ctx is not None else workdir
    try:
        cfg = FedConfig(
            max_rounds=rounds,
            cohort_size=2,
            registration_window_s=5.0,
            round_deadline_s=60.0,  # backstop only; the drill never waits it out
            port=0,
            state_path=os.path.join(base, "server_state.msgpack"),
        )
        t_session = time.perf_counter()
        server1 = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
        with ServerThread(server1) as st1:
            channel, call = _raw_caller(st1.port)
            assert call(_ready("a")).status == R.SW
            assert call(_ready("b")).status == R.SW
            assert call(_done("a", 1, 1.0, 10)).status == R.RESP_ACY
            channel.close()
            # The kill must strike after A's update is durable.
            _wait_for_statefile(
                cfg.state_path, cfg, lambda s: "a" in s.received
            )
            t_kill = time.perf_counter()
            st1.kill()

        server2 = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
        resumed = server2.state
        t_restored = time.perf_counter()
        if not (
            resumed.phase == R.PHASE_RUNNING
            and resumed.current_round == 1
            and "a" in resumed.received
            and resumed.cohort == frozenset({"a", "b"})
        ):
            raise RuntimeError(
                f"restart did not resume the round: phase={resumed.phase} "
                f"round={resumed.current_round} received={sorted(resumed.received)}"
            )
        with ServerThread(server2) as st2:
            channel, call = _raw_caller(st2.port)
            rep = call(_done("b", 1, 3.0, 30))
            t_recovered = time.perf_counter()
            if rep.status != R.RESP_ARY:
                raise RuntimeError(f"recovery aggregation failed: {rep.status}")
            # Weighted average over BOTH updates — A's restored from disk:
            # (10*1 + 30*3) / 40 = 2.5.
            got = tree_from_bytes(rep.weights)["params"]["w"]
            avg_exact = bool(np.allclose(got, 2.5, atol=1e-6))
            for rnd in range(2, rounds + 1):
                call(_done("a", rnd, 1.0, 10))
                rep = call(_done("b", rnd, 3.0, 30))
            channel.close()
            state = st2.state
        history_rounds = [h["round"] for h in state.history]
        return {
            "rounds": rounds,
            "restore_s": round(t_restored - t_kill, 4),
            "kill_to_recover_s": round(t_recovered - t_kill, 4),
            "session_s": round(time.perf_counter() - t_session, 4),
            "resumed_mid_round": True,
            "received_preserved": True,
            "recovered_avg_exact": avg_exact,
            "finished": state.phase == R.PHASE_FINISHED,
            "history_rounds": history_rounds,
            "history_gapless": history_rounds
            == list(range(1, len(history_rounds) + 1)),
        }
    finally:
        if ctx is not None:
            ctx.cleanup()


def run_corrupt_frame_drill() -> dict:
    """CORRUPT_COMPRESSED_FRAME drill (round 12): a cohort uploading int8
    compressed frames where one client's frame takes a single bit-flip on
    the wire. The server must reject it on the frame CRC — BEFORE any
    reconstruction — log it to the round's ``rejected`` history map, and
    still close the round at quorum from the two clean frames. The
    aggregation result is checked EXACTLY against the weighted average of
    what decode_update reconstructs from the two clean frames (int8 encode
    is seeded, so frames and reconstructions are deterministic)."""
    from fedcrack_tpu.chaos.inject import _poison_weights
    from fedcrack_tpu.chaos.plan import CORRUPT_COMPRESSED_FRAME
    from fedcrack_tpu.compress import decode_update, get_codec, is_frame
    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    cfg = FedConfig(
        max_rounds=1,
        cohort_size=3,
        quorum_fraction=2.0 / 3.0,  # 2 of 3: the poisoned client must not stall it
        registration_window_s=5.0,
        round_deadline_s=60.0,
        update_codec="int8",
        port=0,
    )
    base_vars = _vars(0.0)
    server = FedServer(cfg, base_vars, tick_period_s=0.02)
    base_blob = server.state.broadcast_blob

    def framed(cname: str, value: float, ns: int, corrupt: bool) -> pb.ClientMessage:
        frame = get_codec("int8", client_tag=cname).encode_update(
            tree_to_bytes(_vars(value)), base_blob, round=1, base_version=0
        )
        assert is_frame(frame)
        if corrupt:
            frame = _poison_weights(frame, CORRUPT_COMPRESSED_FRAME)
        msg = pb.ClientMessage(cname=cname)
        msg.done.round = 1
        msg.done.weights = frame
        msg.done.sample_count = ns
        return msg

    t0 = time.perf_counter()
    with ServerThread(server) as st:
        channel, call = _raw_caller(st.port)
        for c in ("a", "b", "c"):
            assert call(_ready(c)).status == R.SW
        # The corrupt frame lands FIRST: rejection, not a stale-round resync.
        rej = call(framed("c", 9.0, 20, corrupt=True))
        rep_a = call(framed("a", 1.0, 10, corrupt=False))
        rep_b = call(framed("b", 3.0, 30, corrupt=False))
        t_quorum = time.perf_counter()
        channel.close()
        state = st.state
    got = tree_from_bytes(rep_b.weights)["params"]["w"]
    base_tree = tree_from_bytes(base_blob)
    dec = {}
    for cname, value in (("a", 1.0), ("b", 3.0)):
        frame = get_codec("int8", client_tag=cname).encode_update(
            tree_to_bytes(_vars(value)), base_blob, round=1, base_version=0
        )
        tree, _ = decode_update(
            frame, template=base_tree, base=base_tree, expected_base_version=0
        )
        dec[cname] = np.asarray(tree["params"]["w"], np.float32)
    want = (10 * dec["a"] + 30 * dec["b"]) / 40
    entry = state.history[0] if state.history else {}
    return {
        "corrupt_rejected": rej.status == R.REJECTED,
        "reject_reason_is_checksum": "checksum" in (
            entry.get("rejected", {}).get("c", "")
        ),
        "quorum_reached": rep_a.status == R.RESP_ACY
        and rep_b.status in (R.RESP_ARY, R.FIN),
        "clean_clients_aggregated": entry.get("clients") == ["a", "b"],
        "codecs": entry.get("codecs"),
        "wire_bytes_received": entry.get("bytes_received"),
        "decoded_bytes_received": entry.get("decoded_bytes_received"),
        "avg_matches_decoded_frames": bool(np.allclose(got, want, atol=1e-5)),
        "reject_to_quorum_s": round(t_quorum - t0, 4),
    }


def run_edge_crash_drill(workdir: str | None = None) -> dict:
    """EDGE_AGGREGATOR_CRASH drill (round 13): a 2-edge aggregation tree
    where one edge tier is KILLED mid-round — after 2 of its 3 leaves
    reported — and restarted from its statefile. The restarted edge must
    resume the SAME round with the already-received updates intact, accept
    the third leaf, close its K-of-N quorum, and push its partial to the
    root (a real gRPC FedServer) so the root round still closes — with the
    root average EXACTLY the sample-weighted mean over both edges'
    partials, and the recovered edge's partial EXACTLY the weighted mean
    of all three leaves (nothing lost to the crash). The scripted kill is
    scheduled and recorded through a chaos FaultPlan so the artifact
    proves the fault actually fired."""
    from fedcrack_tpu.chaos.plan import EDGE_AGGREGATOR_CRASH, Fault, FaultPlan
    from fedcrack_tpu.fed.tree import EdgeAggregator
    from fedcrack_tpu.transport.edge import EdgeRelay
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    ctx = (
        tempfile.TemporaryDirectory(prefix="edge_crash_drill_")
        if workdir is None
        else None
    )
    base = ctx.name if ctx is not None else workdir
    try:
        cfg = FedConfig(
            max_rounds=1,
            cohort_size=2,  # the ROOT's cohort is the two edges
            registration_window_s=5.0,
            round_deadline_s=60.0,
            port=0,
        )
        plan = FaultPlan(
            [Fault(kind=EDGE_AGGREGATOR_CRASH, round=1, client="edge-0")]
        )
        t0 = time.perf_counter()
        root = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
        template = root.state.template
        with ServerThread(root) as st:
            relay0 = EdgeRelay("edge-0", st.port)
            relay1 = EdgeRelay("edge-1", st.port)
            h0 = relay0.enroll()
            relay1.enroll()
            base_blob = relay0.pull()
            base_version = int(h0["model_version"])
            round_no = int(h0["current_round"])

            state_path = os.path.join(base, "edge-0.msgpack")
            edge0 = EdgeAggregator(
                "edge-0", template, quorum_fraction=1.0, state_path=state_path
            )
            edge0.begin_round(
                round_no, base_blob, base_version, ["a", "b", "c"]
            )
            assert edge0.offer("a", tree_to_bytes(_vars(1.0)), 10)[0]
            assert edge0.offer("b", tree_to_bytes(_vars(2.0)), 10)[0]
            # KILL edge-0 mid-round (leaf c still training): drop the
            # in-memory aggregator; durable state is whatever the atomic
            # writer had renamed.
            assert plan.take(EDGE_AGGREGATOR_CRASH, client="edge-0", round=round_no)
            t_kill = time.perf_counter()
            del edge0

            restored = EdgeAggregator.restore(
                state_path, template, quorum_fraction=1.0
            )
            t_restored = time.perf_counter()
            if restored is None or sorted(restored.received) != ["a", "b"]:
                raise RuntimeError("edge restart did not resume from its statefile")
            resumed_mid_round = (
                restored.round == round_no
                and restored.base_version == base_version
            )
            assert restored.offer("c", tree_to_bytes(_vars(6.0)), 20)[0]
            assert restored.quorum_met()
            partial0, total0 = restored.partial()
            status0, _, _ = relay0.push_partial(round_no, partial0, total0)

            edge1 = EdgeAggregator("edge-1", template, quorum_fraction=1.0)
            edge1.begin_round(round_no, base_blob, base_version, ["d"])
            assert edge1.offer("d", tree_to_bytes(_vars(8.0)), 40)[0]
            partial1, total1 = edge1.partial()
            status1, new_global, _ = relay1.push_partial(round_no, partial1, total1)
            t_recovered = time.perf_counter()
            relay0.close()
            relay1.close()
            state = st.state
        # edge-0's partial: (10*1 + 10*2 + 20*6) / 40 = 3.75 — A and B
        # restored from disk, C delivered post-restart.
        p0 = tree_from_bytes(partial0)["params"]["w"]
        # root: (40*3.75 + 40*8) / 80 = 5.875.
        got = tree_from_bytes(new_global)["params"]["w"]
        entry = state.history[0] if state.history else {}
        return {
            "fault_fired": [f.kind for f in plan.triggered] == [EDGE_AGGREGATOR_CRASH],
            "resumed_mid_round": bool(resumed_mid_round),
            "received_preserved": True,
            "edge_partial_exact": bool(np.allclose(p0, 3.75, atol=1e-6)),
            "root_round_closed": status0 == R.RESP_ACY
            and status1 in (R.RESP_ARY, R.FIN),
            "root_avg_exact": bool(np.allclose(got, 5.875, atol=1e-6)),
            "root_clients": entry.get("clients"),
            "root_cohort_size": entry.get("cohort_size"),
            "restore_s": round(t_restored - t_kill, 4),
            "kill_to_recover_s": round(t_recovered - t_kill, 4),
            "session_s": round(time.perf_counter() - t0, 4),
        }
    finally:
        if ctx is not None:
            ctx.cleanup()


def _poll(cname: str, model_version: int, rnd: int):
    from fedcrack_tpu.transport import transport_pb2 as pb

    msg = pb.ClientMessage(cname=cname)
    msg.poll.model_version = model_version
    msg.poll.round = rnd
    return msg


def _pull(cname: str):
    from fedcrack_tpu.transport import transport_pb2 as pb

    msg = pb.ClientMessage(cname=cname)
    msg.pull.SetInParent()
    return msg


def run_straggler_storm_drill(
    seed: int = 0,
    n_clients: int = 6,
    versions: int = 3,
    buffer_k: int = 2,
    staleness_alpha: float = 0.5,
) -> dict:
    """STRAGGLER_STORM drill (round 14): the sync-vs-buffered A/B under ONE
    seeded heavy-tail delay schedule (``FaultPlan.storm`` — both arms
    replay the identical per-(client, iteration) delays).

    - SYNC arm: the barrier round machine; every round's wall is the
      cohort's MAX delay (the failure mode the async plane exists for).
    - BUFFERED arm: FedBuff — the server flushes on the ``buffer_k``
      fastest arrivals, staleness-weighting the stragglers' late updates
      instead of waiting on them.

    Decision metrics (the ROADMAP async item's): sustained accepted
    updates/sec and global versions/min at EQUAL WALL — the sync arm runs
    ``versions`` barrier rounds, then the buffered arm runs for that same
    wall-clock window and we count what it ingested/flushed in it (a
    buffered server never idles waiting on a straggler, so equal-versions
    would cap its throughput at K x versions while the stragglers are
    still sleeping — "sustained" is a rate, measured over a window). The
    returned artifact carries both arms plus the strict comparison bools
    the acceptance gate reads.

    Round 15: each arm's counts come from SCRAPING the live metric
    registry over a real ``/metrics`` HTTP endpoint (before/after sample
    deltas of ``fed_updates_total{result="accepted"}`` and
    ``fed_global_versions_total``) — and each arm pins its scraped deltas
    against the protocol history (``scrape_matches_history``), so the A/B
    rates a dashboard would show and the rates this artifact reports are
    the SAME numbers by construction, not parallel bookkeeping."""
    import threading

    from fedcrack_tpu.chaos.plan import (
        STRAGGLER_DELAY,
        STRAGGLER_STORM,
        FaultPlan,
    )
    from fedcrack_tpu.fed.buffered import async_summary
    from fedcrack_tpu.obs.promexp import MetricsExporter, sample_value, scrape
    from fedcrack_tpu.obs.registry import REGISTRY
    from fedcrack_tpu.transport.codec import decode_scalar_map
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    def fed_counters(url: str) -> dict:
        """One scrape, reduced to the two A/B series (absent -> 0: the
        registry only materializes a family at its first bump)."""
        parsed = scrape(url)
        return {
            "accepted": sample_value(
                parsed, "fed_updates_total", {"result": "accepted"}
            ) or 0.0,
            "versions": sample_value(parsed, "fed_global_versions_total") or 0.0,
        }

    names = [f"c{i}" for i in range(n_clients)]
    # One schedule, two arms: the delay dicts are read WITHOUT consuming
    # (plan.take is single-threaded-per-target; N drill threads share the
    # schedule), the storm MARKER is consumed so `triggered` proves the
    # storm actually fired.
    plan = FaultPlan.storm(
        seed,
        clients=names,
        n_iterations=versions * 4,
        # Heavy enough that the per-round MAX over the cohort (what the
        # sync barrier serializes on) dwarfs the K fastest draws (what a
        # buffered flush waits for) — the regime the async plane targets.
        tail_alpha=1.1,
        scale_s=0.03,
        cap_s=0.8,
    )
    assert plan.take(STRAGGLER_STORM, round=1) is not None
    delays = {
        (f.client, f.round): f.delay_s
        for f in plan.pending
        if f.kind == STRAGGLER_DELAY
    }

    def run_sync(url: str) -> dict:
        cfg = FedConfig(
            max_rounds=versions,
            cohort_size=n_clients,
            registration_window_s=5.0,
            round_deadline_s=60.0,
            port=0,
        )
        server = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
        errors: list[str] = []

        def client(name: str):
            channel, call = _raw_caller(server_thread.port)
            try:
                assert call(_ready(name)).status == R.SW
                rnd, mv = 1, 0
                for it in range(1, versions + 1):
                    time.sleep(delays[(name, it)])
                    rep = call(_done(name, rnd, 1.0 + it, 10))
                    if rep.status == R.RESP_ACY:
                        # The barrier: poll until the round closes behind
                        # the slowest client.
                        while True:
                            time.sleep(0.01)
                            rep = call(_poll(name, mv, rnd))
                            if rep.status != R.WAIT:
                                break
                    if rep.status == R.FIN:
                        return
                    c = decode_scalar_map(rep.config)
                    rnd, mv = int(c["current_round"]), int(c["model_version"])
            except Exception as e:  # surfaced in the artifact, never silent
                errors.append(f"{name}: {e!r}")
            finally:
                channel.close()

        pre = fed_counters(url)
        t0 = time.perf_counter()
        with ServerThread(server) as server_thread:
            threads = [
                threading.Thread(target=client, args=(n,)) for n in names
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            wall = time.perf_counter() - t0
            state = server_thread.state
        post = fed_counters(url)
        # The arm's counts come from the SCRAPE (before/after deltas of the
        # live registry over HTTP); the protocol history is the cross-check.
        n_accepted = int(post["accepted"] - pre["accepted"])
        n_versions = int(post["versions"] - pre["versions"])
        return {
            "wall_s": round(wall, 4),
            "accepted_updates": n_accepted,
            "global_versions": n_versions,
            "updates_per_sec": round(n_accepted / wall, 3),
            "versions_per_min": round(n_versions / wall * 60.0, 3),
            "scrape_matches_history": (
                n_accepted == sum(len(h["clients"]) for h in state.history)
                and n_versions == int(state.model_version)
            ),
            "errors": errors,
        }

    def run_buffered(window_s: float, url: str) -> dict:
        cfg = FedConfig(
            # A horizon the window can never reach: the drill measures the
            # SUSTAINED rate over `window_s`, not time-to-N-versions.
            max_rounds=100_000,
            cohort_size=n_clients,
            mode="buffered",
            buffer_k=buffer_k,
            staleness_alpha=staleness_alpha,
            max_staleness=8,
            registration_window_s=5.0,
            round_deadline_s=60.0,
            port=0,
        )
        server = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
        errors: list[str] = []
        stop = threading.Event()
        n_sched = versions * 4

        def client(name: str):
            channel, call = _raw_caller(server_thread.port)
            try:
                assert call(_ready(name)).status == R.SW
                it = 0
                while not stop.is_set():
                    it += 1
                    rep = call(_pull(name))
                    c = decode_scalar_map(rep.config)
                    # The same schedule, consumed cyclically past the sync
                    # arm's horizon (the window outlives `versions`
                    # iterations for fast clients — that is the point).
                    time.sleep(delays[(name, (it - 1) % n_sched + 1)])
                    if stop.is_set():
                        return
                    call(_done(name, int(c["current_round"]), 1.0 + it, 10))
            except Exception as e:
                errors.append(f"{name}: {e!r}")
            finally:
                channel.close()

        pre = fed_counters(url)
        t0 = time.perf_counter()
        with ServerThread(server) as server_thread:
            threads = [
                threading.Thread(target=client, args=(n,)) for n in names
            ]
            for t in threads:
                t.start()
            time.sleep(window_s)
            # Measure AT the window edge: in-flight sleeps past it must not
            # count (the rates divide by window_s). Scrape-sandwich the
            # state snapshot — two identical scrapes bracketing the read
            # prove no update landed mid-measurement, so the scraped deltas
            # and the history describe the SAME instant.
            for _ in range(200):
                post = fed_counters(url)
                state = server_thread.state
                if fed_counters(url) == post:
                    break
            stop.set()
            for t in threads:
                t.join(timeout=60)
        summary = async_summary(state.history)
        n_accepted = int(post["accepted"] - pre["accepted"])
        n_versions = int(post["versions"] - pre["versions"])
        return {
            "wall_s": round(window_s, 4),
            "accepted_updates": n_accepted,
            "global_versions": n_versions,
            "updates_per_sec": round(n_accepted / window_s, 3),
            "versions_per_min": round(n_versions / window_s * 60.0, 3),
            "scrape_matches_history": (
                n_accepted
                == int(summary["accepted_updates"]) + len(state.buffer)
                and n_versions == int(state.model_version)
            ),
            "staleness": summary["staleness"],
            "mean_buffer_fill": summary["mean_buffer_fill"],
            "errors": errors,
        }

    with MetricsExporter(REGISTRY) as exporter:
        sync = run_sync(exporter.url)
        buffered = run_buffered(sync["wall_s"], exporter.url)
    return {
        "rates_scraped_from_registry": True,
        "seed": seed,
        "n_clients": n_clients,
        "versions": versions,
        "buffer_k": buffer_k,
        "staleness_alpha": staleness_alpha,
        "storm_fired": [f.kind for f in plan.triggered] == [STRAGGLER_STORM],
        "sync": sync,
        "buffered": buffered,
        # The ROADMAP decision points, read by the acceptance gate: same
        # fault plan, strictly more sustained updates/sec AND global
        # versions/min in buffered mode.
        "buffered_gt_sync_updates_per_sec": (
            buffered["updates_per_sec"] > sync["updates_per_sec"]
        ),
        "buffered_gt_sync_versions_per_min": (
            buffered["versions_per_min"] > sync["versions_per_min"]
        ),
    }


def run_buffered_kill_drill(workdir: str | None = None) -> dict:
    """Buffered-mode mid-BUFFER server kill→restart drill (round 14): a
    3-client buffered federation (``buffer_k=3``), two of three updates
    accepted into the buffer, server KILLED with zero grace, restarted
    over the same statefile, third update delivered — the flush must land
    on the BIT-IDENTICAL next global version an unkilled twin produces
    (same buffer contents, same sorted fold, same bytes)."""
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    ctx = (
        tempfile.TemporaryDirectory(prefix="buffered_kill_drill_")
        if workdir is None
        else None
    )
    base = ctx.name if ctx is not None else workdir
    try:
        def cfg_for(state_path: str) -> FedConfig:
            return FedConfig(
                max_rounds=1,
                cohort_size=3,
                mode="buffered",
                buffer_k=3,
                staleness_alpha=0.5,
                max_staleness=4,
                registration_window_s=5.0,
                round_deadline_s=60.0,
                port=0,
                state_path=state_path,
            )

        def drive(call):
            for c in ("a", "b", "c"):
                assert call(_ready(c)).status == R.SW
            for c in ("a", "b", "c"):
                call(_pull(c))

        # Twin 1: uninterrupted.
        cfg1 = cfg_for(os.path.join(base, "twin.msgpack"))
        server1 = FedServer(cfg1, _vars(0.0), tick_period_s=0.02)
        with ServerThread(server1) as st:
            channel, call = _raw_caller(st.port)
            drive(call)
            call(_done("a", 1, 1.0, 10))
            call(_done("b", 1, 3.0, 30))
            rep = call(_done("c", 1, 6.0, 20))
            channel.close()
            twin_status = rep.status
            twin_blob = bytes(rep.weights)
            twin_version = st.state.model_version

        # Twin 2: killed mid-buffer.
        cfg2 = cfg_for(os.path.join(base, "killed.msgpack"))
        server2 = FedServer(cfg2, _vars(0.0), tick_period_s=0.02)
        with ServerThread(server2) as st:
            channel, call = _raw_caller(st.port)
            drive(call)
            call(_done("a", 1, 1.0, 10))
            call(_done("b", 1, 3.0, 30))
            channel.close()
            # The kill must strike after both buffer entries AND c's pull
            # record are durable (c's framed/raw base is pinned to it).
            _wait_for_statefile(
                cfg2.state_path,
                cfg2,
                lambda s: len(s.buffer) == 2 and "c" in s.pulled,
            )
            t_kill = time.perf_counter()
            st.kill()

        server3 = FedServer(cfg2, _vars(0.0), tick_period_s=0.02)
        resumed = server3.state
        t_restored = time.perf_counter()
        resumed_mid_buffer = (
            len(resumed.buffer) == 2
            and sorted(e["cname"] for e in resumed.buffer) == ["a", "b"]
            and resumed.pulled.get("c") == 0
        )
        if not resumed_mid_buffer:
            raise RuntimeError(
                f"restart did not resume the buffer: "
                f"{[e['cname'] for e in resumed.buffer]} pulled={dict(resumed.pulled)}"
            )
        with ServerThread(server3) as st:
            channel, call = _raw_caller(st.port)
            rep = call(_done("c", 1, 6.0, 20))
            t_recovered = time.perf_counter()
            channel.close()
            killed_blob = bytes(rep.weights)
            killed_version = st.state.model_version
        return {
            "resumed_mid_buffer": True,
            "twin_flush_status": twin_status,
            "recovered_flush_status": rep.status,
            "global_version_identical": killed_version == twin_version,
            "global_blob_bit_identical": killed_blob == twin_blob,
            "restore_s": round(t_restored - t_kill, 4),
            "kill_to_recover_s": round(t_recovered - t_kill, 4),
        }
    finally:
        if ctx is not None:
            ctx.cleanup()


def run_replica_crash_drill() -> dict:
    """Serve-fleet replica-crash drill (round 17, SERVE_REPLICA_CRASH).

    A 2-replica fleet (tiny model, shared engine) under concurrent load:
    one replica is killed mid-load with requests still queued on it — the
    router drains that queue to the survivor WITH the original futures, so
    every accepted request answers (zero drops). Then the fleet-wide
    two-phase swap is driven on the surviving topology and must land: every
    post-commit request answers from the new version (zero torn versions on
    a degraded fleet). The kill is scheduled and consumed through a chaos
    FaultPlan so the artifact proves it fired."""
    import threading

    import jax

    from fedcrack_tpu.chaos.plan import SERVE_REPLICA_CRASH, Fault, FaultPlan
    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve.fleet import ServeFleet

    model_config = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    serve_config = ServeConfig(
        bucket_sizes=(16,),
        max_batch=4,
        max_delay_ms=30.0,
        tile_overlap=4,
        replicas=2,
    )
    v0 = init_variables(jax.random.key(0), model_config)
    v1 = init_variables(jax.random.key(1), model_config)
    plan = FaultPlan([Fault(kind=SERVE_REPLICA_CRASH, round=1)])

    class _SlowBatches:
        """Batcher chaos hook stretching every dispatch, so a queued
        BACKLOG provably exists on the victim at kill time (a tiny CPU
        model would otherwise drain its queue before the kill lands and
        the reroute path would go untested)."""

        def on_batch(self, bucket, batch_index, attempt):
            time.sleep(0.08)

    fleet = ServeFleet(
        model_config, serve_config, v0, initial_version=0, chaos=_SlowBatches()
    )
    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (16, 16, 3), dtype=np.uint8)
    t_start = time.perf_counter()
    try:
        # Phase 1: a burst wide enough that BOTH replicas hold queued work
        # (least-outstanding routing alternates them), submitted from
        # threads like real front-door traffic.
        n_burst = 24
        futures = []
        fut_lock = threading.Lock()

        def submit_some(n):
            for _ in range(n):
                f = fleet.submit(img)
                with fut_lock:
                    futures.append(f)

        threads = [
            threading.Thread(target=submit_some, args=(n_burst // 4,))
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Phase 2: the scheduled crash — consumed from the plan (the
        # artifact's proof it fired), executed by the router's kill path.
        fault = plan.take(SERVE_REPLICA_CRASH, round=1)
        assert fault is not None
        victim = 1
        t_kill = time.perf_counter()
        reroute = fleet.router.kill_replica(victim)
        # Phase 3: every accepted request answers (original futures).
        results = [f.result(timeout=60) for f in futures]
        answered = len(results)
        # Phase 4: the fleet swap still lands on the degraded fleet.
        installed = fleet.install(1, v1)
        post = [fleet.submit(img) for _ in range(4)]
        post_versions = sorted({f.result(timeout=60).model_version for f in post})
        stats = fleet.router.stats()
        return {
            "replicas": serve_config.replicas,
            "burst": n_burst,
            "fault_fired": fault.kind,
            "victim": victim,
            "rerouted": reroute["rerouted"],
            "reroute_failed": reroute["failed"],
            "answered": answered,
            "dropped": n_burst - answered,
            "zero_dropped": answered == n_burst,
            "live_after_kill": stats["live"],
            "swap_installed": installed,
            "post_swap_versions": post_versions,
            "swap_landed_untorn": installed and post_versions == [1],
            "swap_pause_ms": (fleet.manager.last_swap or {}).get("pause_ms"),
            "kill_to_drained_s": round(time.perf_counter() - t_kill, 3),
            "drill_s": round(time.perf_counter() - t_start, 3),
        }
    finally:
        fleet.close()


def run_elastic_fleet_drill() -> dict:
    """Elastic-fleet drill (round 22): REPLICA_CRASH_DURING_SCALE +
    SHADOW_REPLICA_CRASH.

    Part A — crash racing a scale-down: a 3-replica fleet under threaded
    load; the autoscaler's scale-down (drains the highest-index replica)
    races a concurrent crash of ANOTHER replica — two drains contend on
    one router, and the pin is that every accepted request still answers
    with its original future (zero drops), exactly the r17 discipline.

    Part B — dying shadow lane: while a candidate stages on the shadow
    mirror under live traffic, the shadow batcher is killed mid-staging.
    Pins: every production request answers (the shadow has no wire path to
    clients), zero sheds attributable to the shadow, and the verdict
    degrades to a LOUD rollback (a lane that answered nothing can never be
    promoted). Both faults are scheduled and consumed through a chaos
    FaultPlan so the artifact proves they fired."""
    import threading

    import jax

    from fedcrack_tpu.chaos.plan import (
        REPLICA_CRASH_DURING_SCALE,
        SHADOW_REPLICA_CRASH,
        Fault,
        FaultPlan,
    )
    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.serve.autoscaler import FleetAutoscaler
    from fedcrack_tpu.serve.fleet import ServeFleet
    from fedcrack_tpu.serve.shadow import ShadowController

    model_config = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    serve_config = ServeConfig(
        bucket_sizes=(16,),
        max_batch=4,
        max_delay_ms=30.0,
        tile_overlap=4,
        replicas=3,
        min_replicas=1,
        max_replicas=3,
        scale_cooldown_s=0.0,
        scale_down_idle_evals=1,
        shadow_fraction=0.5,
        shadow_min_samples=64,
    )
    v0 = init_variables(jax.random.key(0), model_config)
    v1 = init_variables(jax.random.key(1), model_config)
    plan = FaultPlan(
        [
            Fault(kind=REPLICA_CRASH_DURING_SCALE, round=1),
            Fault(kind=SHADOW_REPLICA_CRASH, round=0),
        ]
    )

    class _SlowBatches:
        """Stretch every dispatch so queued backlogs provably exist on the
        drained/crashed replicas at race time (see run_replica_crash_drill)."""

        def on_batch(self, bucket, batch_index, attempt):
            time.sleep(0.05)

    fleet = ServeFleet(
        model_config, serve_config, v0, initial_version=0, chaos=_SlowBatches()
    )
    auto = FleetAutoscaler(fleet)
    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (16, 16, 3), dtype=np.uint8)
    t_start = time.perf_counter()
    # A calm synthetic exposition: the autoscaler sees 3 idle replicas and
    # wants one drained — the drill controls WHEN, so the crash can race it.
    calm = {
        "serve_fleet_replicas": {
            "type": "gauge", "help": "", "samples": {(): 3.0}
        },
        "serve_rolling_p95_seconds": {
            "type": "gauge", "help": "", "samples": {(): 0.0}
        },
        "serve_router_queue_depth_total": {
            "type": "gauge", "help": "",
            "samples": {(("bucket", "16"),): 0.0},
        },
    }
    try:
        # ---- part A: crash vs scale-down race ----
        n_burst = 24
        futures = []
        fut_lock = threading.Lock()

        def submit_some(n):
            for _ in range(n):
                f = fleet.submit(img)
                with fut_lock:
                    futures.append(f)

        threads = [
            threading.Thread(target=submit_some, args=(n_burst // 4,))
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fault = plan.take(REPLICA_CRASH_DURING_SCALE, round=1)
        assert fault is not None
        crash_victim = fault.round  # replica index, like SERVE_REPLICA_CRASH
        t_race = time.perf_counter()
        barrier = threading.Barrier(2)

        def scale_down():
            barrier.wait()
            auto.evaluate(calm)  # calm + idle_evals=1 -> drains replica 2

        def crash():
            barrier.wait()
            fleet.router.kill_replica(crash_victim)

        racers = [
            threading.Thread(target=scale_down),
            threading.Thread(target=crash),
        ]
        for t in racers:
            t.start()
        for t in racers:
            t.join()
        results = [f.result(timeout=60) for f in futures]
        answered = len(results)
        live_after = len(fleet.router.live_replicas())
        scale_actions = [a["action"] for a in auto.actions]
        race_s = round(time.perf_counter() - t_race, 3)

        # ---- part B: dying shadow lane ----
        ctrl = ShadowController(fleet)
        stop_pump = threading.Event()
        prod_results: list = []
        prod_errors: list = []

        def pump():
            while not stop_pump.is_set():
                try:
                    prod_results.append(fleet.submit(img).result(timeout=30))
                except Exception as e:  # any shed/fail here breaks the pin
                    prod_errors.append(repr(e))

        pump_threads = [threading.Thread(target=pump) for _ in range(2)]
        for t in pump_threads:
            t.start()

        def kill_shadow():
            # Wait for the mirror to attach, then kill its lane — the
            # scheduled fault, consumed so the artifact proves it fired.
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                mirror = fleet.router._shadow
                if mirror is not None and mirror.completed() >= 1:
                    break
                time.sleep(0.01)
            fault_b = plan.take(SHADOW_REPLICA_CRASH, round=0)
            assert fault_b is not None
            mirror = fleet.router._shadow
            if mirror is not None:
                mirror._batcher.close()

        killer = threading.Thread(target=kill_shadow)
        killer.start()
        verdict = ctrl.stage(1, v1, wait_s=4.0)
        killer.join(timeout=15)
        stop_pump.set()
        for t in pump_threads:
            t.join(timeout=15)
        shed = sum(fleet.router.shed_counts().values())
        return {
            "burst": n_burst,
            "fault_fired": [f.kind for f in plan.triggered],
            "crash_victim": crash_victim,
            "answered": answered,
            "dropped": n_burst - answered,
            "zero_dropped": answered == n_burst,
            "live_after_race": live_after,
            "scale_actions": scale_actions,
            "shadow_verdict": verdict["verdict"],
            "shadow_reasons": verdict["reasons"],
            "shadow_completed": verdict["completed"],
            "shadow_failures": verdict["shadow_failures"],
            "production_answered_during_shadow": len(prod_results),
            "production_errors_during_shadow": prod_errors,
            "production_unperturbed": not prod_errors,
            "shed_total": shed,
            "rollback_not_promote": verdict["verdict"] == "rollback",
            "race_s": race_s,
            "drill_s": round(time.perf_counter() - t_start, 3),
        }
    finally:
        fleet.close()


def run_stream_reset_drill() -> dict:
    """SERVE_STREAM_RESET drill (round 19): a mid-stream session drop on
    the video serving plane.

    A video session (tiny engine, multi-tile frames) serves a seeded
    correlated frame sequence while a chaos plan schedules a
    ``SERVE_STREAM_RESET`` at a mid-sequence frame — ``StreamChaos``
    consumes it and wipes the per-stream tile cache BEFORE that frame is
    served. The pinned claims:

    - the reset stream falls back to a full-tile re-run on the reset frame
      (``tiles_computed == tiles_total``, zero cache hits);
    - ZERO wrong bytes: every frame, including the reset frame and the
      cache-warm frames around it, is byte-identical to stateless
      ``engine.predict_tiled`` under the same weights snapshot;
    - zero dropped accepted requests: every submitted frame answers.

    The fault is scheduled and consumed through the plan, so the artifact
    proves the reset actually fired instead of silently matching nothing.
    """
    import jax

    from fedcrack_tpu.chaos.inject import StreamChaos
    from fedcrack_tpu.chaos.plan import SERVE_STREAM_RESET, Fault, FaultPlan
    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.obs.registry import MetricsRegistry
    from fedcrack_tpu.serve.engine import InferenceEngine
    from fedcrack_tpu.serve.stream import StreamSessionManager
    from fedcrack_tpu.tools.load_gen import make_frame_sequence

    model_config = ModelConfig(
        img_size=32, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    serve_config = ServeConfig(
        bucket_sizes=(16, 32), max_batch=4, max_delay_ms=10.0, tile_overlap=4
    )
    engine = InferenceEngine(model_config, serve_config)
    variables = engine.prepare(init_variables(jax.random.key(0), model_config))

    class _Static:
        def snapshot(self):
            return 0, variables

    n_frames, reset_at = 8, 4
    plan = FaultPlan([Fault(kind=SERVE_STREAM_RESET, round=reset_at)])
    manager = StreamSessionManager(
        engine,
        _Static(),
        chaos=StreamChaos(plan, manager=None),
        registry=MetricsRegistry(),
    )
    manager.chaos.manager = manager
    frames = make_frame_sequence(n_frames, 64, 0.1, seed=7)
    session = manager.open("drill", height=64, width=64)
    t_start = time.perf_counter()
    wrong_bytes = 0
    answered = 0
    reset_frame = None
    per_frame = []
    for fi, frame in enumerate(frames):
        result = session.process_frame(frame)
        manager.record(result)
        answered += 1
        ref = engine.predict_tiled(variables, frame)
        identical = result.probs.tobytes() == ref.tobytes()
        if not identical:
            wrong_bytes += 1
        if fi == reset_at:
            reset_frame = {
                "frame": fi,
                "full_rerun": result.full_rerun,
                "tiles_computed": result.tiles_computed,
                "tiles_total": result.tiles_total,
                "cache_hits": result.cache_hits,
            }
        per_frame.append(
            {
                "frame": fi,
                "hits": result.cache_hits,
                "computed": result.tiles_computed,
                "identical": identical,
            }
        )
    manager.close("drill")
    fired = [f.kind for f in plan.triggered]
    stats = manager.stats()
    return {
        "frames": n_frames,
        "reset_at": reset_at,
        "fault_fired": SERVE_STREAM_RESET in fired,
        "resets_recorded": session.totals["resets"],
        "answered": answered,
        "dropped": n_frames - answered,
        "zero_dropped": answered == n_frames,
        "wrong_bytes": wrong_bytes,
        "zero_wrong_bytes": wrong_bytes == 0,
        "reset_frame": reset_frame,
        "reset_was_full_rerun": bool(
            reset_frame
            and reset_frame["full_rerun"]
            and reset_frame["tiles_computed"] == reset_frame["tiles_total"]
        ),
        "per_frame": per_frame,
        "hit_ratio": stats["hit_ratio"],
        "effective_speedup": stats["effective_speedup"],
        "drill_s": round(time.perf_counter() - t_start, 3),
    }


def run_scaled_update_drill() -> dict:
    """SCALED_UPDATE drill (round 18, Blanchard et al.'s threat model): an
    adversarially AMPLIFIED update — the client's real trained weights
    scaled by a large finite factor, shape-correct and fully finite — is
    ACCEPTED by sanitation and averaged into the global. The drill pins the
    two-layer detection story the health plane exists for:

    Part 1 (ledger): a 3-client sync round where client c uploads its
    update poisoned by ``_poison_weights(..., SCALED_UPDATE)`` (scheduled
    and consumed through a chaos FaultPlan so the artifact proves it
    fired). The server ACCEPTS it — same status as the honest clients, c
    lands in the round's ``clients`` history — and FedAvg drags the global
    by orders of magnitude; but the flush-time robust z-score in
    ``state.ledger`` flags c past ANOMALY_ALERT while the honest clients
    stay well below.

    Part 2 (canary → watchdog): a tiny ResUNet serve stack evaluates the
    canary reference on the boot weights, then hot-swaps in the dragged
    global (the boot weights scaled by the same FedAvg drag factor part 1
    produced). The pinned-probe IoU cliffs, the armed
    ``configs/slo_health.json`` rules breach on BOTH signals (canary IoU
    floor + anomaly ceiling over part 1's exported ledger), the flight
    ring dumps, and the artifact records the ``BREACH_EXIT`` (3) contract.
    """
    import jax

    from fedcrack_tpu.chaos import inject
    from fedcrack_tpu.chaos.inject import _poison_weights
    from fedcrack_tpu.chaos.plan import SCALED_UPDATE, Fault, FaultPlan
    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.health import ledger as health_ledger
    from fedcrack_tpu.health.canary import CanaryEvaluator
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.obs import flight
    from fedcrack_tpu.obs.registry import MetricsRegistry
    from fedcrack_tpu.obs.watchdog import BREACH_EXIT, Watchdog, load_rules
    from fedcrack_tpu.serve.engine import InferenceEngine, watch_recompiles
    from fedcrack_tpu.serve.hot_swap import ModelVersionManager
    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    # ---- part 1: sanitation accepts, the ledger flags ----
    plan = FaultPlan([Fault(kind=SCALED_UPDATE, round=1, client="c")])
    cfg = FedConfig(
        max_rounds=1,
        cohort_size=3,
        registration_window_s=5.0,
        round_deadline_s=60.0,
        port=0,
    )
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
    t0 = time.perf_counter()
    with ServerThread(server) as st:
        channel, call = _raw_caller(st.port)
        for c in ("a", "b", "c"):
            assert call(_ready(c)).status == R.SW
        # The poisoned upload lands FIRST: its accept status (RESP_ACY)
        # cannot be confused with a round-closing reply.
        fault = plan.take(SCALED_UPDATE, client="c", round=1)
        assert fault is not None
        poisoned = _poison_weights(tree_to_bytes(_vars(1.1)), SCALED_UPDATE)
        msg = pb.ClientMessage(cname="c")
        msg.done.round = 1
        msg.done.weights = poisoned
        msg.done.sample_count = 10
        rep_c = call(msg)
        rep_a = call(_done("a", 1, 1.0, 10))
        rep_b = call(_done("b", 1, 1.2, 10))
        channel.close()
        state = st.state
    entry = state.history[0] if state.history else {}
    # Equal sample counts: the dragged global is the plain mean
    # (1.0 + 1.2 + 1.1 * SCALE_FACTOR) / 3.
    got_avg = float(
        np.mean(tree_from_bytes(rep_b.weights)["params"]["w"])
    )
    drag = (1.0 + 1.2 + 1.1 * inject.SCALE_FACTOR) / 3.0
    scores = {
        name: state.ledger.get(name, {}).get("anomaly", 0.0)
        for name in ("a", "b", "c")
    }
    ledger_part = {
        "fault_fired": fault.kind,
        "poisoned_accepted": rep_c.status == R.RESP_ACY,
        "honest_accepted": rep_a.status == R.RESP_ACY
        and rep_b.status in (R.RESP_ARY, R.FIN),
        "poisoned_in_history_clients": entry.get("clients") == ["a", "b", "c"],
        "nothing_rejected": entry.get("rejected", {}) == {},
        "global_dragged_avg": round(got_avg, 4),
        "global_drag_matches_fedavg": bool(
            np.isclose(got_avg, drag, rtol=1e-5)
        ),
        "anomaly_scores": {k: round(v, 3) for k, v in scores.items()},
        "alert_threshold": health_ledger.ANOMALY_ALERT,
        "poisoned_flagged": scores["c"] >= health_ledger.ANOMALY_ALERT,
        "honest_below_alert": max(scores["a"], scores["b"])
        < health_ledger.ANOMALY_ALERT,
        "flagged_flushes": state.ledger.get("c", {}).get("flags", 0),
        "round_s": round(time.perf_counter() - t0, 4),
    }

    # ---- part 2: the dragged global cliffs the canary; watchdog fires ----
    model_config = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,), decoder_features=(8, 4)
    )
    serve_config = ServeConfig(
        bucket_sizes=(16,), max_batch=4, max_delay_ms=30.0, tile_overlap=4
    )
    v0 = init_variables(jax.random.key(0), model_config)
    # The serving-side view of part 1's FedAvg: every float leaf dragged by
    # the same mean-of-(1, 1, SCALE_FACTOR) factor a x1000 client lands on
    # a 3-cohort — finite and shape-correct, so the swap path installs it.
    leaf_drag = (1.0 + 1.0 + inject.SCALE_FACTOR) / 3.0
    v_poisoned = jax.tree_util.tree_map(
        lambda a: a * np.asarray(leaf_drag, np.asarray(a).dtype)
        if np.asarray(a).dtype.kind == "f"
        else a,
        v0,
    )
    reg = MetricsRegistry()
    engine = InferenceEngine(model_config, serve_config)
    canary = CanaryEvaluator(engine, registry=reg)
    manager = ModelVersionManager(
        engine, v0, initial_version=0, canary=canary
    )
    engine.warmup(manager.snapshot()[1])
    sentry = watch_recompiles(engine, registry=reg)
    ref = canary.evaluate(0, manager.snapshot()[1])
    installed = manager.install(1, v_poisoned)
    assert installed and canary.last is not None
    post = canary.last
    recompiles = (
        sum(sentry.deltas().values())
        if type(sentry).supported(engine._fn)
        else -1
    )

    # The armed health rules over ONE registry holding both signals: part
    # 1's exported ledger anomaly gauges + the canary IoU time-series.
    health_ledger.export_anomaly_metrics(state.ledger, registry=reg)
    rules_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        os.pardir, os.pardir, "configs", "slo_health.json",
    )
    ring = flight.current()
    installed_ring = False
    if ring is None:  # direct invocation (tests); main() arms its own
        ring = flight.install(path=os.path.join(
            tempfile.gettempdir(), "scaled_update_drill.flight.json"
        ), hooks=False)
        installed_ring = True
    try:
        dumps_before = len(ring.dumps)
        watchdog = Watchdog(load_rules(rules_path), registry=reg)
        report = watchdog.enforce()
        audit = watchdog.audit()
        dumped = ring.dumps[dumps_before:]
    finally:
        if installed_ring:
            flight.uninstall()
    breached_rules = sorted({b["rule"] for b in report["breaches"]})
    return {
        "ledger": ledger_part,
        "canary": {
            "reference_iou": ref["iou"],
            "poisoned_iou": post["iou"],
            "iou_cliff": post["iou"] < 0.5 <= ref["iou"],
            "swap_still_installed": installed,
            "recompiles_since_warmup": recompiles,
        },
        "watchdog": {
            "rules": audit["rules"],
            "breached": breached_rules,
            "both_signals_breached": breached_rules
            == ["canary_iou_floor", "client_anomaly_ceiling"],
            "flight_dumped": bool(dumped),
            "flight_dump_reason": dumped[0]["reason"] if dumped else None,
            "breach_exit_code": BREACH_EXIT,
            "would_exit": BREACH_EXIT if audit["breaches"] else 0,
        },
    }


def run_robust_aggregation_drill() -> dict:
    """Robust-aggregation A/B drill (round 21): the r18 SCALED_UPDATE
    scenario re-run as FOUR arms over real gRPC — identical cohort
    (a=1.0, b=1.2, c's 1.1 update amplified x``SCALE_FACTOR`` through a
    consumed chaos FaultPlan), identical wire path, the ONLY delta being
    ``FedConfig.aggregation``/``quarantine_z``:

    - ``fedavg``       — the r18 baseline; the global drags by ~x300.
    - ``trimmed_mean`` — beta=0.34 trims one value per coordinate end;
      the x1000 coordinate is the trimmed tail, drag collapses to the
      honest spread.
    - ``krum``         — f=1; the poisoned vector's pairwise distance is
      astronomical, an HONEST update is selected verbatim.
    - ``fedavg_quarantine`` — null combine, ``quarantine_z=3.5``: the
      flush-time robust z-score (the r18 *detection*) now feeds the fold's
      exclusion gate (the r21 *response*). The poisoned client lands LAST
      so it triggers the flush — and gets the direct ``NOT_WAIT`` resync
      reply (the EF-rollback contract) instead of an ``RESP_ARY`` that
      would claim its update was averaged.

    Each arm's serve-side story rides one shared tiny-ResUNet engine: the
    canary reference is evaluated once on the boot weights, then every
    arm installs the boot weights scaled by THAT arm's combine applied to
    an honest/honest/x``SCALE_FACTOR`` cohort (the exact part-2 framing
    of the r18 drill). FedAvg cliffs the IoU; every robust arm holds it.

    A colluding-minority variant re-runs the fed plane with 7 clients —
    5 honest, 2 colluders shipping the IDENTICAL amplified update (the
    worst case for Krum's min-distance score; n=7 >= 2f+3 keeps the
    selection sound) — across fedavg / trimmed_mean / krum / multi_krum /
    quarantine, and the quarantine arm's ledger round-trips through
    ``tools/health_report`` to prove the exclusion is visible there too.
    """
    import jax

    from fedcrack_tpu.chaos import inject
    from fedcrack_tpu.chaos.inject import _poison_weights
    from fedcrack_tpu.chaos.plan import SCALED_UPDATE, Fault, FaultPlan
    from fedcrack_tpu.configs import ModelConfig, ServeConfig
    from fedcrack_tpu.fed import aggregation as A
    from fedcrack_tpu.health import ledger as health_ledger
    from fedcrack_tpu.health.canary import CanaryEvaluator
    from fedcrack_tpu.models.resunet import init_variables
    from fedcrack_tpu.obs.registry import MetricsRegistry
    from fedcrack_tpu.serve.engine import InferenceEngine
    from fedcrack_tpu.serve.hot_swap import ModelVersionManager
    from fedcrack_tpu.tools.health_report import build_report, validate_report
    from fedcrack_tpu.transport import transport_pb2 as pb
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    t0 = time.perf_counter()

    def run_arm(clients, poisoned_names, poison_value, order, **agg_kwargs):
        """One real-gRPC round: ``clients`` is {name: (value, ns)};
        updates land in ``order`` (last one closes the barrier); every
        name in ``poisoned_names`` ships its update through
        ``_poison_weights(..., SCALED_UPDATE)``, scheduled and consumed
        via a FaultPlan so the artifact proves the faults fired."""
        plan = FaultPlan(
            [Fault(kind=SCALED_UPDATE, round=1, client=n)
             for n in sorted(poisoned_names)]
        )
        cfg = FedConfig(
            max_rounds=1,
            cohort_size=len(clients),
            registration_window_s=5.0,
            round_deadline_s=60.0,
            port=0,
            **agg_kwargs,
        )
        server = FedServer(cfg, _vars(0.0), tick_period_s=0.02)
        replies = {}
        with ServerThread(server) as st:
            channel, call = _raw_caller(st.port)
            for c in order:
                assert call(_ready(c)).status == R.SW
            for c in order:
                value, ns = clients[c]
                if c in poisoned_names:
                    fault = plan.take(SCALED_UPDATE, client=c, round=1)
                    assert fault is not None
                    msg = pb.ClientMessage(cname=c)
                    msg.done.round = 1
                    msg.done.weights = _poison_weights(
                        tree_to_bytes(_vars(value)), SCALED_UPDATE
                    )
                    msg.done.sample_count = ns
                else:
                    msg = _done(c, 1, value, ns)
                replies[c] = call(msg)
            channel.close()
            state = st.state
        closer = replies[order[-1]]
        # The round-closing reply carries the aggregated global UNLESS the
        # closer was quarantined (NOT_WAIT resync); read the broadcast then.
        blob = closer.weights if closer.weights else state.broadcast_blob
        got_avg = float(np.mean(tree_from_bytes(blob)["params"]["w"]))
        entry = state.history[0] if state.history else {}
        return {
            "state": state,
            "entry": entry,
            "replies": replies,
            "global_avg": got_avg,
        }

    # ---- part 1: the 4-arm A/B (3 clients, 1 poisoned) ----
    clients3 = {"a": (1.0, 10), "b": (1.2, 10), "c": (1.1, 10)}
    honest_mean = (1.0 * 10 + 1.2 * 10) / 20.0  # what a,b alone average to
    arm_specs = {
        # r18 ordering (poisoned first) for the combine arms; the
        # quarantine arm puts the poisoned client LAST so the NOT_WAIT
        # direct-reply resync contract is exercised on the wire.
        "fedavg": dict(order=("c", "a", "b")),
        "trimmed_mean": dict(
            order=("c", "a", "b"), aggregation="trimmed_mean",
            trim_fraction=0.34,
        ),
        "krum": dict(
            order=("c", "a", "b"), aggregation="krum", byzantine_f=1,
        ),
        "fedavg_quarantine": dict(
            order=("a", "b", "c"), quarantine_z=3.5,
        ),
    }
    arms = {}
    raw = {}
    for name, spec in arm_specs.items():
        spec = dict(spec)
        order = spec.pop("order")
        r = run_arm(clients3, {"c"}, 1.1, order, **spec)
        raw[name] = r
        drag = abs(r["global_avg"] - honest_mean)
        arms[name] = {
            "aggregation": spec.get("aggregation", "fedavg"),
            "quarantine_z": spec.get("quarantine_z", 0.0),
            "global_avg": round(r["global_avg"], 4),
            "drag": round(drag, 4),
            "quarantined": {
                k: round(v, 3)
                for k, v in r["entry"].get("quarantined", {}).items()
            },
        }
    fedavg_drag = abs(raw["fedavg"]["global_avg"] - honest_mean)
    for name in ("trimmed_mean", "krum", "fedavg_quarantine"):
        d = abs(raw[name]["global_avg"] - honest_mean)
        arms[name]["drag_reduction_vs_fedavg"] = round(
            fedavg_drag / max(d, 1e-9), 1
        )
    q = raw["fedavg_quarantine"]
    arms["fedavg_quarantine"].update({
        # The poisoned closer is excluded AND resynced: NOT_WAIT with the
        # clean global attached (fires the client-side topk EF rollback).
        "poisoned_reply": q["replies"]["c"].status,
        "poisoned_resynced_not_wait": q["replies"]["c"].status == R.NOT_WAIT,
        "clean_global_attached": bool(q["replies"]["c"].weights),
        "ledger_quarantined_count": q["state"].ledger.get("c", {}).get(
            "quarantined", 0
        ),
        "honest_not_quarantined": all(
            q["state"].ledger.get(n, {}).get("quarantined", 0) == 0
            for n in ("a", "b")
        ),
    })

    # ---- part 2: per-arm canary over ONE shared tiny serve stack ----
    # The serving-side view of each arm: the boot weights scaled by the
    # arm's combine applied to an honest/honest/xSCALE cohort — the exact
    # r18 part-2 framing ((1 + 1 + SCALE)/3 for FedAvg), now computed
    # THROUGH the real algebra per arm instead of hard-coded for FedAvg.
    def arm_factor(algebra):
        triples = [
            ("a", 10, {"w": np.float32([1.0])}),
            ("b", 10, {"w": np.float32([1.0])}),
            ("c", 10, {"w": np.float32([1.0 * inject.SCALE_FACTOR])}),
        ]
        return float(A.fold(algebra, triples)["w"][0])

    factors = {
        "fedavg": arm_factor(A.FedAvg()),
        "trimmed_mean": arm_factor(A.TrimmedMean(0.34)),
        "krum": arm_factor(A.Krum(1)),
        # Quarantine excludes c before the fold (part 1 proved that over
        # the wire); the serving factor is the honest mean: 1.0 exactly.
        "fedavg_quarantine": 1.0,
    }
    model_config = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,),
        decoder_features=(8, 4),
    )
    serve_config = ServeConfig(
        bucket_sizes=(16,), max_batch=4, max_delay_ms=30.0, tile_overlap=4
    )
    v0 = init_variables(jax.random.key(0), model_config)
    reg = MetricsRegistry()
    engine = InferenceEngine(model_config, serve_config)
    canary = CanaryEvaluator(engine, registry=reg)
    manager = ModelVersionManager(engine, v0, initial_version=0, canary=canary)
    engine.warmup(manager.snapshot()[1])
    ref = canary.evaluate(0, manager.snapshot()[1])
    for version, name in enumerate(arms, start=1):
        factor = factors[name]
        v_arm = jax.tree_util.tree_map(
            lambda a: a * np.asarray(factor, np.asarray(a).dtype)
            if np.asarray(a).dtype.kind == "f"
            else a,
            v0,
        )
        installed = manager.install(version, v_arm)
        assert installed and canary.last is not None
        arms[name]["canary_iou"] = round(float(canary.last["iou"]), 6)
        arms[name]["serve_factor"] = round(factor, 4)

    # ---- part 3: colluding minority (7 clients, 2 identical colluders) ----
    honest7 = {
        "h1": (1.0, 10), "h2": (1.05, 10), "h3": (1.1, 10),
        "h4": (1.15, 10), "h5": (1.2, 10),
    }
    clients7 = dict(honest7, p1=(1.1, 10), p2=(1.1, 10))
    order7 = ("p1", "p2", "h1", "h2", "h3", "h4", "h5")
    honest_mean7 = sum(v for v, _ in honest7.values()) / len(honest7)
    colluding_specs = {
        "fedavg": {},
        # floor(0.3 * 7) = 2 trimmed per coordinate end: both colluders.
        "trimmed_mean": dict(aggregation="trimmed_mean", trim_fraction=0.3),
        "krum": dict(aggregation="krum", byzantine_f=2),
        "multi_krum": dict(aggregation="multi_krum", byzantine_f=2),
        "fedavg_quarantine": dict(quarantine_z=3.5),
    }
    colluding = {}
    q7_state = None
    for name, spec in colluding_specs.items():
        r = run_arm(clients7, {"p1", "p2"}, 1.1, order7, **spec)
        d = abs(r["global_avg"] - honest_mean7)
        colluding[name] = {
            "global_avg": round(r["global_avg"], 4),
            "drag": round(d, 4),
            "quarantined": sorted(r["entry"].get("quarantined", {})),
        }
        if name == "fedavg_quarantine":
            q7_state = r["state"]
    fedavg_drag7 = colluding["fedavg"]["drag"]
    colluders_beaten = {
        name: bool(colluding[name]["drag"] <= 0.25)
        for name in colluding if name != "fedavg"
    }

    # ---- part 4: the exclusion is visible in the joined health report ----
    with tempfile.TemporaryDirectory() as tmp:
        ledger_path = os.path.join(tmp, "ledger.jsonl")
        health_ledger.write_ledger_jsonl(q7_state.ledger, ledger_path)
        report = build_report(ledger_path)
        violations = validate_report(report)
    health_part = {
        "schema_violations": violations,
        "quarantines": report["summary"]["quarantines"],
        "quarantined_clients": report["summary"]["quarantined_clients"],
        "exclusion_visible": report["summary"]["quarantined_clients"]
        == ["p1", "p2"],
    }

    robust_arm_names = ("trimmed_mean", "krum", "fedavg_quarantine")
    return {
        "scale_factor": inject.SCALE_FACTOR,
        "honest_mean": honest_mean,
        "reference_iou": round(float(ref["iou"]), 6),
        "arms": arms,
        "fedavg_cliffed": arms["fedavg"]["canary_iou"] < 0.5,
        "robust_arms_hold": all(
            arms[n]["canary_iou"] >= 0.9 for n in robust_arm_names
        ),
        "drag_reduced_10x": all(
            arms[n]["drag_reduction_vs_fedavg"] >= 10.0
            for n in robust_arm_names
        ),
        "colluding": {
            "n_clients": len(clients7),
            "colluders": ["p1", "p2"],
            "honest_mean": honest_mean7,
            "fedavg_drag": fedavg_drag7,
            "arms": colluding,
            "colluders_beaten": colluders_beaten,
        },
        "health_report": health_part,
        "drill_s": round(time.perf_counter() - t0, 4),
    }


def run_secagg_dropout_drill() -> dict:
    """SECAGG_DROPOUT drill (round 23, privacy plane): a masker dies in the
    Bonawitz recovery window — AFTER its seed froze into the masking roster
    (survivors' uploads carry uncancelled pairwise masks against it) and
    BEFORE its own masked upload — over REAL gRPC. The round must still
    close at quorum via seed recovery, and the unmasked cohort average must
    equal the plaintext weighted fixed-point mean of the SURVIVORS
    bit-for-bit: modular integer cancellation, not float-tolerance.

    3 FedClient sessions, `c` injected with a chaos-plan SECAGG_DROPOUT
    (consumed through the plan so the artifact proves the drop fired).
    The survivors' trainers add known constants, so the expected average
    is closed-form; the pin runs in the fixed-point residue domain AND on
    the decoded float blob the survivors pulled as the new global.
    """
    import threading

    from fedcrack_tpu.chaos.inject import ClientChaos, InjectedCrash
    from fedcrack_tpu.chaos.plan import SECAGG_DROPOUT, Fault, FaultPlan
    from fedcrack_tpu.privacy.secagg import (
        fixed_point_decode,
        weighted_fixed_sum,
    )
    from fedcrack_tpu.transport.client import FedClient
    from fedcrack_tpu.transport.service import FedServer, ServerThread

    def fake_train(inc: float, ns: int):
        def train_fn(blob: bytes, rnd: int):
            tree = tree_from_bytes(blob)
            tree["params"]["w"] = tree["params"]["w"] + np.float32(inc)
            return tree_to_bytes(tree), ns, {"loss": float(rnd)}

        return train_fn

    cfg = FedConfig(
        max_rounds=1,
        cohort_size=3,
        registration_window_s=5.0,
        round_deadline_s=2.0,
        quorum_fraction=0.67,
        poll_period_s=0.05,
        secagg=True,
        port=0,
    )
    plan = FaultPlan([Fault(kind=SECAGG_DROPOUT, round=1, client="c")])
    server = FedServer(cfg, _vars(0.0), tick_period_s=0.05)
    t0 = time.perf_counter()
    errors: dict[str, BaseException] = {}
    results: dict[str, object] = {}

    def run(client: FedClient, name: str) -> None:
        try:
            results[name] = client.run_session()
        except InjectedCrash as e:
            errors[name] = e

    with ServerThread(server) as st:
        clients = {
            "a": FedClient(cfg, fake_train(1.0, 10), cname="a", port=st.port),
            "b": FedClient(cfg, fake_train(3.0, 30), cname="b", port=st.port),
            "c": FedClient(
                cfg,
                fake_train(5.0, 20),
                cname="c",
                port=st.port,
                chaos=ClientChaos(plan),
            ),
        }
        threads = [
            threading.Thread(target=run, args=(cl, n))
            for n, cl in clients.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        state = st.state

    entry = state.history[0] if state.history else {}
    secagg_info = entry.get("secagg") or {}
    # The drill's pin: the unmasked fixed-point sum of the fold equals the
    # PLAINTEXT weighted sum of the survivors — recover it from the global
    # blob by re-encoding the closed-form expectation through the same
    # fixed-point path (bit-for-bit on the decoded float leaves).
    surv_updates = [_vars(1.0), _vars(3.0)]
    surv_ns = [10, 30]
    want = fixed_point_decode(
        weighted_fixed_sum(surv_updates, surv_ns, cfg.secagg_bits),
        sum(surv_ns),
        cfg.secagg_bits,
        _vars(0.0),
    )
    got = tree_from_bytes(state.global_blob)
    exact = bool(
        np.array_equal(got["params"]["w"], want["params"]["w"])
    )
    return {
        "fault_fired": [f.kind for f in plan.triggered] == [SECAGG_DROPOUT],
        "dropper_crashed": "c" in errors and "c" not in results,
        "survivors_completed": all(
            n in results and results[n].rounds_completed == 1
            for n in ("a", "b")
        ),
        "round_closed": state.phase == R.PHASE_FINISHED
        and len(state.history) == 1,
        "maskers": secagg_info.get("maskers"),
        "recovered": secagg_info.get("recovered"),
        "dropout_recovered": secagg_info.get("recovered") == ["c"],
        "exact_average_bit_for_bit": exact,
        "torn_rounds": int(state.failed_rounds),
        "drill_s": round(time.perf_counter() - t0, 4),
    }


def run_dp_replay_drill() -> dict:
    """DP replay drill (round 23): a mesh round with the DP-SGD twin on
    (clip + seeded Gaussian noise) is killed by an injected device failure
    and retried under ``max_round_retries`` — the retried trajectory must
    be BIT-IDENTICAL to an uninterrupted run. The noise key chain's round
    axis is the same replicated per-dispatch seed scalar the r12 codec
    threads, restored on replay via ``codec_state()``; this drill is the
    proof that a chaos-retried DP round never double-draws its noise."""
    import jax

    from fedcrack_tpu.chaos.inject import MeshChaos
    from fedcrack_tpu.chaos.plan import MESH_DEVICE_FAIL, Fault, FaultPlan
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.parallel import make_mesh, run_mesh_federation
    from fedcrack_tpu.parallel.fedavg_mesh import (
        build_federated_round,
        stack_client_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    tiny = ModelConfig(
        img_size=16, stem_features=4, encoder_features=(8,),
        decoder_features=(8, 4),
    )
    steps, batch = 2, 2
    mesh = make_mesh(1, 1)
    t0 = time.perf_counter()

    def data_fn(r: int):
        images, masks = stack_client_data(
            [synth_crack_batch(steps * batch, img_size=16, seed=r)],
            steps,
            batch,
        )
        return (
            images,
            masks,
            np.ones(1, np.float32),
            np.full(1, float(steps * batch), np.float32),
        )

    def build():
        return build_federated_round(
            mesh, tiny, learning_rate=1e-3, local_epochs=1,
            dp_clip_norm=1.0, dp_noise_multiplier=1.1, dp_seed=42,
        )

    init = create_train_state(jax.random.key(0), tiny).variables
    v_clean, _ = run_mesh_federation(build(), init, data_fn, 2, mesh)

    plan = FaultPlan([Fault(kind=MESH_DEVICE_FAIL, round=0)])
    v_chaos, records = run_mesh_federation(
        build(), init, data_fn, 2, mesh,
        max_round_retries=1, fault_injector=MeshChaos(plan),
    )
    identical = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(v_clean),
            jax.tree_util.tree_leaves(v_chaos),
        )
    )
    return {
        "fault_fired": not plan.pending and len(plan.triggered) == 1,
        "retries_round_0": int(records[0].retries),
        "replay_bit_identical": bool(identical),
        "rounds": len(records),
        "drill_s": round(time.perf_counter() - t0, 4),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--rounds", type=int, default=3)
    args = p.parse_args(argv)
    # Flight recorder (round 16): the drills feed the ring for free (fault
    # injections via FaultPlan.take, fed-plane transitions, spans); a drill
    # that dies ships its last-N-seconds history next to the traceback
    # instead of just final counters.
    from fedcrack_tpu.obs import flight

    flight_path = os.path.abspath(f"{args.out}.flight.json")
    flight.install(path=flight_path)
    try:
        artifact = {
            "generated_by": "fedcrack_tpu.tools.chaos_drill",
            "kill_restart": run_kill_restart_drill(rounds=args.rounds),
            "corrupt_frame": run_corrupt_frame_drill(),
            "edge_crash": run_edge_crash_drill(),
            "straggler_storm": run_straggler_storm_drill(),
            "buffered_kill": run_buffered_kill_drill(),
            "replica_crash": run_replica_crash_drill(),
            "elastic_fleet": run_elastic_fleet_drill(),
            "scaled_update": run_scaled_update_drill(),
            "robust_aggregation": run_robust_aggregation_drill(),
            "stream_reset": run_stream_reset_drill(),
            "secagg_dropout": run_secagg_dropout_drill(),
            "dp_replay": run_dp_replay_drill(),
        }
    except BaseException:
        flight.dump("chaos drill failed")
        print(f"drill failed; flight record at {flight_path}", file=sys.stderr)
        raise
    finally:
        flight.uninstall()
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
    print(json.dumps(artifact["kill_restart"]), flush=True)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
