"""Flax ResUNet -> Keras h5 weight exporter (inverse of tools/h5_import.py).

A user of the reference keeps their tooling around ``crack_segmentation.h5``
checkpoints (reference: test/Segmentation.py:177-179, loaded by
test/Segmentation2.py:94); this exporter writes a federation-trained global
model (e.g. the server's ``--best-path`` msgpack) as a legacy Keras h5 that
``keras.Model.load_weights`` consumes directly — so switching to this
framework is a two-way door.

Layout written: the legacy full-model-h5 weight schema (``model_weights``
group, ``layer_names``/``weight_names`` attrs) that this image's Keras
emits for ``model.save`` — verified round-trip against real Keras in
tests/test_h5_export.py. Only weighted layers are listed, in the reference
model's creation order; Keras' legacy loader matches by order, not name.

Kernel-layout conversions are the exact inverses of h5_import.py:

- ``Conv2D``: unchanged.
- ``SeparableConv2D``: Flax depthwise ``(kh, kw, 1, in)`` ->
  Keras ``(kh, kw, in, 1)`` (transpose last two axes).
- ``Conv2DTranspose``: Flax ``(kh, kw, in, out)`` -> flip both spatial axes
  and swap channel axes -> Keras' gradient-of-conv ``(kh, kw, out, in)``.
- ``BatchNorm``: ``scale``/``bias`` -> gamma/beta; ``batch_stats`` -> moving
  mean/variance.
"""

from __future__ import annotations

import numpy as np

from fedcrack_tpu.configs import ModelConfig

try:  # pragma: no cover - h5py ships with the image
    import h5py

    HAVE_H5PY = True
except ImportError:  # pragma: no cover
    HAVE_H5PY = False


def _f32(x) -> np.ndarray:
    return np.asarray(x, np.float32)


def _layer_entries(variables: dict, config: ModelConfig) -> list[tuple[str, dict]]:
    """(layer_name, {weight_base: array}) in the Keras model's creation
    order: stem conv+bn, per encoder block [sep1, bn1, sep2, bn2, res],
    per decoder block [convT1, bn1, convT2, bn2, res], head. Layer names
    carry 'transpose' for ConvT so h5_import's classifier re-reads our own
    files correctly."""
    p = variables["params"]
    s = variables["batch_stats"]

    def conv(name):
        return name, {"kernel": _f32(p[name]["kernel"]), "bias": _f32(p[name]["bias"])}

    def bn(name):
        return name, {
            "gamma": _f32(p[name]["scale"]),
            "beta": _f32(p[name]["bias"]),
            "moving_mean": _f32(s[name]["mean"]),
            "moving_variance": _f32(s[name]["var"]),
        }

    def sep(name):
        dw = _f32(p[name]["depthwise"]["kernel"])  # (kh, kw, 1, in)
        return name, {
            "depthwise_kernel": np.transpose(dw, (0, 1, 3, 2)),  # -> (kh, kw, in, 1)
            "pointwise_kernel": _f32(p[name]["pointwise"]["kernel"]),
            "bias": _f32(p[name]["pointwise"]["bias"]),
        }

    def convT(flax_name, file_name):
        k = _f32(p[flax_name]["kernel"])  # (kh, kw, in, out), un-flipped
        return file_name, {
            "kernel": np.transpose(k[::-1, ::-1], (0, 1, 3, 2)),  # -> (kh, kw, out, in)
            "bias": _f32(p[flax_name]["bias"]),
        }

    entries = [conv("stem_conv"), bn("stem_bn")]
    for i in range(len(config.encoder_features)):
        entries += [
            sep(f"enc{i}_sep1"), bn(f"enc{i}_bn1"),
            sep(f"enc{i}_sep2"), bn(f"enc{i}_bn2"),
            conv(f"enc{i}_res"),
        ]
    for i in range(len(config.decoder_features)):
        entries += [
            convT(f"dec{i}_convT1", f"dec{i}_conv_transpose1"), bn(f"dec{i}_bn1"),
            convT(f"dec{i}_convT2", f"dec{i}_conv_transpose2"), bn(f"dec{i}_bn2"),
            conv(f"dec{i}_res"),
        ]
    entries.append(conv("head"))
    return entries


def _check_structure(variables: dict, config: ModelConfig) -> None:
    """Every module in ``variables`` must be consumed by the export — a
    config declaring fewer blocks than the weights hold would otherwise
    produce a well-formed h5 with blocks silently missing (the importer's
    invariant is 'a mismatch raises instead of silently mis-seeding'; the
    exporter holds the same line)."""
    n_enc = len(config.encoder_features)
    n_dec = len(config.decoder_features)
    expected_params = {"stem_conv", "stem_bn", "head"}
    expected_stats = {"stem_bn"}
    for i in range(n_enc):
        expected_params |= {f"enc{i}_sep1", f"enc{i}_bn1", f"enc{i}_sep2",
                            f"enc{i}_bn2", f"enc{i}_res"}
        expected_stats |= {f"enc{i}_bn1", f"enc{i}_bn2"}
    for i in range(n_dec):
        expected_params |= {f"dec{i}_convT1", f"dec{i}_bn1", f"dec{i}_convT2",
                            f"dec{i}_bn2", f"dec{i}_res"}
        expected_stats |= {f"dec{i}_bn1", f"dec{i}_bn2"}
    for tree, expected, label in (
        (variables["params"], expected_params, "params"),
        (variables["batch_stats"], expected_stats, "batch_stats"),
    ):
        got = set(tree.keys())
        if got != expected:
            raise ValueError(
                f"{label} structure does not match the export config: "
                f"unconsumed {sorted(got - expected)}, "
                f"missing {sorted(expected - got)}"
            )


def export_resunet_h5(
    variables: dict, path: str, config: ModelConfig | None = None
) -> None:
    """Write ``{'params','batch_stats'}`` as a Keras-loadable legacy h5."""
    if not HAVE_H5PY:  # pragma: no cover
        raise ImportError("h5py is required for Keras h5 export")
    config = config or ModelConfig()
    _check_structure(variables, config)
    entries = _layer_entries(variables, config)
    str_dt = h5py.special_dtype(vlen=str)
    with h5py.File(path, "w") as f:
        root = f.create_group("model_weights")
        for g in (f, root):
            g.attrs["backend"] = "tensorflow"
            g.attrs["keras_version"] = "3"
        root.attrs.create(
            "layer_names", [name for name, _ in entries], dtype=str_dt
        )
        for name, weights in entries:
            group = root.create_group(name)
            weight_names = [f"{name}/{base}" for base in weights]
            group.attrs.create("weight_names", weight_names, dtype=str_dt)
            for base, arr in weights.items():
                group.create_dataset(f"{name}/{base}", data=arr)


def main(argv: list[str] | None = None) -> int:
    """CLI: ``python -m fedcrack_tpu.tools.h5_export model.msgpack out.h5``."""
    import argparse

    import jax

    from fedcrack_tpu.fed.serialization import tree_from_bytes
    from fedcrack_tpu.models.resunet import init_variables

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("msgpack_path", help="msgpack pytree (fed/serialization format, "
                   "e.g. the server's --best-path or centralized best.msgpack)")
    p.add_argument("out_path", help="Keras h5 output")
    p.add_argument("--img-size", type=int, default=128)
    p.add_argument("--config", help="JSON FedConfig file; its model section wins")
    args = p.parse_args(argv)
    if args.config:
        from fedcrack_tpu.configs import FedConfig

        with open(args.config) as f:
            config = FedConfig.from_json(f.read()).model
    else:
        config = ModelConfig(img_size=args.img_size)
    template = init_variables(jax.random.key(0), config)
    with open(args.msgpack_path, "rb") as f:
        variables = tree_from_bytes(f.read(), template=template)
    export_resunet_h5(variables, args.out_path, config)
    print(f"exported {args.msgpack_path} -> {args.out_path}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
