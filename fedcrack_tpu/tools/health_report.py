"""Join the health plane's artifacts into ONE schema-guarded report.

``python -m fedcrack_tpu.tools.health_report --ledger ledger.jsonl
--canary canary.json --drift drift.json --out health_report.json``

The soak/serve harnesses emit three deterministic artifacts — the
per-client update ledger (``health.ledger.write_ledger_jsonl``), the
canary IoU history (``tools/soak.py``), and the drift profile comparison
(``health.drift.write_drift_json``). Operators and CI want one document
answering "is the federation healthy": who offered what, who got flagged,
how the canary IoU moved across installed versions, and which traffic
signals drifted. This tool is that join.

Schema guard: the report is validated (:func:`validate_report`) against
the typed contract below BEFORE it is written, and the process exits
nonzero on any violation — a malformed ledger row, a non-unit canary IoU,
a non-finite PSI, or a conservation break (offers !=
accepted + rejected + resyncs) all fail loudly instead of shipping a
plausible-looking artifact. CI runs this against the soak smoke's workdir
and uploads the report.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

from fedcrack_tpu.health.ledger import (
    ANOMALY_ALERT,
    conservation,
    read_ledger_jsonl,
)

# Typed contracts, bench.py DETAIL_SCHEMA style: key -> isinstance types.
LEDGER_ROW_SCHEMA = {
    "offers": int,
    "accepted": int,
    "resyncs": int,
    "samples": int,
    "wire_bytes": int,
    "rejected": dict,
    "last_round": int,
    "last_staleness": int,
    "norms": list,
    "cosines": list,
    "anomaly": (int, float),
    "flags": int,
    # Round 21: flushes this client was excluded from by the ledger-coupled
    # quarantine (detect -> exclude, end to end in one report).
    "quarantined": int,
}
CANARY_EVAL_SCHEMA = {
    "version": int,
    "iou": (int, float),
    "per_bucket": dict,
    "reference_version": int,
    "probe_batch": int,
    "probe_seed": int,
}
# Round 23: the privacy-plane artifact (fed.rounds.privacy_summary, written
# by `server.py --privacy-summary`) joined into the report — the budget the
# federation SPENT belongs next to who spent it.
PRIVACY_DP_SCHEMA = {
    "enabled": bool,
    "clip_norm": (int, float),
    "noise_multiplier": (int, float),
    "sample_rate": (int, float),
    "delta": (int, float),
    "epsilon_budget": (int, float),
    "clients": dict,
    "max_epsilon": (int, float),
}
PRIVACY_CLIENT_SCHEMA = {
    "steps": int,
    "epsilon": (int, float),
}
PRIVACY_SECAGG_SCHEMA = {
    "enabled": bool,
    "bits": int,
    "roster_size": int,
}
SUMMARY_SCHEMA = {
    "clients": int,
    "offers": int,
    "accepted": int,
    "rejected": int,
    "resyncs": int,
    "flagged_clients": list,
    "max_anomaly": (int, float),
    "conservation_violations": list,
    "quarantines": int,
    "quarantined_clients": list,
}


def build_report(
    ledger_path: str,
    canary_path: str | None = None,
    drift_path: str | None = None,
    privacy_path: str | None = None,
) -> dict:
    """The joined report (deterministic: sorted clients, no timestamps).
    The canary/drift/privacy sections are None when their artifact is not
    given — absence, not an empty-but-plausible block."""
    ledger = read_ledger_jsonl(ledger_path)
    cons = conservation(ledger)
    clients = {}
    for name in sorted(ledger):
        rec = dict(ledger[name])
        rec["flagged"] = float(rec.get("anomaly", 0.0)) >= ANOMALY_ALERT
        clients[name] = rec
    summary = {
        "clients": len(ledger),
        "offers": sum(r["offers"] for r in ledger.values()),
        "accepted": sum(r["accepted"] for r in ledger.values()),
        "rejected": sum(
            sum(r["rejected"].values()) for r in ledger.values()
        ),
        "resyncs": sum(r["resyncs"] for r in ledger.values()),
        "flagged_clients": sorted(
            n for n, r in clients.items() if r["flagged"]
        ),
        "max_anomaly": max(
            (float(r.get("anomaly", 0.0)) for r in ledger.values()),
            default=0.0,
        ),
        "conservation_violations": cons["violations"],
        # Round 21: the response layer's totals — how many flush-time
        # exclusions the quarantine gate made, and for whom; joined with
        # the per-client `flagged` detection bit above, the report shows
        # detect -> exclude end to end.
        "quarantines": sum(
            int(r.get("quarantined", 0)) for r in ledger.values()
        ),
        "quarantined_clients": sorted(
            n for n, r in ledger.items() if int(r.get("quarantined", 0)) > 0
        ),
    }
    canary = None
    if canary_path:
        with open(canary_path, encoding="utf-8") as f:
            canary = json.load(f)
    drift = None
    if drift_path:
        with open(drift_path, encoding="utf-8") as f:
            doc = json.load(f)
        psis = doc.get("psi") or {}
        drift = {
            "psi": {k: float(psis[k]) for k in sorted(psis)},
            "max_psi": max((float(v) for v in psis.values()), default=0.0),
            "signals": sorted({k.split("/", 1)[1] for k in psis}),
            "buckets": sorted({k.split("/", 1)[0] for k in psis}),
        }
    privacy = None
    if privacy_path:
        with open(privacy_path, encoding="utf-8") as f:
            privacy = json.load(f)
    return {
        "generated_by": "fedcrack_tpu.tools.health_report",
        "anomaly_alert": ANOMALY_ALERT,
        "clients": clients,
        "summary": summary,
        "canary": canary,
        "drift": drift,
        "privacy": privacy,
    }


def _typed(block: dict, schema: dict, where: str, bad: list) -> None:
    for key, typ in schema.items():
        if key not in block:
            bad.append(f"{where}[{key!r}] missing")
        elif typ is bool:
            # A declared-bool field wants a REAL bool (the privacy block's
            # `enabled` flags) — ints masquerading as flags fail.
            if not isinstance(block[key], bool):
                bad.append(
                    f"{where}[{key!r}] is {type(block[key]).__name__}, "
                    "wants bool"
                )
        elif isinstance(block[key], bool) or not isinstance(block[key], typ):
            bad.append(
                f"{where}[{key!r}] is {type(block[key]).__name__}, wants {typ}"
            )


def validate_report(report: dict) -> list:
    """Contract violations (empty = clean) — shared by the CLI's exit-code
    gate and the tier-1 guard test, so the contract cannot drift from the
    code that writes it."""
    bad: list[str] = []
    clients = report.get("clients")
    if not isinstance(clients, dict):
        return [f"clients is {type(clients).__name__}, wants dict"]
    for name in sorted(clients):
        rec = clients[name]
        _typed(rec, LEDGER_ROW_SCHEMA, f"clients[{name!r}]", bad)
        rejected = rec.get("rejected")
        n_rejected = (
            sum(int(v) for v in rejected.values())
            if isinstance(rejected, dict)
            else 0
        )
        if isinstance(rec.get("offers"), int) and rec["offers"] != (
            rec.get("accepted", 0) + n_rejected + rec.get("resyncs", 0)
        ):
            bad.append(
                f"clients[{name!r}] conservation: offers != "
                "accepted + rejected + resyncs"
            )
        for window in ("norms", "cosines"):
            for x in rec.get(window) or []:
                if not isinstance(x, (int, float)) or not math.isfinite(x):
                    bad.append(f"clients[{name!r}][{window!r}] non-finite")
                    break
    summary = report.get("summary")
    if isinstance(summary, dict):
        _typed(summary, SUMMARY_SCHEMA, "summary", bad)
    else:
        bad.append(f"summary is {type(summary).__name__}, wants dict")
    canary = report.get("canary")
    if canary is not None:
        history = canary.get("history") if isinstance(canary, dict) else None
        if not isinstance(history, list):
            bad.append("canary.history missing or not a list")
        else:
            for i, ev in enumerate(history):
                _typed(ev, CANARY_EVAL_SCHEMA, f"canary.history[{i}]", bad)
                iou = ev.get("iou")
                if isinstance(iou, (int, float)) and not (
                    math.isfinite(iou) and 0.0 <= iou <= 1.0
                ):
                    bad.append(f"canary.history[{i}].iou not a unit value")
    privacy = report.get("privacy")
    if privacy is not None:
        dp = privacy.get("dp") if isinstance(privacy, dict) else None
        sa = privacy.get("secagg") if isinstance(privacy, dict) else None
        if not isinstance(dp, dict):
            bad.append("privacy.dp missing or not a dict")
        else:
            _typed(dp, PRIVACY_DP_SCHEMA, "privacy.dp", bad)
            pclients = dp.get("clients")
            if isinstance(pclients, dict):
                for name in sorted(pclients):
                    rec = pclients[name]
                    where = f"privacy.dp.clients[{name!r}]"
                    if not isinstance(rec, dict):
                        bad.append(f"{where} not a dict")
                        continue
                    _typed(rec, PRIVACY_CLIENT_SCHEMA, where, bad)
                    eps = rec.get("epsilon")
                    if isinstance(eps, (int, float)) and not (
                        math.isfinite(eps) and eps >= 0.0
                    ):
                        bad.append(f"{where}.epsilon not finite-nonnegative")
                # The headline must AGREE with the per-client ledger: a
                # max_epsilon that is not the max of its own rows is a
                # privacy accounting bug, the one class this report exists
                # to catch.
                worst = max(
                    (
                        float(r.get("epsilon", 0.0))
                        for r in pclients.values()
                        if isinstance(r, dict)
                        and isinstance(r.get("epsilon"), (int, float))
                    ),
                    default=0.0,
                )
                got = dp.get("max_epsilon")
                if isinstance(got, (int, float)) and not math.isclose(
                    float(got), worst, rel_tol=1e-9, abs_tol=1e-9
                ):
                    bad.append(
                        f"privacy.dp.max_epsilon {got} != per-client max "
                        f"{worst}"
                    )
        if not isinstance(sa, dict):
            bad.append("privacy.secagg missing or not a dict")
        else:
            _typed(sa, PRIVACY_SECAGG_SCHEMA, "privacy.secagg", bad)
    drift = report.get("drift")
    if drift is not None:
        psis = drift.get("psi") if isinstance(drift, dict) else None
        if not isinstance(psis, dict):
            bad.append("drift.psi missing or not a dict")
        else:
            for key in sorted(psis):
                v = psis[key]
                if "/" not in key:
                    bad.append(f"drift.psi[{key!r}] not '<bucket>/<signal>'")
                if not isinstance(v, (int, float)) or not math.isfinite(v):
                    bad.append(f"drift.psi[{key!r}] non-finite")
    return bad


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fedcrack_tpu.tools.health_report", description=__doc__
    )
    p.add_argument("--ledger", required=True, help="ledger JSONL path")
    p.add_argument("--canary", default="", help="canary history JSON path")
    p.add_argument("--drift", default="", help="drift profile JSON path")
    p.add_argument(
        "--privacy", default="",
        help="privacy summary JSON path (server.py --privacy-summary)",
    )
    p.add_argument("--out", default="", help="write the joined report here")
    args = p.parse_args(argv)
    report = build_report(
        args.ledger, args.canary or None, args.drift or None,
        args.privacy or None,
    )
    violations = validate_report(report)
    payload = json.dumps(report, indent=1, sort_keys=True)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload + "\n")
        print(f"wrote {args.out}")
        print(json.dumps(report["summary"], indent=1, sort_keys=True))
    else:
        print(payload)
    if violations:
        for v in violations:
            print(f"SCHEMA {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
