"""Closed/open-loop load generator for the serving plane.

Drives ``fedcrack.ServePlane/Predict`` (serve/service.py) with synthetic
crack images and reports a machine-readable summary: completed/dropped
counts, client-side latency percentiles (p50/p95/p99 via the same bounded
reservoir the server uses), throughput, per-bucket traffic, and the set of
model versions observed — the last is how a harness proves a live hot-swap
actually landed mid-run.

Modes:

- **closed** (default): ``concurrency`` workers, each with its own stream,
  one request in flight per worker — latency under a fixed multiprogramming
  level (the classic closed-loop SLO probe).
- **open**: requests injected on the arrival schedule at ``rate_rps``
  regardless of completions, dealt round-robin over ``concurrency``
  parallel streams (the server handles one request per stream at a time,
  so multiple streams are what lets an open-loop run actually outpace the
  service rate) — the overload-behavior probe; a server that falls behind
  shows it as growing latency or loud sheds, never as drops.

Arrival profiles (round 17, open mode): ``--profile const`` keeps the fixed
injection rate; ``ramp`` steps the rate through 0.25x/0.5x/1x/2x of
``rate_rps`` (equal request counts per phase, seeded Poisson gaps) and
``diurnal`` replays a compressed day (night/morning/peak/evening at
0.2x/1x/1.8x/0.8x). Both are the load shapes that prove the fleet's
admission control: the summary reports shed requests (``RESOURCE_EXHAUSTED``
responses — counted separately from rejects and NEVER as drops; a shed
client got a loud answer) and client-side p50/p95/p99 PER PHASE, so an
artifact shows latency held inside SLO at 1x while the 2x/peak phase shed
the overflow instead of melting.

``--swap-statefile``/``--swap-after`` publish new weights (a bumped
``model_version`` statefile, ``serve.hot_swap.publish_statefile``) after the
N-th completion — a one-command serve-while-training smoke against a server
watching that path.

``--metrics-url`` (round 22) points at the server's Prometheus endpoint;
a background sampler polls it through the run and the summary gains a
``fleet`` block — ``serve_fleet_replicas`` min/max/first/last plus the
full sample track — which is how the elastic-fleet smoke proves the
autoscaler actually resized the fleet under the diurnal profile (the
``replicas_varied`` flag) without reaching into server internals.

Masks can be dumped as PNGs (``--out-dir``) and piped straight into
``tools/quantify.py --pred-dir`` — the reference's contour quantification
over served output.
"""

from __future__ import annotations

import json
import threading
import time
from queue import Empty, Queue
from typing import Sequence

import numpy as np

from fedcrack_tpu.obs.metrics import StreamingPercentiles
from fedcrack_tpu.transport import transport_pb2 as pb
from fedcrack_tpu.transport.service import channel_options
from fedcrack_tpu.serve.service import OK, PREDICT_PATH, SHED, STREAM_PATH

_STOP = object()

# (phase name, rate multiplier) sequences for the seeded arrival profiles.
RAMP_PHASES = (
    ("ramp_0.25x", 0.25),
    ("ramp_0.5x", 0.5),
    ("ramp_1x", 1.0),
    ("ramp_2x", 2.0),
)
DIURNAL_PHASES = (
    ("diurnal_night", 0.2),
    ("diurnal_morning", 1.0),
    ("diurnal_peak", 1.8),
    ("diurnal_evening", 0.8),
)
PROFILES = ("const", "ramp", "diurnal", "video")


def arrival_schedule(
    profile: str, n: int, rate_rps: float, seed: int = 0
) -> tuple[list[float], list[int], list[dict]]:
    """Seeded send schedule for ``n`` open-loop requests.

    Returns ``(offsets_s, phase_of, phase_meta)``: per-request send offsets
    from the run start (strictly non-decreasing), each request's phase
    index, and per-phase metadata (name, target rate, request count). Same
    (profile, n, rate_rps, seed) -> same schedule, so a shed-count artifact
    is replayable. ``const`` uses fixed periods (the pre-r17 behavior);
    ``ramp``/``diurnal`` draw exponential inter-arrival gaps (Poisson
    arrivals) at each phase's target rate from one seeded rng."""
    import random

    if profile not in PROFILES:
        raise ValueError(f"profile must be one of {PROFILES}, got {profile!r}")
    if profile == "video":
        raise ValueError(
            "video is a session profile (StreamPredict), not an arrival "
            "schedule; run_load dispatches it before scheduling"
        )
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if profile == "const":
        period = 1.0 / rate_rps
        offsets = [i * period for i in range(n)]
        return (
            offsets,
            [0] * n,
            [{"phase": "const", "target_rps": rate_rps, "requests": n}],
        )
    phases = RAMP_PHASES if profile == "ramp" else DIURNAL_PHASES
    per = [n // len(phases)] * len(phases)
    per[-1] += n - sum(per)
    rng = random.Random(f"load_gen/{profile}/{seed}")
    offsets: list[float] = []
    phase_of: list[int] = []
    meta: list[dict] = []
    t = 0.0
    for pi, ((name, mult), count) in enumerate(zip(phases, per)):
        rate = rate_rps * mult
        meta.append(
            {"phase": name, "target_rps": round(rate, 3), "requests": count}
        )
        for _ in range(count):
            offsets.append(t)
            phase_of.append(pi)
            t += rng.expovariate(rate)
    return offsets, phase_of, meta


def make_images(
    n: int, sizes: Sequence[int], seed: int = 0
) -> list[np.ndarray]:
    """n uint8 RGB crack images cycling through ``sizes`` — request i gets
    size ``sizes[i % len(sizes)]``, so any n >= 2*len(sizes) exercises every
    bucket."""
    from fedcrack_tpu.data.pipeline import to_uint8_transport
    from fedcrack_tpu.data.synthetic import synth_crack_batch

    per_size: dict[int, list[np.ndarray]] = {}
    for si, size in enumerate(sizes):
        count = len(range(si, n, len(sizes)))
        if not count:
            continue
        imgs_f, msks_f = synth_crack_batch(count, img_size=size, seed=seed + si)
        imgs_u8, _ = to_uint8_transport(imgs_f, msks_f)
        per_size[size] = list(imgs_u8)
    out = []
    for i in range(n):
        size = sizes[i % len(sizes)]
        out.append(per_size[size].pop())
    return out


def make_frame_sequence(
    n_frames: int, size: int, motion_fraction: float, seed: int = 0
) -> list[np.ndarray]:
    """A seeded correlated video sequence: frame 0 is a synthetic crack
    image, each later frame copies its predecessor and rewrites a contiguous
    row band of ``motion_fraction * size`` rows at a moving offset — the
    motion band a vehicle-mounted camera produces. ``motion_fraction`` 0 is
    a static camera (all frames byte-identical), 1.0 rewrites the whole
    frame every time (zero exploitable coherence). Same (n_frames, size,
    motion_fraction, seed) -> same bytes."""
    if n_frames < 1:
        raise ValueError(f"n_frames must be >= 1, got {n_frames}")
    if not 0.0 <= motion_fraction <= 1.0:
        raise ValueError(
            f"motion_fraction must be in [0, 1], got {motion_fraction}"
        )
    rng = np.random.default_rng(seed)
    base = make_images(1, (size,), seed)[0]
    frames = [base]
    band = int(round(motion_fraction * size))
    for t in range(1, n_frames):
        f = frames[-1].copy()
        if band > 0:
            r0 = (t * band) % max(1, size - band + 1)
            f[r0 : r0 + band] = rng.integers(
                0, 256, (band, size, 3), dtype=np.uint8
            )
        frames.append(f)
    return frames


def _request_chunks(
    request_id: int,
    image: np.ndarray,
    *,
    threshold: float,
    deadline_ms: float,
    chunk_bytes: int,
    crc: bool,
):
    """LogChunk-style framing of one image (offset/last + optional CRC32C)."""
    h, w, c = image.shape
    blob = image.tobytes()
    n = max(1, chunk_bytes)
    for off in range(0, len(blob), n):
        piece = blob[off : off + n]
        msg = pb.PredictRequest(
            client_id="load_gen",
            request_id=request_id,
            height=h,
            width=w,
            channels=c,
            image=piece,
            offset=off,
            last=off + n >= len(blob),
            threshold=threshold,
            deadline_ms=deadline_ms,
        )
        if crc:
            from fedcrack_tpu.native import crc32c

            msg.crc32c = crc32c(piece)
        yield msg


class _Collector:
    """Thread-safe result aggregation shared by all workers."""

    def __init__(self, phase_meta: list[dict] | None = None):
        self.lock = threading.Lock()
        self.latency = StreamingPercentiles(8192)
        self.completed = 0
        self.rejected = 0
        self.shed = 0
        self.deadline_missed = 0
        self.per_size: dict[str, int] = {}
        self.versions: dict[str, int] = {}
        self.server_latency = StreamingPercentiles(8192)
        self.masks: list[tuple[int, int, int, bytes]] = []
        # Per-phase accounting (round 17 profiles): one slot per phase of
        # the arrival schedule — completions, sheds and a client-side
        # latency reservoir each.
        self.phases = [
            {
                "meta": m,
                "completed": 0,
                "shed": 0,
                "rejected": 0,
                "latency": StreamingPercentiles(4096),
            }
            for m in (phase_meta or [])
        ]

    def record(
        self,
        resp: pb.PredictResponse,
        latency_s: float,
        keep_mask: bool,
        phase: int | None = None,
    ):
        with self.lock:
            slot = (
                self.phases[phase]
                if phase is not None and phase < len(self.phases)
                else None
            )
            if resp.status == SHED:
                # A shed is a LOUD answer, not a drop: counted apart from
                # rejects so an artifact can say "admission control fired
                # N times" instead of "N requests failed".
                self.shed += 1
                if slot is not None:
                    slot["shed"] += 1
                return
            if resp.status != OK:
                self.rejected += 1
                if slot is not None:
                    slot["rejected"] += 1
                return
            self.completed += 1
            self.latency.add(latency_s * 1e3)
            self.server_latency.add(resp.latency_ms)
            if slot is not None:
                slot["completed"] += 1
                slot["latency"].add(latency_s * 1e3)
            key = f"{resp.height}x{resp.width}"
            self.per_size[key] = self.per_size.get(key, 0) + 1
            v = str(resp.model_version)
            self.versions[v] = self.versions.get(v, 0) + 1
            if keep_mask:
                self.masks.append(
                    (int(resp.request_id), resp.height, resp.width, resp.mask)
                )

    def per_phase_summary(self) -> list[dict] | None:
        with self.lock:
            if not self.phases:
                return None
            out = []
            for slot in self.phases:
                s = slot["latency"].summary()
                out.append(
                    {
                        **slot["meta"],
                        "completed": slot["completed"],
                        "shed": slot["shed"],
                        "rejected": slot["rejected"],
                        "latency_ms": {
                            k: s[k] for k in ("count", "p50", "p95", "p99")
                        },
                    }
                )
            return out


class _MetricsSampler:
    """Poll a /metrics endpoint through a load run (round 22).

    Samples ``serve_fleet_replicas`` (and the rolling p95 gauge when
    present) every ``interval_s`` on a daemon thread. Scrape failures are
    counted, never raised — a load run must not die because the metrics
    port lagged. The summary's ``replicas_varied`` flag is the elastic
    smoke's proof that the fleet actually resized mid-run."""

    def __init__(self, url: str, interval_s: float = 0.5):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.url = url
        self.interval_s = interval_s
        self.samples: list[dict] = []
        self.errors = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._t0 = time.perf_counter()

    def sample_once(self) -> None:
        from fedcrack_tpu.obs.promexp import sample_value, scrape

        try:
            parsed = scrape(self.url, timeout_s=self.interval_s + 5.0)
        except Exception:
            with self._lock:
                self.errors += 1
            return
        replicas = sample_value(parsed, "serve_fleet_replicas")
        p95_s = sample_value(parsed, "serve_rolling_p95_seconds")
        with self._lock:
            self.samples.append(
                {
                    "t_s": round(time.perf_counter() - self._t0, 3),
                    "replicas": int(replicas) if replicas is not None else None,
                    "p95_ms": round(p95_s * 1e3, 3) if p95_s is not None else None,
                }
            )

    def start(self) -> None:
        if self._thread is not None:
            return
        self._t0 = time.perf_counter()

        def loop():
            self.sample_once()  # t=0 baseline before traffic lands
            while not self._stop.wait(self.interval_s):
                self.sample_once()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10)
            self._thread = None
        self.sample_once()  # final state after the run drained

    def summary(self) -> dict:
        with self._lock:
            samples = list(self.samples)
            errors = self.errors
        track = [s["replicas"] for s in samples if s["replicas"] is not None]
        return {
            "url": self.url,
            "interval_s": self.interval_s,
            "samples": len(samples),
            "scrape_errors": errors,
            "replicas_min": min(track) if track else None,
            "replicas_max": max(track) if track else None,
            "replicas_first": track[0] if track else None,
            "replicas_last": track[-1] if track else None,
            "replicas_varied": bool(track) and min(track) != max(track),
            "track": samples,
        }


def _stream_call(channel):
    return channel.stream_stream(
        PREDICT_PATH,
        request_serializer=pb.PredictRequest.SerializeToString,
        response_deserializer=pb.PredictResponse.FromString,
    )


def _video_call(channel):
    return channel.stream_stream(
        STREAM_PATH,
        request_serializer=pb.StreamRequest.SerializeToString,
        response_deserializer=pb.StreamResponse.FromString,
    )


def _frame_chunks(stream_id, frame_id, image, *, chunk_bytes, crc):
    """LogChunk-style framing of one video frame over StreamRequest."""
    blob = image.tobytes()
    n = max(1, chunk_bytes)
    for off in range(0, len(blob), n):
        piece = blob[off : off + n]
        f = pb.StreamFrame(
            frame_id=frame_id,
            image=piece,
            offset=off,
            last=off + n >= len(blob),
        )
        if crc:
            from fedcrack_tpu.native import crc32c

            f.crc32c = crc32c(piece)
        yield pb.StreamRequest(stream_id=stream_id, frame=f)


def _predict_once(predict_stub, rid: int, image: np.ndarray, opts: dict):
    """One stateless Predict of ``image`` on a fresh RPC (the identity-audit
    reference call); returns the PredictResponse or None."""
    msgs = list(
        _request_chunks(
            rid,
            image,
            threshold=opts["threshold"],
            deadline_ms=0.0,
            chunk_bytes=opts["chunk_bytes"],
            crc=opts["crc"],
        )
    )
    try:
        return next(predict_stub(iter(msgs)))
    except StopIteration:
        return None


class _VideoStats:
    """Thread-safe aggregation across video stream workers."""

    def __init__(self):
        self.lock = threading.Lock()
        self.frames_sent = 0
        self.frames_completed = 0
        self.frames_rejected = 0
        self.tiles_total = 0
        self.tiles_computed = 0
        self.cache_hits = 0
        self.full_reruns = 0
        self.open_failed = 0
        self.versions: dict[str, int] = {}
        self.latency = StreamingPercentiles(8192)
        # Wire-level byte-identity audit: sampled frames re-served through
        # the STATELESS Predict RPC and compared mask-for-mask. Masks are
        # only comparable when both answers came from the SAME model
        # version (a hot swap between the two calls legitimately changes
        # the output) — those samples count as version_skipped, not failed.
        self.audit = {
            "checked": 0,
            "matched": 0,
            "mismatched": 0,
            "version_skipped": 0,
        }

    def summary(self, streams: int, frames_per_stream: int, mf: float) -> dict:
        with self.lock:
            t, c = self.tiles_total, self.tiles_computed
            audit = dict(self.audit)
            audit["ok"] = audit["mismatched"] == 0
            return {
                "streams": streams,
                "frames_per_stream": frames_per_stream,
                "motion_fraction": mf,
                "frames_sent": self.frames_sent,
                "frames_completed": self.frames_completed,
                "frames_rejected": self.frames_rejected,
                "dropped": (
                    self.frames_sent
                    - self.frames_completed
                    - self.frames_rejected
                ),
                "open_failed": self.open_failed,
                "tiles_total": t,
                "tiles_computed": c,
                "cache_hits": self.cache_hits,
                "full_reruns": self.full_reruns,
                "hit_ratio": round(self.cache_hits / t, 4) if t else 0.0,
                "effective_speedup": round(t / c, 3) if c else 1.0,
                "frame_latency_ms": self.latency.summary(),
                "versions_observed": dict(self.versions),
                "audit": audit,
            }


def _video_stream(
    channel,
    stream_id: str,
    frames: list[np.ndarray],
    stats: _VideoStats,
    opts: dict,
    audit_every: int,
    on_complete,
) -> None:
    """Drive one StreamPredict session: open, feed every frame in order,
    close. Every ``audit_every``-th completed frame is re-served through the
    stateless Predict RPC on the same channel and byte-compared."""
    size = frames[0].shape[0]
    send_q: Queue = Queue()

    def request_iter():
        while True:
            item = send_q.get()
            if item is _STOP:
                return
            yield from item

    responses = _video_call(channel)(request_iter())
    predict_stub = _stream_call(channel)
    try:
        send_q.put(
            [
                pb.StreamRequest(
                    stream_id=stream_id,
                    open=pb.StreamOpen(
                        height=size,
                        width=size,
                        channels=3,
                        threshold=opts["threshold"],
                        track=opts.get("track", False),
                    ),
                )
            ]
        )
        try:
            ack = next(responses)
        except StopIteration:
            with stats.lock:
                stats.open_failed += 1
            return
        if ack.status != OK:
            with stats.lock:
                stats.open_failed += 1
            return
        for fi, frame in enumerate(frames):
            with stats.lock:
                stats.frames_sent += 1
            t0 = time.perf_counter()
            send_q.put(
                list(
                    _frame_chunks(
                        stream_id,
                        fi + 1,
                        frame,
                        chunk_bytes=opts["chunk_bytes"],
                        crc=opts["crc"],
                    )
                )
            )
            try:
                resp = next(responses)
            except StopIteration:
                return  # server ended the stream; unsent frames are drops
            lat_ms = (time.perf_counter() - t0) * 1e3
            with stats.lock:
                if resp.status != OK:
                    stats.frames_rejected += 1
                    continue
                stats.frames_completed += 1
                stats.tiles_total += resp.tiles_total
                stats.tiles_computed += resp.tiles_computed
                stats.cache_hits += resp.cache_hits
                if resp.full_rerun:
                    stats.full_reruns += 1
                v = str(resp.model_version)
                stats.versions[v] = stats.versions.get(v, 0) + 1
                stats.latency.add(lat_ms)
            if audit_every > 0 and fi % audit_every == 0:
                ref = _predict_once(predict_stub, fi + 1, frame, opts)
                with stats.lock:
                    if ref is None or ref.status != OK:
                        pass  # audit reference failed; not a stream defect
                    elif ref.model_version != resp.model_version:
                        stats.audit["version_skipped"] += 1
                    else:
                        stats.audit["checked"] += 1
                        if ref.mask == resp.mask:
                            stats.audit["matched"] += 1
                        else:
                            stats.audit["mismatched"] += 1
            if on_complete is not None:
                on_complete()
        send_q.put(
            [pb.StreamRequest(stream_id=stream_id, close=pb.StreamClose())]
        )
        try:
            next(responses)  # close ack
        except StopIteration:
            pass
    finally:
        send_q.put(_STOP)


def _closed_worker(
    stub, jobs: Queue, collector: _Collector, opts: dict, on_complete
) -> None:
    """One worker = one stream, one request in flight at a time."""

    send_q: Queue = Queue()

    def request_iter():
        while True:
            item = send_q.get()
            if item is _STOP:
                return
            yield from item

    responses = stub(request_iter())
    try:
        while True:
            try:
                request_id, image = jobs.get_nowait()
            except Empty:
                break
            t0 = time.perf_counter()
            send_q.put(
                list(
                    _request_chunks(
                        request_id,
                        image,
                        threshold=opts["threshold"],
                        deadline_ms=opts["deadline_ms"],
                        chunk_bytes=opts["chunk_bytes"],
                        crc=opts["crc"],
                    )
                )
            )
            try:
                resp = next(responses)
            except StopIteration:
                break  # server ended the stream; remaining jobs count as dropped
            collector.record(resp, time.perf_counter() - t0, opts["keep_masks"])
            if on_complete is not None:
                on_complete()
    finally:
        send_q.put(_STOP)


def _open_stream(
    stub,
    jobs: list,                # [(rid, image, offset_s)] for THIS stream
    t_start: float,
    collector: _Collector,
    opts: dict,
    phase_of: list[int],
    on_complete,
) -> None:
    """One open-loop stream: a sender injects its slice of the arrival
    schedule at ABSOLUTE offsets from the shared run start, a receiver
    drains. The server handles one request per stream at a time, so
    open-loop overload pressure comes from running SEVERAL of these in
    parallel (``concurrency`` streams) — one stream alone is throttled to
    the service latency, whatever the nominal rate."""
    send_q: Queue = Queue()
    t_sent: dict[int, float] = {}
    lock = threading.Lock()

    def request_iter():
        while True:
            item = send_q.get()
            if item is _STOP:
                return
            yield from item

    responses = stub(request_iter())

    def receiver():
        for _ in range(len(jobs)):
            try:
                resp = next(responses)
            except StopIteration:
                return
            rid = int(resp.request_id)
            with lock:
                t0 = t_sent.pop(rid, None)
            lat = (time.perf_counter() - t0) if t0 is not None else 0.0
            collector.record(
                resp,
                lat,
                opts["keep_masks"],
                phase=phase_of[rid] if rid < len(phase_of) else None,
            )
            if on_complete is not None:
                on_complete()

    rx = threading.Thread(target=receiver, daemon=True)
    rx.start()
    for rid, image, offset in jobs:
        t_target = t_start + offset
        now = time.perf_counter()
        if now < t_target:
            time.sleep(t_target - now)
        with lock:
            t_sent[rid] = time.perf_counter()
        send_q.put(
            list(
                _request_chunks(
                    rid,
                    image,
                    threshold=opts["threshold"],
                    deadline_ms=opts["deadline_ms"],
                    chunk_bytes=opts["chunk_bytes"],
                    crc=opts["crc"],
                )
            )
        )
    rx.join(timeout=opts["timeout_s"])
    send_q.put(_STOP)


def _open_loop(
    make_stub,
    images: list,
    collector: _Collector,
    opts: dict,
    offsets: list[float],
    phase_of: list[int],
    on_complete,
    n_streams: int = 1,
) -> None:
    """Open-loop injection over ``n_streams`` parallel streams: requests
    are dealt round-robin (each keeps its ABSOLUTE schedule offset, so the
    aggregate arrival process matches the profile), and each stream runs an
    independent sender/receiver pair."""
    n_streams = max(1, n_streams)
    per_stream: list[list] = [[] for _ in range(n_streams)]
    for rid, image in enumerate(images):
        per_stream[rid % n_streams].append((rid, image, offsets[rid]))
    t_start = time.perf_counter()
    threads = [
        threading.Thread(
            target=_open_stream,
            args=(make_stub(), jobs, t_start, collector, opts, phase_of, on_complete),
            daemon=True,
        )
        for jobs in per_stream
        if jobs
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + opts["timeout_s"]
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.monotonic()))


def run_load(
    target: str,
    *,
    mode: str = "closed",
    n_requests: int = 64,
    concurrency: int = 4,
    rate_rps: float = 50.0,
    profile: str = "const",
    sizes: Sequence[int] = (128,),
    seed: int = 0,
    threshold: float = 0.5,
    deadline_ms: float = 0.0,
    chunk_bytes: int = 1 << 20,
    crc: bool = True,
    timeout_s: float = 300.0,
    keep_masks: bool = False,
    max_message_mb: int = 64,
    on_complete=None,
    streams: int = 2,
    frames_per_stream: int = 16,
    motion_fraction: float = 0.1,
    video_size: int = 320,
    audit_every: int = 4,
    track: bool = False,
    metrics_url: str | None = None,
    metrics_interval_s: float = 0.5,
) -> dict:
    """Drive the endpoint; returns the JSON-safe summary (see module doc).
    ``on_complete()`` fires after every completed request — harnesses hook
    swap triggers on it.

    ``--profile video`` (round 19) is a SESSION profile, not an arrival
    schedule: ``streams`` StreamPredict sessions each feed
    ``frames_per_stream`` seeded correlated frames (``motion_fraction``
    controls the moving row band) while ``n_requests`` ordinary still
    requests run closed-loop through the same front door — mixed traffic
    over one router. Every ``audit_every``-th frame is also served through
    the stateless Predict RPC and byte-compared (the wire-level identity
    audit); the ``video`` summary block carries cache hit ratio, effective
    speedup (tiles_total/tiles_computed) and the audit verdict."""
    import grpc

    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be 'closed' or 'open', got {mode!r}")
    sampler = None
    if metrics_url:
        sampler = _MetricsSampler(metrics_url, metrics_interval_s)
        sampler.start()
    if profile == "video":
        return _attach_fleet(sampler, _run_video_load(
            target,
            n_requests=n_requests,
            concurrency=concurrency,
            sizes=sizes,
            seed=seed,
            threshold=threshold,
            deadline_ms=deadline_ms,
            chunk_bytes=chunk_bytes,
            crc=crc,
            timeout_s=timeout_s,
            keep_masks=keep_masks,
            max_message_mb=max_message_mb,
            on_complete=on_complete,
            streams=streams,
            frames_per_stream=frames_per_stream,
            motion_fraction=motion_fraction,
            video_size=video_size,
            audit_every=audit_every,
            track=track,
        ))
    if profile != "const" and mode != "open":
        raise ValueError(
            f"profile {profile!r} needs open-loop injection (--mode open); "
            "closed-loop pacing is completion-driven"
        )
    images = make_images(n_requests, sizes, seed)
    offsets, phase_of, phase_meta = arrival_schedule(
        profile, n_requests, rate_rps, seed
    )
    collector = _Collector(phase_meta if mode == "open" else None)
    opts = {
        "threshold": threshold,
        "deadline_ms": deadline_ms,
        "chunk_bytes": chunk_bytes,
        "crc": crc,
        "timeout_s": timeout_s,
        "keep_masks": keep_masks,
    }
    channel = grpc.insecure_channel(target, options=channel_options(max_message_mb))
    t_start = time.perf_counter()
    try:
        grpc.channel_ready_future(channel).result(timeout=30)
        stub = _stream_call(channel)
        if mode == "closed":
            jobs: Queue = Queue()
            for rid, image in enumerate(images):
                jobs.put((rid, image))
            workers = [
                threading.Thread(
                    target=_closed_worker,
                    args=(stub, jobs, collector, opts, on_complete),
                    daemon=True,
                )
                for _ in range(max(1, concurrency))
            ]
            for w in workers:
                w.start()
            deadline = time.monotonic() + timeout_s
            for w in workers:
                w.join(timeout=max(0.0, deadline - time.monotonic()))
        else:
            _open_loop(
                lambda: _stream_call(channel),
                images,
                collector,
                opts,
                offsets,
                phase_of,
                on_complete,
                n_streams=max(1, concurrency),
            )
    finally:
        channel.close()
    wall_s = time.perf_counter() - t_start

    with collector.lock:
        completed = collector.completed
        rejected = collector.rejected
        shed = collector.shed
        per_size = dict(collector.per_size)
        versions = dict(collector.versions)
    return _attach_fleet(sampler, {
        "mode": mode,
        "target": target,
        "n_requests": n_requests,
        "completed": completed,
        "rejected": rejected,
        "shed": shed,
        "dropped": n_requests - completed - rejected - shed,
        "wall_s": round(wall_s, 3),
        "throughput_rps": round(completed / wall_s, 3) if wall_s > 0 else None,
        "concurrency": concurrency,
        "rate_rps": rate_rps if mode == "open" else None,
        "profile": profile,
        "per_phase": collector.per_phase_summary(),
        "sizes": list(sizes),
        "per_size": per_size,
        "versions_observed": versions,
        "latency_ms": collector.latency.summary(),
        "server_latency_ms": collector.server_latency.summary(),
        "masks": collector.masks if keep_masks else None,
    })


def _attach_fleet(sampler: _MetricsSampler | None, summary: dict) -> dict:
    """Stop the metrics sampler (if any) and attach its ``fleet`` block."""
    if sampler is not None:
        sampler.stop()
        summary["fleet"] = sampler.summary()
    else:
        summary["fleet"] = None
    return summary


def _run_video_load(
    target: str,
    *,
    n_requests: int,
    concurrency: int,
    sizes: Sequence[int],
    seed: int,
    threshold: float,
    deadline_ms: float,
    chunk_bytes: int,
    crc: bool,
    timeout_s: float,
    keep_masks: bool,
    max_message_mb: int,
    on_complete,
    streams: int,
    frames_per_stream: int,
    motion_fraction: float,
    video_size: int,
    audit_every: int,
    track: bool,
) -> dict:
    """The ``--profile video`` driver: ``streams`` video sessions plus
    ``n_requests`` closed-loop stills through the same server/channel."""
    import grpc

    if streams < 1:
        raise ValueError(f"streams must be >= 1, got {streams}")
    if frames_per_stream < 1:
        raise ValueError(
            f"frames_per_stream must be >= 1, got {frames_per_stream}"
        )
    if video_size < 1:
        raise ValueError(f"video_size must be >= 1, got {video_size}")
    sequences = [
        make_frame_sequence(
            frames_per_stream, video_size, motion_fraction, seed + si
        )
        for si in range(streams)
    ]
    still_images = make_images(n_requests, sizes, seed) if n_requests else []
    collector = _Collector()
    stats = _VideoStats()
    opts = {
        "threshold": threshold,
        "deadline_ms": deadline_ms,
        "chunk_bytes": chunk_bytes,
        "crc": crc,
        "timeout_s": timeout_s,
        "keep_masks": keep_masks,
        "track": track,
    }
    channel = grpc.insecure_channel(target, options=channel_options(max_message_mb))
    t_start = time.perf_counter()
    try:
        grpc.channel_ready_future(channel).result(timeout=30)
        video_threads = [
            threading.Thread(
                target=_video_stream,
                args=(
                    channel,
                    f"video-{si}",
                    sequences[si],
                    stats,
                    opts,
                    audit_every,
                    on_complete,
                ),
                daemon=True,
            )
            for si in range(streams)
        ]
        still_threads = []
        if still_images:
            stub = _stream_call(channel)
            jobs: Queue = Queue()
            for rid, image in enumerate(still_images):
                jobs.put((rid, image))
            still_threads = [
                threading.Thread(
                    target=_closed_worker,
                    args=(stub, jobs, collector, opts, on_complete),
                    daemon=True,
                )
                for _ in range(max(1, concurrency))
            ]
        for t in video_threads + still_threads:
            t.start()
        deadline = time.monotonic() + timeout_s
        for t in video_threads + still_threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
    finally:
        channel.close()
    wall_s = time.perf_counter() - t_start

    with collector.lock:
        completed = collector.completed
        rejected = collector.rejected
        shed = collector.shed
        per_size = dict(collector.per_size)
        versions = dict(collector.versions)
    video = stats.summary(streams, frames_per_stream, motion_fraction)
    frames_done = video["frames_completed"]
    # Effective img/s: completed frames scaled by the work a stateless
    # server would have done for them (tiles_total / tiles_computed) —
    # the ~1/(changed-tile-fraction) model, measured on the wire.
    video["frames_per_s"] = (
        round(frames_done / wall_s, 3) if wall_s > 0 else None
    )
    video["effective_frames_per_s"] = (
        round(frames_done * video["effective_speedup"] / wall_s, 3)
        if wall_s > 0
        else None
    )
    return {
        "mode": "video",
        "target": target,
        "n_requests": n_requests,
        "completed": completed,
        "rejected": rejected,
        "shed": shed,
        "dropped": n_requests - completed - rejected - shed,
        "wall_s": round(wall_s, 3),
        "throughput_rps": round(completed / wall_s, 3) if wall_s > 0 else None,
        "concurrency": concurrency,
        "rate_rps": None,
        "profile": "video",
        "per_phase": None,
        "sizes": list(sizes),
        "per_size": per_size,
        "versions_observed": versions,
        "latency_ms": collector.latency.summary(),
        "server_latency_ms": collector.server_latency.summary(),
        "masks": collector.masks if keep_masks else None,
        "video": video,
    }


def write_masks(masks, out_dir: str) -> int:
    """Dump (request_id, h, w, bytes) masks as PNGs for tools/quantify.py
    --pred-dir; returns how many were written."""
    import os

    import cv2

    os.makedirs(out_dir, exist_ok=True)
    n = 0
    for rid, h, w, blob in masks:
        mask = np.frombuffer(blob, np.uint8).reshape(h, w)
        cv2.imwrite(os.path.join(out_dir, f"mask_{rid:05d}.png"), mask)
        n += 1
    return n


def main(argv=None) -> int:
    import argparse

    from fedcrack_tpu.serve.hot_swap import publish_statefile

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--target", default="127.0.0.1:8890", help="host:port")
    p.add_argument("--mode", choices=["closed", "open"], default="closed")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--rate-rps", type=float, default=50.0)
    p.add_argument(
        "--profile",
        choices=list(PROFILES),
        default="const",
        help="open-loop arrival profile: const (fixed rate), ramp "
        "(0.25x->2x rate steps), diurnal (compressed-day replay); seeded. "
        "'video' is a session profile instead: StreamPredict sessions with "
        "seeded correlated frames mixed with closed-loop stills",
    )
    p.add_argument(
        "--streams", type=int, default=2,
        help="video profile: concurrent StreamPredict sessions",
    )
    p.add_argument(
        "--frames", type=int, default=16,
        help="video profile: frames per stream",
    )
    p.add_argument(
        "--motion-fraction", type=float, default=0.1,
        help="video profile: fraction of frame rows rewritten per frame "
        "(0 = static camera, 1 = zero frame coherence)",
    )
    p.add_argument(
        "--video-size", type=int, default=320,
        help="video profile: square frame edge in px (multi-tile frames "
        "need this larger than the server's largest bucket)",
    )
    p.add_argument(
        "--audit-every", type=int, default=4,
        help="video profile: byte-compare every Nth frame against the "
        "stateless Predict RPC (0 disables the identity audit)",
    )
    p.add_argument(
        "--track", action="store_true",
        help="video profile: enable server-side crack-track continuity",
    )
    p.add_argument(
        "--metrics-url",
        help="poll this Prometheus endpoint during the run and report the "
        "serve_fleet_replicas track (min/max/varied) in the summary's "
        "'fleet' block — the elastic-fleet smoke's proof the autoscaler "
        "resized the fleet",
    )
    p.add_argument(
        "--metrics-interval-s", type=float, default=0.5,
        help="seconds between --metrics-url scrapes",
    )
    p.add_argument("--sizes", default="128", help="comma-separated request sizes")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--deadline-ms", type=float, default=0.0)
    p.add_argument("--timeout-s", type=float, default=300.0)
    p.add_argument("--out-dir", help="write served masks as PNGs here")
    p.add_argument(
        "--swap-statefile",
        help="publish new weights to this statefile mid-run (live hot-swap smoke)",
    )
    p.add_argument("--swap-after", type=int, default=0,
                   help="publish the swap after N completed requests")
    p.add_argument("--swap-version", type=int, default=1000)
    p.add_argument("--swap-seed", type=int, default=1)
    p.add_argument("--img-size", type=int, default=128,
                   help="model config size for --swap-statefile weights init")
    p.add_argument(
        "--swap-config",
        help="FedConfig JSON whose model section shapes the --swap-statefile "
        "weights (the published tree must match the SERVED model; overrides "
        "--img-size)",
    )
    args = p.parse_args(argv)

    sizes = tuple(int(s) for s in args.sizes.split(",") if s.strip())

    swap_state = {"fired": False, "count": 0}
    swap_blob = None
    if args.swap_statefile:
        # Encode the swap weights BEFORE the run: serializing a full model
        # at trigger time costs seconds under load and would push the
        # publish past the end of the run.
        import jax

        from fedcrack_tpu.configs import FedConfig, ModelConfig
        from fedcrack_tpu.fed.serialization import tree_to_bytes
        from fedcrack_tpu.models.resunet import init_variables

        if args.swap_config:
            with open(args.swap_config) as f:
                swap_model = FedConfig.from_json(f.read()).model
        else:
            swap_model = ModelConfig(img_size=args.img_size)
        swap_blob = tree_to_bytes(
            init_variables(jax.random.key(args.swap_seed), swap_model)
        )

    def on_complete():
        swap_state["count"] += 1
        if (
            not swap_state["fired"]
            and swap_state["count"] >= args.swap_after > 0
        ):
            swap_state["fired"] = True
            publish_statefile(
                args.swap_statefile, model_version=args.swap_version, blob=swap_blob
            )

    summary = run_load(
        args.target,
        mode=args.mode,
        n_requests=args.requests,
        concurrency=args.concurrency,
        rate_rps=args.rate_rps,
        profile=args.profile,
        sizes=sizes,
        seed=args.seed,
        threshold=args.threshold,
        deadline_ms=args.deadline_ms,
        timeout_s=args.timeout_s,
        keep_masks=bool(args.out_dir),
        on_complete=on_complete if args.swap_statefile else None,
        streams=args.streams,
        frames_per_stream=args.frames,
        motion_fraction=args.motion_fraction,
        video_size=args.video_size,
        audit_every=args.audit_every,
        track=args.track,
        metrics_url=args.metrics_url,
        metrics_interval_s=args.metrics_interval_s,
    )
    masks = summary.pop("masks", None)
    if args.out_dir and masks:
        summary["masks_written"] = write_masks(masks, args.out_dir)
    summary["swap_published"] = swap_state["fired"] if args.swap_statefile else None
    print(json.dumps(summary), flush=True)
    video = summary.get("video")
    video_ok = video is None or (
        video["dropped"] == 0
        and video["open_failed"] == 0
        and video["audit"]["ok"]
    )
    return 0 if summary["dropped"] == 0 and video_ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
