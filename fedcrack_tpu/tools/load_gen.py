"""Closed/open-loop load generator for the serving plane.

Drives ``fedcrack.ServePlane/Predict`` (serve/service.py) with synthetic
crack images and reports a machine-readable summary: completed/dropped
counts, client-side latency percentiles (p50/p95/p99 via the same bounded
reservoir the server uses), throughput, per-bucket traffic, and the set of
model versions observed — the last is how a harness proves a live hot-swap
actually landed mid-run.

Modes:

- **closed** (default): ``concurrency`` workers, each with its own stream,
  one request in flight per worker — latency under a fixed multiprogramming
  level (the classic closed-loop SLO probe).
- **open**: one stream, requests injected at a fixed ``rate_rps`` regardless
  of completions (sender/receiver threads) — the overload-behavior probe; a
  server that falls behind shows it as growing latency, never as drops.

``--swap-statefile``/``--swap-after`` publish new weights (a bumped
``model_version`` statefile, ``serve.hot_swap.publish_statefile``) after the
N-th completion — a one-command serve-while-training smoke against a server
watching that path.

Masks can be dumped as PNGs (``--out-dir``) and piped straight into
``tools/quantify.py --pred-dir`` — the reference's contour quantification
over served output.
"""

from __future__ import annotations

import json
import threading
import time
from queue import Empty, Queue
from typing import Sequence

import numpy as np

from fedcrack_tpu.obs.metrics import StreamingPercentiles
from fedcrack_tpu.transport import transport_pb2 as pb
from fedcrack_tpu.transport.service import channel_options
from fedcrack_tpu.serve.service import OK, PREDICT_PATH

_STOP = object()


def make_images(
    n: int, sizes: Sequence[int], seed: int = 0
) -> list[np.ndarray]:
    """n uint8 RGB crack images cycling through ``sizes`` — request i gets
    size ``sizes[i % len(sizes)]``, so any n >= 2*len(sizes) exercises every
    bucket."""
    from fedcrack_tpu.data.pipeline import to_uint8_transport
    from fedcrack_tpu.data.synthetic import synth_crack_batch

    per_size: dict[int, list[np.ndarray]] = {}
    for si, size in enumerate(sizes):
        count = len(range(si, n, len(sizes)))
        if not count:
            continue
        imgs_f, msks_f = synth_crack_batch(count, img_size=size, seed=seed + si)
        imgs_u8, _ = to_uint8_transport(imgs_f, msks_f)
        per_size[size] = list(imgs_u8)
    out = []
    for i in range(n):
        size = sizes[i % len(sizes)]
        out.append(per_size[size].pop())
    return out


def _request_chunks(
    request_id: int,
    image: np.ndarray,
    *,
    threshold: float,
    deadline_ms: float,
    chunk_bytes: int,
    crc: bool,
):
    """LogChunk-style framing of one image (offset/last + optional CRC32C)."""
    h, w, c = image.shape
    blob = image.tobytes()
    n = max(1, chunk_bytes)
    for off in range(0, len(blob), n):
        piece = blob[off : off + n]
        msg = pb.PredictRequest(
            client_id="load_gen",
            request_id=request_id,
            height=h,
            width=w,
            channels=c,
            image=piece,
            offset=off,
            last=off + n >= len(blob),
            threshold=threshold,
            deadline_ms=deadline_ms,
        )
        if crc:
            from fedcrack_tpu.native import crc32c

            msg.crc32c = crc32c(piece)
        yield msg


class _Collector:
    """Thread-safe result aggregation shared by all workers."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latency = StreamingPercentiles(8192)
        self.completed = 0
        self.rejected = 0
        self.deadline_missed = 0
        self.per_size: dict[str, int] = {}
        self.versions: dict[str, int] = {}
        self.server_latency = StreamingPercentiles(8192)
        self.masks: list[tuple[int, int, int, bytes]] = []

    def record(self, resp: pb.PredictResponse, latency_s: float, keep_mask: bool):
        with self.lock:
            if resp.status != OK:
                self.rejected += 1
                return
            self.completed += 1
            self.latency.add(latency_s * 1e3)
            self.server_latency.add(resp.latency_ms)
            key = f"{resp.height}x{resp.width}"
            self.per_size[key] = self.per_size.get(key, 0) + 1
            v = str(resp.model_version)
            self.versions[v] = self.versions.get(v, 0) + 1
            if keep_mask:
                self.masks.append(
                    (int(resp.request_id), resp.height, resp.width, resp.mask)
                )


def _stream_call(channel):
    return channel.stream_stream(
        PREDICT_PATH,
        request_serializer=pb.PredictRequest.SerializeToString,
        response_deserializer=pb.PredictResponse.FromString,
    )


def _closed_worker(
    stub, jobs: Queue, collector: _Collector, opts: dict, on_complete
) -> None:
    """One worker = one stream, one request in flight at a time."""

    send_q: Queue = Queue()

    def request_iter():
        while True:
            item = send_q.get()
            if item is _STOP:
                return
            yield from item

    responses = stub(request_iter())
    try:
        while True:
            try:
                request_id, image = jobs.get_nowait()
            except Empty:
                break
            t0 = time.perf_counter()
            send_q.put(
                list(
                    _request_chunks(
                        request_id,
                        image,
                        threshold=opts["threshold"],
                        deadline_ms=opts["deadline_ms"],
                        chunk_bytes=opts["chunk_bytes"],
                        crc=opts["crc"],
                    )
                )
            )
            try:
                resp = next(responses)
            except StopIteration:
                break  # server ended the stream; remaining jobs count as dropped
            collector.record(resp, time.perf_counter() - t0, opts["keep_masks"])
            if on_complete is not None:
                on_complete()
    finally:
        send_q.put(_STOP)


def _open_loop(
    stub, images: list, collector: _Collector, opts: dict, rate_rps: float, on_complete
) -> None:
    """One stream; a sender injects at the target rate, a receiver drains."""
    send_q: Queue = Queue()
    t_sent: dict[int, float] = {}
    lock = threading.Lock()

    def request_iter():
        while True:
            item = send_q.get()
            if item is _STOP:
                return
            yield from item

    responses = stub(request_iter())

    def receiver():
        for _ in range(len(images)):
            try:
                resp = next(responses)
            except StopIteration:
                return
            with lock:
                t0 = t_sent.pop(int(resp.request_id), None)
            lat = (time.perf_counter() - t0) if t0 is not None else 0.0
            collector.record(resp, lat, opts["keep_masks"])
            if on_complete is not None:
                on_complete()

    rx = threading.Thread(target=receiver, daemon=True)
    rx.start()
    period = 1.0 / max(rate_rps, 1e-6)
    t_next = time.perf_counter()
    for rid, image in enumerate(images):
        now = time.perf_counter()
        if now < t_next:
            time.sleep(t_next - now)
        t_next += period
        with lock:
            t_sent[rid] = time.perf_counter()
        send_q.put(
            list(
                _request_chunks(
                    rid,
                    image,
                    threshold=opts["threshold"],
                    deadline_ms=opts["deadline_ms"],
                    chunk_bytes=opts["chunk_bytes"],
                    crc=opts["crc"],
                )
            )
        )
    rx.join(timeout=opts["timeout_s"])
    send_q.put(_STOP)


def run_load(
    target: str,
    *,
    mode: str = "closed",
    n_requests: int = 64,
    concurrency: int = 4,
    rate_rps: float = 50.0,
    sizes: Sequence[int] = (128,),
    seed: int = 0,
    threshold: float = 0.5,
    deadline_ms: float = 0.0,
    chunk_bytes: int = 1 << 20,
    crc: bool = True,
    timeout_s: float = 300.0,
    keep_masks: bool = False,
    max_message_mb: int = 64,
    on_complete=None,
) -> dict:
    """Drive the endpoint; returns the JSON-safe summary (see module doc).
    ``on_complete()`` fires after every completed request — harnesses hook
    swap triggers on it."""
    import grpc

    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be 'closed' or 'open', got {mode!r}")
    images = make_images(n_requests, sizes, seed)
    collector = _Collector()
    opts = {
        "threshold": threshold,
        "deadline_ms": deadline_ms,
        "chunk_bytes": chunk_bytes,
        "crc": crc,
        "timeout_s": timeout_s,
        "keep_masks": keep_masks,
    }
    channel = grpc.insecure_channel(target, options=channel_options(max_message_mb))
    t_start = time.perf_counter()
    try:
        grpc.channel_ready_future(channel).result(timeout=30)
        stub = _stream_call(channel)
        if mode == "closed":
            jobs: Queue = Queue()
            for rid, image in enumerate(images):
                jobs.put((rid, image))
            workers = [
                threading.Thread(
                    target=_closed_worker,
                    args=(stub, jobs, collector, opts, on_complete),
                    daemon=True,
                )
                for _ in range(max(1, concurrency))
            ]
            for w in workers:
                w.start()
            deadline = time.monotonic() + timeout_s
            for w in workers:
                w.join(timeout=max(0.0, deadline - time.monotonic()))
        else:
            _open_loop(stub, images, collector, opts, rate_rps, on_complete)
    finally:
        channel.close()
    wall_s = time.perf_counter() - t_start

    with collector.lock:
        completed = collector.completed
        rejected = collector.rejected
        per_size = dict(collector.per_size)
        versions = dict(collector.versions)
    return {
        "mode": mode,
        "target": target,
        "n_requests": n_requests,
        "completed": completed,
        "rejected": rejected,
        "dropped": n_requests - completed - rejected,
        "wall_s": round(wall_s, 3),
        "throughput_rps": round(completed / wall_s, 3) if wall_s > 0 else None,
        "concurrency": concurrency if mode == "closed" else None,
        "rate_rps": rate_rps if mode == "open" else None,
        "sizes": list(sizes),
        "per_size": per_size,
        "versions_observed": versions,
        "latency_ms": collector.latency.summary(),
        "server_latency_ms": collector.server_latency.summary(),
        "masks": collector.masks if keep_masks else None,
    }


def write_masks(masks, out_dir: str) -> int:
    """Dump (request_id, h, w, bytes) masks as PNGs for tools/quantify.py
    --pred-dir; returns how many were written."""
    import os

    import cv2

    os.makedirs(out_dir, exist_ok=True)
    n = 0
    for rid, h, w, blob in masks:
        mask = np.frombuffer(blob, np.uint8).reshape(h, w)
        cv2.imwrite(os.path.join(out_dir, f"mask_{rid:05d}.png"), mask)
        n += 1
    return n


def main(argv=None) -> int:
    import argparse

    from fedcrack_tpu.serve.hot_swap import publish_statefile

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--target", default="127.0.0.1:8890", help="host:port")
    p.add_argument("--mode", choices=["closed", "open"], default="closed")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--rate-rps", type=float, default=50.0)
    p.add_argument("--sizes", default="128", help="comma-separated request sizes")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--deadline-ms", type=float, default=0.0)
    p.add_argument("--timeout-s", type=float, default=300.0)
    p.add_argument("--out-dir", help="write served masks as PNGs here")
    p.add_argument(
        "--swap-statefile",
        help="publish new weights to this statefile mid-run (live hot-swap smoke)",
    )
    p.add_argument("--swap-after", type=int, default=0,
                   help="publish the swap after N completed requests")
    p.add_argument("--swap-version", type=int, default=1000)
    p.add_argument("--swap-seed", type=int, default=1)
    p.add_argument("--img-size", type=int, default=128,
                   help="model config size for --swap-statefile weights init")
    args = p.parse_args(argv)

    sizes = tuple(int(s) for s in args.sizes.split(",") if s.strip())

    swap_state = {"fired": False, "count": 0}
    swap_blob = None
    if args.swap_statefile:
        # Encode the swap weights BEFORE the run: serializing a full model
        # at trigger time costs seconds under load and would push the
        # publish past the end of the run.
        import jax

        from fedcrack_tpu.configs import ModelConfig
        from fedcrack_tpu.fed.serialization import tree_to_bytes
        from fedcrack_tpu.models.resunet import init_variables

        swap_blob = tree_to_bytes(
            init_variables(
                jax.random.key(args.swap_seed), ModelConfig(img_size=args.img_size)
            )
        )

    def on_complete():
        swap_state["count"] += 1
        if (
            not swap_state["fired"]
            and swap_state["count"] >= args.swap_after > 0
        ):
            swap_state["fired"] = True
            publish_statefile(
                args.swap_statefile, model_version=args.swap_version, blob=swap_blob
            )

    summary = run_load(
        args.target,
        mode=args.mode,
        n_requests=args.requests,
        concurrency=args.concurrency,
        rate_rps=args.rate_rps,
        sizes=sizes,
        seed=args.seed,
        threshold=args.threshold,
        deadline_ms=args.deadline_ms,
        timeout_s=args.timeout_s,
        keep_masks=bool(args.out_dir),
        on_complete=on_complete if args.swap_statefile else None,
    )
    masks = summary.pop("masks", None)
    if args.out_dir and masks:
        summary["masks_written"] = write_masks(masks, args.out_dir)
    summary["swap_published"] = swap_state["fired"] if args.swap_statefile else None
    print(json.dumps(summary), flush=True)
    return 0 if summary["dropped"] == 0 else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
