"""Crack quantification from predicted masks (host-side post-processing).

Capability parity with the reference's contour analysis
(reference: test/Segmentation2.py:114-144): threshold the predicted mask at
127/255, extract contours, measure per-crack area and perimeter, simplify
each contour with approxPolyDP at epsilon = 1% and 10% of the perimeter, and
write annotated overlays. The reference's client calls this at the final
round but crashes on a missing method (client_fit_model.py:215, SURVEY.md
§2.2(5)) — here it is a real module wired into the client entry point.

This stays on CPU/OpenCV by design: contour tracing is irregular,
data-dependent control flow — the wrong shape for XLA — and runs once per
session on a handful of masks (SURVEY.md §2.7).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np


@dataclass
class ContourInfo:
    area_px: float
    perimeter_px: float
    approx_points_1pct: int   # vertices of the eps=1% polygon
    approx_points_10pct: int  # vertices of the eps=10% polygon


@dataclass
class CrackStats:
    contour_count: int = 0
    total_area_px: float = 0.0
    total_perimeter_px: float = 0.0
    crack_fraction: float = 0.0  # crack pixels / image pixels
    contours: list[ContourInfo] = field(default_factory=list)


def quantify_mask(mask: np.ndarray, threshold: int = 127) -> CrackStats:
    """Measure cracks in one mask.

    ``mask``: [H, W] (or [H, W, 1]) in either {0,1} floats or 0..255 uint8.
    Threshold semantics follow the reference (>127 on the 0..255 scale,
    test/Segmentation2.py:118).
    """
    import cv2

    mask = np.asarray(mask)
    if mask.ndim == 3:
        mask = mask[..., 0]
    if mask.dtype != np.uint8:
        mask = (np.clip(mask, 0.0, 1.0) * 255).astype(np.uint8)
    _, binary = cv2.threshold(mask, threshold, 255, cv2.THRESH_BINARY)
    contours, _ = cv2.findContours(binary, cv2.RETR_TREE, cv2.CHAIN_APPROX_SIMPLE)

    stats = CrackStats(crack_fraction=float((binary > 0).mean()))
    for contour in contours:
        area = float(cv2.contourArea(contour))
        perim = float(cv2.arcLength(contour, True))
        approx1 = cv2.approxPolyDP(contour, 0.01 * perim, True)
        approx10 = cv2.approxPolyDP(contour, 0.10 * perim, True)
        stats.contours.append(
            ContourInfo(
                area_px=area,
                perimeter_px=perim,
                approx_points_1pct=len(approx1),
                approx_points_10pct=len(approx10),
            )
        )
        stats.total_area_px += area
        stats.total_perimeter_px += perim
    stats.contour_count = len(stats.contours)
    return stats


def annotate(image: np.ndarray, mask: np.ndarray, threshold: int = 127) -> np.ndarray:
    """Overlay detected crack contours on the (RGB float or uint8) image."""
    import cv2

    img = np.asarray(image)
    if img.dtype != np.uint8:
        img = (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)
    img = img.copy()
    mask = np.asarray(mask)
    if mask.ndim == 3:
        mask = mask[..., 0]
    if mask.dtype != np.uint8:
        mask = (np.clip(mask, 0.0, 1.0) * 255).astype(np.uint8)
    _, binary = cv2.threshold(mask, threshold, 255, cv2.THRESH_BINARY)
    contours, _ = cv2.findContours(binary, cv2.RETR_TREE, cv2.CHAIN_APPROX_SIMPLE)
    cv2.drawContours(img, contours, -1, (255, 0, 0), 1)
    return img


def predict_and_quantify(
    state,
    dataset,
    out_dir: str,
    threshold: float = 0.5,
    max_images: int = 8,
) -> list[dict]:
    """Final-round prediction + quantification (the reference's intended
    ``Predict`` flow, client_fit_model.py:176-223): run the trained model on
    a few batches, write predicted-mask PNGs and contour overlays, return
    per-image crack stats."""
    import cv2
    import jax

    os.makedirs(out_dir, exist_ok=True)
    reports: list[dict] = []
    done = 0
    from fedcrack_tpu.data.pipeline import normalize_images

    for images, _ in dataset:
        # Datasets may yield uint8 transport bytes (data.pipeline); the model
        # contract is float32 in [0, 1]. normalize_images keeps the values
        # bit-identical to what training saw.
        images = np.asarray(normalize_images(np.asarray(images)))
        probs = jax.device_get(
            jax.nn.sigmoid(state.apply_fn(state.variables, images, train=False))
        )
        for i in range(len(images)):
            if done >= max_images:
                return reports
            pred = (probs[i, :, :, 0] > threshold).astype(np.uint8) * 255
            cv2.imwrite(os.path.join(out_dir, f"pred_{done:03d}.png"), pred)
            overlay = annotate(images[i], pred)
            cv2.imwrite(
                os.path.join(out_dir, f"overlay_{done:03d}.png"),
                cv2.cvtColor(overlay, cv2.COLOR_RGB2BGR),
            )
            s = quantify_mask(pred)
            reports.append(
                {
                    "image": done,
                    "contours": s.contour_count,
                    "area_px": s.total_area_px,
                    "perimeter_px": s.total_perimeter_px,
                    "crack_fraction": s.crack_fraction,
                }
            )
            done += 1
    return reports


def _stats_record(name, s: CrackStats) -> dict:
    return {
        "image": name,
        "contours": s.contour_count,
        "area_px": s.total_area_px,
        "perimeter_px": s.total_perimeter_px,
        "crack_fraction": s.crack_fraction,
    }


MASK_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".tif", ".tiff")


def quantify_mask_dir(pred_dir: str, threshold: int = 127) -> dict:
    """Batch-directory mode (round 10): quantify every predicted-mask image
    in ``pred_dir`` (sorted, so output order is stable) WITHOUT a model —
    the serving plane's post-processing step pipes its returned masks (e.g.
    ``tools/load_gen.py --out-dir``) straight through this. Returns
    ``{"images": [per-image stats...], "totals": {...}}``."""
    import cv2

    if not os.path.isdir(pred_dir):
        raise ValueError(f"--pred-dir {pred_dir} is not a directory")
    names = sorted(
        n
        for n in os.listdir(pred_dir)
        if n.lower().endswith(MASK_EXTENSIONS)
    )
    if not names:
        raise ValueError(f"no mask images ({'/'.join(MASK_EXTENSIONS)}) in {pred_dir}")
    images = []
    totals = {"contours": 0, "area_px": 0.0, "perimeter_px": 0.0}
    for name in names:
        mask = cv2.imread(os.path.join(pred_dir, name), cv2.IMREAD_GRAYSCALE)
        if mask is None:
            raise ValueError(f"unreadable mask image: {name}")
        s = quantify_mask(mask, threshold=threshold)
        images.append(_stats_record(name, s))
        totals["contours"] += s.contour_count
        totals["area_px"] += s.total_area_px
        totals["perimeter_px"] += s.total_perimeter_px
    totals["images"] = len(images)
    totals["mean_crack_fraction"] = float(
        np.mean([r["crack_fraction"] for r in images])
    )
    return {"images": images, "totals": totals}


def main(argv=None) -> None:
    """``python -m fedcrack_tpu.tools.quantify`` — the reference's inference +
    crack-quantification script (test/Segmentation2.py) as a real CLI: load
    trained weights, predict masks, write overlays, print per-image stats.
    ``--pred-dir`` skips the model entirely and quantifies a directory of
    already-predicted masks (the serving plane's output); ``--out-json``
    writes the machine-readable stats in either mode."""
    import argparse
    import json

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--weights", help="msgpack pytree (best.msgpack)")
    p.add_argument("--image-dir")
    p.add_argument("--mask-dir")
    p.add_argument("--synthetic", type=int, default=0, help="use N generated samples")
    p.add_argument("--img-size", type=int, default=128)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--out-dir", default="contour")  # reference wrote contour/imgN.jpg
    p.add_argument("--max-images", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--pred-dir",
        help="batch mode: quantify every predicted-mask image in this "
        "directory (no model/weights needed)",
    )
    p.add_argument(
        "--mask-threshold", type=int, default=127,
        help="binarization threshold on the 0..255 scale (reference: >127)",
    )
    p.add_argument("--out-json", help="write machine-readable stats JSON here")
    args = p.parse_args(argv)

    if args.pred_dir:
        try:
            report = quantify_mask_dir(args.pred_dir, threshold=args.mask_threshold)
        except ValueError as e:
            p.error(str(e))
        for r in report["images"]:
            print(json.dumps(r))
        print(json.dumps({"totals": report["totals"]}))
        if args.out_json:
            with open(args.out_json, "w") as f:
                json.dump(report, f, indent=2, sort_keys=True)
        return

    if not args.weights:
        p.error("--weights is required unless --pred-dir is given")

    import jax

    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.fed.serialization import tree_from_bytes
    from fedcrack_tpu.train.local import create_train_state

    model_config = ModelConfig(img_size=args.img_size)
    state = create_train_state(jax.random.key(args.seed), model_config)
    with open(args.weights, "rb") as f:
        variables = tree_from_bytes(f.read(), template=state.variables)
    state = state.replace_variables(variables)

    from fedcrack_tpu.data.pipeline import dataset_from_source

    # Inference must see every image: drop_last=False keeps tail batches,
    # and the shared builder clamps the batch to the dataset size.
    try:
        dataset = dataset_from_source(
            args.synthetic,
            args.image_dir,
            args.mask_dir,
            img_size=args.img_size,
            batch_size=args.batch,
            seed=args.seed,
            drop_last=False,
        )
    except ValueError as e:
        p.error(str(e))

    reports = predict_and_quantify(
        state, dataset, out_dir=args.out_dir, max_images=args.max_images
    )
    for r in reports:
        print(json.dumps(r))
    if args.out_json:
        with open(args.out_json, "w") as f:
            json.dump({"images": reports}, f, indent=2, sort_keys=True)


if __name__ == "__main__":
    main()
