"""fedlint CLI — run the repo's static-analysis rule packs.

Usage (from the repo root)::

    python -m fedcrack_tpu.tools.fedlint                  # whole package
    python -m fedcrack_tpu.tools.fedlint fedcrack_tpu/serve
    python -m fedcrack_tpu.tools.fedlint --rules DET001,DUR001
    python -m fedcrack_tpu.tools.fedlint --json findings.json
    python -m fedcrack_tpu.tools.fedlint --lock-graph bench_runs/lock_graph.json
    python -m fedcrack_tpu.tools.fedlint --write-baseline fedlint_baseline.json

Exit codes (CI contract): 0 = clean, 1 = non-baselined findings, 2 = usage
or internal error. The committed ``fedlint_baseline.json`` at the repo root
is applied automatically when present (``--no-baseline`` to see everything);
the tier-1 gate test pins "zero non-baselined findings over fedcrack_tpu/".

The per-file result cache lives in ``.fedlint_cache/`` (gitignored); it is
keyed on file mtime+size and the rule-set version, so ``--no-cache`` is only
needed when hacking on the rules themselves.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from fedcrack_tpu.analysis.engine import (
    LintEngine,
    ModuleSource,
    Severity,
    apply_baseline,
    load_baseline,
    make_baseline,
)
from fedcrack_tpu.analysis.rules import all_rules, rules_by_id
from fedcrack_tpu.analysis.rules.locks import build_lock_graph

DEFAULT_BASELINE = "fedlint_baseline.json"
DEFAULT_CACHE_DIR = ".fedlint_cache"


def repo_root() -> str:
    """The directory holding the fedcrack_tpu package."""
    import fedcrack_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(fedcrack_tpu.__file__)))


def _parse_args(argv) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="fedlint", description="repo-native static analysis"
    )
    p.add_argument("paths", nargs="*", help="files/dirs to lint "
                   "(default: the fedcrack_tpu package)")
    p.add_argument("--rules", help="comma-separated rule ids to run "
                   "(default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE} at the "
                   "repo root when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", metavar="PATH",
                   help="write the current findings as the new baseline and "
                   "exit 0")
    p.add_argument("--json", metavar="PATH",
                   help="also write findings as JSON ('-' for stdout)")
    p.add_argument("--lock-graph", metavar="PATH",
                   help="emit the static lock-acquisition graph (nodes/"
                   "edges/cycles) as JSON and continue")
    p.add_argument("--cache-dir", default=None,
                   help=f"per-file cache dir (default: {DEFAULT_CACHE_DIR} "
                   "at the repo root)")
    p.add_argument("--no-cache", action="store_true")
    return p.parse_args(argv)


def main(argv=None) -> int:
    try:
        args = _parse_args(argv)
    except SystemExit as e:
        return 0 if e.code in (0, None) else 2

    if args.list_rules:
        for rule in all_rules():
            scope = ",".join(rule.paths) if rule.paths else "all files"
            print(f"{rule.id:9s} {rule.severity.name:7s} [{scope}]")
            print(f"          {rule.description}")
        return 0

    root = repo_root()
    rules = all_rules()
    if args.rules:
        catalog = rules_by_id()
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in wanted if r not in catalog]
        if unknown:
            print(f"fedlint: unknown rule ids: {', '.join(unknown)} "
                  f"(--list-rules for the catalog)", file=sys.stderr)
            return 2
        rules = [catalog[r] for r in wanted]

    paths = args.paths or [os.path.join(root, "fedcrack_tpu")]
    for pth in paths:
        if not os.path.exists(pth):
            print(f"fedlint: no such path: {pth}", file=sys.stderr)
            return 2

    cache_dir = None
    if not args.no_cache:
        cache_dir = args.cache_dir or os.path.join(root, DEFAULT_CACHE_DIR)
    engine = LintEngine(rules, cache_dir=cache_dir)

    # One walk serves both the modules and the cache's path mapping.
    abs_paths: dict[str, str] = {}
    for pth in paths:
        for fp in engine.iter_python_files(pth):
            rel = os.path.relpath(fp, root).replace(os.sep, "/")
            abs_paths[rel] = fp
    modules = []
    try:
        for rel, fp in abs_paths.items():
            with open(fp, encoding="utf-8") as f:
                modules.append(ModuleSource(rel, f.read()))
    except SyntaxError as e:
        print(f"fedlint: cannot parse {e.filename}:{e.lineno}: {e.msg}",
              file=sys.stderr)
        return 2

    # With --json - the payload owns stdout; human-readable lines move to
    # stderr so the JSON can be piped straight into a parser.
    report = sys.stderr if args.json == "-" else sys.stdout

    if args.lock_graph:
        graph = build_lock_graph(
            [m for m in modules
             if any(r.id == "LOCK001" and r.applies_to(m.path) for r in rules)
             or not any(r.id == "LOCK001" for r in rules)]
        )
        payload = graph.to_json()
        os.makedirs(os.path.dirname(os.path.abspath(args.lock_graph)),
                    exist_ok=True)
        with open(args.lock_graph, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"fedlint: lock graph ({len(payload['nodes'])} locks, "
              f"{len(payload['edges'])} edges, {len(payload['cycles'])} "
              f"cycles) -> {args.lock_graph}", file=report)

    findings = engine.lint_modules(modules, abs_paths=abs_paths)

    if args.write_baseline:
        payload = make_baseline(findings)
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"fedlint: baselined {len(findings)} findings "
              f"({len(payload['entries'])} fingerprints) -> "
              f"{args.write_baseline}")
        return 0

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        candidate = os.path.join(root, DEFAULT_BASELINE)
        baseline_path = candidate if os.path.exists(candidate) else None
    if baseline_path and not args.no_baseline:
        try:
            findings = apply_baseline(findings, load_baseline(baseline_path))
        except (OSError, ValueError) as e:
            print(f"fedlint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    if args.json:
        payload = {"version": 1, "findings": [f.to_json() for f in findings]}
        if args.json == "-":
            json.dump(payload, sys.stdout, indent=2, sort_keys=True)
            print()
        else:
            with open(args.json, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=2, sort_keys=True)
                f.write("\n")

    for f in findings:
        print(f, file=report)
    n_err = sum(1 for f in findings if f.severity >= Severity.ERROR)
    if findings:
        print(f"fedlint: {len(findings)} finding(s) ({n_err} error(s))",
              file=report)
        return 1
    print("fedlint: clean", file=report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
