from fedcrack_tpu.tools.h5_export import export_resunet_h5  # noqa: F401
from fedcrack_tpu.tools.h5_import import import_resunet_h5  # noqa: F401
from fedcrack_tpu.tools.quantify import CrackStats, quantify_mask  # noqa: F401
