from fedcrack_tpu.tools.quantify import CrackStats, quantify_mask  # noqa: F401
