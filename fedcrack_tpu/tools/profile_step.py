"""Profiled step decomposition of the one-program mesh round at one shape.

Round 3 profiled the 128 px flagship (BASELINE.md: ~63% conv time at ~20%
MXU occupancy — the width-bound-ceiling evidence); the 256 px north-star
shape had no profile at all (round-4 verdict, weak #3). This tool makes
shape profiles reproducible artifacts instead of one-off session lore:

- builds the production round program (``parallel.build_federated_round``)
  at ``--img``/``--dtype``, stages one round of data, warms twice
  (compile + committed-signature), then records ``--rounds`` chained
  rounds under ``jax.profiler.trace``;
- converts the captured ``.xplane.pb`` with xprof's ``hlo_stats`` tool and
  aggregates device self-time by HLO category (convolution, fusion,
  reduce, copy, ...), keeping the top ops with their flop rates and
  ``bound_by`` verdicts;
- cross-checks the profile against the measured wall: total profiled
  device self-time vs rounds x measured round wall-clock.

Run on the TPU (the 256 px north-star profile):
    python -m fedcrack_tpu.tools.profile_step --img 256 \
        --out bench_runs/r05_profile_256.json

CPU smoke (tiny shape; exercises trace + conversion wiring):
    python -m fedcrack_tpu.tools.profile_step --img 32 --steps 2 --batch 2 \
        --rounds 1 --out /tmp/profile.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import tempfile
import time

import jax
import numpy as np


def _aggregate_hlo_stats(xplane_paths: list[str], top_n: int) -> dict | None:
    """xprof hlo_stats -> {by_category, top_ops, total_self_time_us}.

    Returns None when xprof (an optional profiling dependency) is absent —
    the artifact then still carries the raw trace path + wall timings.
    """
    try:
        from xprof.convert import raw_to_tool_data
    except Exception:
        return None

    data, _ = raw_to_tool_data.xspace_to_tool_data(xplane_paths, "hlo_stats", {})
    table = json.loads(data)
    if not table.get("rows"):
        # CPU-backend traces carry no per-HLO device events (observed: the
        # jax profiler only populates the HLO plane on accelerator
        # backends); the artifact then records the raw trace path only.
        return None
    idx = {c["id"]: i for i, c in enumerate(table["cols"])}

    def val(row, col):
        cell = row["c"][idx[col]]
        return None if cell is None else cell.get("v")

    by_cat: dict[str, dict] = {}
    ops = []
    total_us = 0.0
    for row in table["rows"]:
        cat = str(val(row, "category") or "unknown")
        self_us = float(val(row, "total_self_time") or 0.0)
        total_us += self_us
        agg = by_cat.setdefault(cat, {"self_time_us": 0.0, "occurrences": 0})
        agg["self_time_us"] += self_us
        agg["occurrences"] += int(val(row, "occurrences") or 0)
        ops.append(
            {
                "hlo_op": str(val(row, "hlo_op_name") or "")[:120],
                "category": cat,
                "self_time_us": round(self_us, 1),
                "occurrences": int(val(row, "occurrences") or 0),
                "self_time_percent": float(val(row, "total_self_time_percent") or 0.0),
                "bound_by": val(row, "bound_by"),
                "model_gflop_per_s": val(row, "model_flop_rate"),
                "measured_memory_bw_gib_s": val(row, "measured_memory_bw"),
            }
        )
    ops.sort(key=lambda o: -o["self_time_us"])
    for cat in by_cat.values():
        cat["fraction"] = round(cat["self_time_us"] / total_us, 4) if total_us else None
        cat["self_time_us"] = round(cat["self_time_us"], 1)
    return {
        "total_self_time_us": round(total_us, 1),
        "by_category": dict(
            sorted(by_cat.items(), key=lambda kv: -kv[1]["self_time_us"])
        ),
        "top_ops": ops[:top_n],
    }


def run_profile(args) -> dict:
    from fedcrack_tpu.configs import ModelConfig
    from fedcrack_tpu.data.synthetic import synth_crack_batch
    from fedcrack_tpu.obs.flops import mfu, train_step_flops
    from fedcrack_tpu.parallel import (
        build_federated_round,
        make_mesh,
        stack_client_data,
        stage_round_data,
    )
    from fedcrack_tpu.train.local import create_train_state

    config = ModelConfig(img_size=args.img, compute_dtype=args.dtype)
    mesh = make_mesh(1, 1)
    device = jax.devices()[0]
    round_fn = build_federated_round(mesh, config, learning_rate=1e-3, local_epochs=1)
    state0 = create_train_state(jax.random.key(args.seed), config)

    imgs, msks = synth_crack_batch(args.steps * args.batch, args.img, seed=args.seed)
    images, masks = stack_client_data([(imgs, msks)], args.steps, args.batch)
    si, sm = stage_round_data(images, masks, mesh)
    active = np.ones(1, np.float32)
    n_samp = np.full(1, float(args.steps * args.batch), np.float32)

    state = {"v": state0.variables}

    def run():
        new_vars, metrics = round_fn(state["v"], si, sm, active, n_samp)
        state["v"] = new_vars
        float(np.asarray(metrics["loss"])[0])

    run()  # compile (host-pytree signature)
    run()  # committed-device-input signature the profiled rounds use

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="fedcrack_profile_")
    walls = []
    with jax.profiler.trace(trace_dir):
        for _ in range(args.rounds):
            t0 = time.perf_counter()
            run()
            walls.append(time.perf_counter() - t0)

    xplanes = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    )
    stats = _aggregate_hlo_stats(xplanes, args.top) if xplanes else None

    flops = train_step_flops(config, args.batch)
    wall_s = float(np.median(walls))
    step_s = wall_s / args.steps
    util = mfu(step_s, flops, device)
    out = {
        "generated_by": "fedcrack_tpu.tools.profile_step",
        "hardware": {
            "platform": device.platform,
            "device_kind": getattr(device, "device_kind", "unknown"),
        },
        "workload": {
            "img_size": args.img,
            "dtype": args.dtype,
            "steps": args.steps,
            "batch": args.batch,
            "profiled_rounds": args.rounds,
        },
        "measured": {
            "round_wall_s_median": round(wall_s, 4),
            "naive_per_step_ms": round(step_s * 1e3, 3),
            "flops_per_step": flops,
            "naive_mfu": None if util is None else round(util, 4),
            "note": (
                "naive division (includes one dispatch); cross-check against "
                "the slope-fit sweep in the BENCH artifact"
            ),
        },
        "trace_dir": trace_dir,
        "xplane_files": xplanes,
        "hlo_stats": stats,
    }
    if stats is not None and stats["total_self_time_us"] > 0:
        # Device self-time per profiled round vs measured wall: >1x gaps are
        # dispatch/tunnel; the per-category fractions are of device time.
        out["measured"]["profiled_device_s_per_round"] = round(
            stats["total_self_time_us"] / 1e6 / args.rounds, 4
        )
    return out


def main(argv=None) -> int:
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--img", type=int, default=256)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--trace-dir", default="")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    artifact = run_profile(args)
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
    if artifact["hlo_stats"] is not None:
        cats = {
            k: v["fraction"] for k, v in artifact["hlo_stats"]["by_category"].items()
        }
        print(json.dumps({"by_category_fraction": cats}))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
